"""Filer core: a POSIX-ish namespace over a FilerStore, with file
content chunked across the blob store.

Reference: weed/filer/filer.go (CreateEntry :217 with parent mkdirs),
filer_deletion.go (async chunk GC), filer_rename.go (2-phase move),
filer_server_handlers_write_upload.go:32 (chunked upload path).
"""

from __future__ import annotations

import hashlib
import os
import queue
import threading
import time
from typing import Iterator, Optional

from ..client.operations import Operations
from ..filer.chunks import read_chunk_views, total_size
from ..pb import filer_pb2 as fpb
from ..utils import trace
from .entry import Entry, new_entry, normalize_path, split_path
from .filer_store import FilerStore, NotFound

DEFAULT_CHUNK_SIZE = 4 * 1024 * 1024  # reference filer -maxMB default
INLINE_LIMIT = 512  # small files live in the entry itself (reference
# filer small-content inlining): no volume round-trip to read them


class FilerError(Exception):
    pass


class Filer:
    def __init__(
        self,
        store: FilerStore,
        master: str = "localhost:9333",
        collection: str = "",
        replication: str = "",
        chunk_size: int = DEFAULT_CHUNK_SIZE,
        jwt_key: str = "",
        chunk_cache_bytes: int = 64 * 1024 * 1024,
        entry_cache_bytes: int | None = None,
    ):
        self.store = store
        self.ops = Operations(master, jwt_key=jwt_key)
        from ..utils.chunk_cache import ChunkCache

        # read-path LRU (reference chunk_cache memory tier); fids are
        # immutable so cached bytes can never go stale. The hot tier of
        # the gateway read path: misses are singleflight-collapsed, so
        # N concurrent GETs of one cold (possibly degraded) chunk cost
        # ONE volume fetch/reconstruction (ISSUE 11).
        self.chunk_cache = ChunkCache(chunk_cache_bytes, tier="filer_chunk")
        # Entry-lookup cache (ISSUE 13): path -> serialized Entry proto,
        # so a warm GET's `filer.lookup` stage stops hitting store.find
        # (and the hardlink KV overlay) on every request. Values are
        # PROTO BYTES, decoded per hit — callers mutate their Entry
        # copies freely without corrupting the cache, and a hit is
        # bit-identical to a fresh store read by construction.
        # Singleflight via get_or_load: N concurrent warm misses on one
        # path collapse to ONE store.find. Invalidated by every local
        # mutator and by replicated meta-log events (_entry_cache_drop
        # call sites); hardlinked entries are NEVER admitted — a
        # sibling name's write changes their content without touching
        # this path. 0 disables (pass-through, no collapsing).
        if entry_cache_bytes is None:
            try:
                entry_cache_bytes = int(
                    os.environ.get("SEAWEED_FILER_ENTRY_CACHE_MB", "8")
                ) << 20
            except ValueError:
                entry_cache_bytes = 8 << 20
        self.entry_cache = ChunkCache(entry_cache_bytes, tier="filer_entry")
        self.collection = collection
        self.replication = replication
        self.chunk_size = chunk_size
        # async chunk GC (reference filer_deletion.go)
        self._gc_queue: "queue.Queue[tuple[str, int]]" = queue.Queue()
        self._gc_stop = threading.Event()
        self._gc_thread = threading.Thread(target=self._gc_loop, daemon=True)
        self._gc_thread.start()
        self._listeners: list = []
        # serializes metadata read-modify-write (tagging, xattr-style
        # updates) against entry replacement; reentrant so composite
        # ops (recursive delete, hardlink) can nest the primitives
        self._mutate_lock = threading.RLock()
        # chunk-list size beyond which create_entry manifestizes
        # (reference filechunk_manifest.go ManifestBatch)
        self.manifest_threshold = 1000
        # strictly-increasing event timestamps: subscription resume and
        # LWW merge both break on equal tsNs (watermarks use strict >)
        self._ts_lock = threading.Lock()
        self._last_ts = 0
        # POSIX advisory byte-range locks (filer_grpc_server_posix_lock)
        from .locks import PosixLockManager

        self.lock_manager = PosixLockManager()

    # ------------------------------------------------------------- meta log

    def subscribe(self, fn) -> None:
        """fn(FullEventNotification) on every mutation."""
        self._listeners.append(fn)

    def _notify(
        self,
        directory: str,
        old: Optional[Entry],
        new: Optional[Entry],
        delete_chunks: bool = False,
        ts_ns: int = 0,
        remote: bool = False,
    ) -> None:
        if not self._listeners:
            return
        ev = fpb.FullEventNotification(
            directory=directory, ts_ns=ts_ns or self._next_ts()
        )
        if old is not None:
            ev.event.old_entry.CopyFrom(old.to_proto())
        if new is not None:
            ev.event.new_entry.CopyFrom(new.to_proto())
        ev.event.delete_chunks = delete_chunks
        ev.event.is_from_other_cluster = remote
        for fn in list(self._listeners):
            try:
                fn(ev)
            except Exception:
                pass

    def _next_ts(self) -> int:
        with self._ts_lock:
            self._last_ts = max(self._last_ts + 1, time.time_ns())
            return self._last_ts

    def _stamp(self, entry: Entry) -> int:
        """Nanosecond metadata timestamp persisted on the entry: the
        multi-filer aggregator's last-writer-wins comparisons need finer
        resolution than attr.mtime's seconds (meta_aggregator.py).
        Strictly increasing per filer so no two events share a tsNs."""
        ts = self._next_ts()
        entry.extended["sw-mts"] = str(ts).encode()
        return ts

    @staticmethod
    def meta_ts(entry: Optional[Entry]) -> int:
        if entry is None:
            return 0
        raw = entry.extended.get("sw-mts")
        if raw:
            try:
                return int(raw)
            except ValueError:
                pass
        return entry.attr.mtime * 1_000_000_000

    # ----------------------------------------------------------- namespace

    def create_entry(
        self,
        entry: Entry,
        ensure_parents: bool = True,
        collection: str | None = None,
    ) -> None:
        if ensure_parents:
            self._ensure_parents(entry.directory)
        if len(entry.chunks) > self.manifest_threshold:
            # huge chunk lists become manifest blobs so every metadata
            # read doesn't deserialize thousands of chunks
            from .manifest import maybe_manifestize

            col = self.collection if collection is None else collection
            entry.chunks = maybe_manifestize(
                lambda blob: self.ops.upload(
                    blob, collection=col, replication=self.replication
                ),
                entry.chunks,
                self.manifest_threshold,
            )
        with self._mutate_lock:
            # stamp inside the lock: racing writers must insert in the
            # same order as their LWW timestamps or peers diverge
            ts = self._stamp(entry)
            old = self._try_find(entry.directory, entry.name)
            if old is not None and old.is_directory != entry.is_directory:
                raise FilerError(
                    f"{entry.full_path}: type conflict with existing entry"
                )
            if old is not None and old.hard_link_id and not entry.hard_link_id:
                # a content commit through an open handle doesn't know
                # about the link identity: inherit it, or the write
                # would silently sever this name from its siblings
                entry.hard_link_id = old.hard_link_id
                entry.hard_link_counter = old.hard_link_counter
            self.store.insert(entry)
            self._hl_publish(entry)
            self._entry_cache_drop(entry.directory, entry.name)
        self._notify(entry.directory, old, entry, ts_ns=ts)

    def mutate_entry(self, full_path: str, fn) -> Entry:
        """Read-modify-write an entry's metadata atomically w.r.t. other
        metadata mutations, and notify subscribers. `fn(entry)` mutates
        in place. A stale pre-read entry must never be written back —
        that would revert a concurrent content overwrite."""
        directory, name = split_path(full_path)
        with self._mutate_lock:
            # overlay FIRST: for a hardlinked name the per-name record
            # can hold a stale content snapshot (a sibling may have
            # written since); republishing it via _hl_publish would
            # revert the sibling's write across every name
            entry = self._hl_overlay(self.store.find(directory, name))
            old = Entry(
                directory=entry.directory,
                name=entry.name,
                is_directory=entry.is_directory,
                chunks=list(entry.chunks),
                content=entry.content,
            )
            old.attr.CopyFrom(entry.attr)
            old.extended = dict(entry.extended)
            fn(entry)
            ts = self._stamp(entry)
            self.store.update(entry)
            self._hl_publish(entry)
            self._entry_cache_drop(directory, name)
        self._notify(directory, old, entry, ts_ns=ts)
        return entry

    def _ensure_parents(self, directory: str) -> None:
        directory = normalize_path(directory)
        if directory == "/":
            return
        parts = directory.strip("/").split("/")
        path = ""
        for part in parts:
            parent = path or "/"
            path = f"{path}/{part}"
            existing = self._try_find(parent, part)
            if existing is None:
                made = new_entry(path, is_directory=True, mode=0o755)
                self.store.insert(made)
                self._entry_cache_drop(parent, part)
                self._notify(parent, None, made)
            elif not existing.is_directory:
                raise FilerError(f"{path} exists and is not a directory")

    def _try_find(self, directory: str, name: str) -> Optional[Entry]:
        try:
            return self.store.find(directory, name)
        except NotFound:
            return None

    # --------------------------------------------------- path-based rules

    def path_conf(self, full_path: str) -> dict:
        """Longest-prefix storage rule for a path (reference
        fs.configure / filer_conf.go): {collection, replication,
        ttl_sec} chosen by location_prefix."""
        raw = self.store.kv_get(b"fs.configure")
        if not raw:
            return {}
        try:
            rules = (__import__("json").loads(raw)).get("locations", [])
        except ValueError:
            return {}
        best: dict = {}
        best_len = -1
        for r in rules:
            p = r.get("location_prefix", "")
            if p and full_path.startswith(p) and len(p) > best_len:
                best, best_len = r, len(p)
        return best

    def _gc_overwritten(self, old: Optional[Entry]) -> None:
        """Release the entry an overwrite replaced. For a hardlinked
        name the NAME survives in its link group (create_entry
        inherited the id and republished hlmeta), so the shared counter
        must not move — only the superseded shared chunks (resolved by
        the caller's pre-republish overlay) are freed."""
        if old is None:
            return
        if old.hard_link_id:
            if old.chunks:
                self.gc_chunks(old.chunks)
            return
        self._release_entry_chunks(old)

    def _hl_publish(self, entry: Entry) -> None:
        """Hardlinked names share ONE content/attr record — the inode
        (reference filer_hardlink.go stores it once, keyed by the link
        id). Every commit through ANY name republishes the shared
        record so all the other names observe the write."""
        if entry.hard_link_id:
            self.store.kv_put(
                b"hlmeta:" + entry.hard_link_id, entry.to_bytes()
            )

    def _hl_overlay(self, entry: Entry) -> Entry:
        """Resolve a hardlinked name against the shared inode record:
        chunks/content/attrs come from hlmeta; only directory+name are
        the entry's own."""
        if not entry.hard_link_id:
            return entry
        raw = self.store.kv_get(b"hlmeta:" + entry.hard_link_id)
        if raw is None:
            return entry
        shadow = Entry.from_bytes(entry.directory, raw)
        entry.chunks = list(shadow.chunks)
        entry.content = shadow.content
        entry.attr.CopyFrom(shadow.attr)
        return entry

    def find_entry(self, full_path: str) -> Entry:
        directory, name = split_path(full_path)
        if name == "":
            root = Entry(directory="/", name="", is_directory=True)
            root.attr.file_mode = 0o755
            return root
        # gateway read-path stage: where a slow GET's metadata-lookup
        # time shows up (ambient span = the server's HTTP root span)
        with trace.stage(trace.current(), "filer.lookup"):
            entry = self._lookup_cached(directory, name)
        if self._is_expired(entry):
            # read-triggered expiry (reference filer TTL): the name
            # vanishes and its chunks are reclaimed asynchronously.
            # Expiry is evaluated on EVERY return (hits included), so a
            # cached entry can never outlive its TTL.
            self.delete_entry(entry.full_path)
            raise NotFound(entry.full_path)
        return entry

    @staticmethod
    def _entry_key(directory: str, name: str) -> str:
        return f"{directory}\x00{name}"

    def _lookup_cached(self, directory: str, name: str) -> Entry:
        """store.find + hardlink overlay through the entry cache.
        Misses singleflight-collapse; a NotFound raised by the loader
        propagates to every collapsed waiter and caches nothing."""
        cache = self.entry_cache
        if cache.capacity <= 0:
            return self._hl_overlay(self.store.find(directory, name))
        hardlinked = [False]

        def load() -> bytes:
            e = self._hl_overlay(self.store.find(directory, name))
            hardlinked[0] = bool(e.hard_link_id)
            return e.to_bytes()

        raw, _src = cache.get_or_load(
            self._entry_key(directory, name),
            load,
            # never admit hardlinked entries (sibling writes mutate
            # them without touching this path), nor entries big enough
            # to flush the hot set (huge inlined content/chunk lists)
            admit=lambda b: not hardlinked[0]
            and len(b) <= cache.capacity // 8,
        )
        return Entry.from_bytes(directory, raw)

    def _entry_cache_drop(self, directory: str, name: str) -> None:
        """Invalidate one path's cached entry. Called by every mutator
        (local writes, renames, deletes, hardlinks, replicated meta-log
        events); an in-flight load for the path is fenced by the cache
        (doomed, never admitted), so a lookup racing the write cannot
        repopulate the stale entry."""
        if name:
            self.entry_cache.drop(
                self._entry_key(normalize_path(directory), name)
            )

    @staticmethod
    def _is_expired(entry: Entry) -> bool:
        ttl = entry.attr.ttl_sec
        return (
            ttl > 0
            and not entry.is_directory
            and entry.attr.crtime + ttl <= int(time.time())
        )

    def exists(self, full_path: str) -> bool:
        try:
            self.find_entry(full_path)
            return True
        except NotFound:
            return False

    def list_entries(
        self, directory: str, start_from: str = "", limit: int = 1024,
        prefix: str = "",
    ) -> Iterator[Entry]:
        """Yields up to `limit` LIVE entries: expired ones are reaped
        and replaced by refetching past them, so a page of expired
        names can never mask live entries behind it."""
        directory = normalize_path(directory)
        yielded = 0
        cursor = start_from
        while yielded < limit:
            batch = list(self.store.list(directory, cursor, limit, prefix))
            if not batch:
                return
            for e in batch:
                if self._is_expired(e):
                    self.delete_entry(e.full_path)
                    continue
                yield self._hl_overlay(e)
                yielded += 1
                if yielded >= limit:
                    return
            if len(batch) < limit:
                return  # store exhausted
            cursor = batch[-1].name

    def delete_entry(
        self, full_path: str, recursive: bool = False, gc_chunks: bool = True
    ) -> None:
        # the whole find→delete→release sequence runs under the
        # (reentrant) mutate lock: two racing deletes of one hardlinked
        # name must not double-decrement the shared counter
        with self._mutate_lock:
            directory, name = split_path(full_path)
            entry = self._try_find(directory, name)
            if entry is None:
                return
            if entry.is_directory:
                children = list(self.store.list(entry.full_path, limit=2))
                if children and not recursive:
                    raise FilerError(f"{full_path} not empty")
                for child in self.store.list(
                    entry.full_path, limit=1_000_000
                ):
                    self.delete_entry(
                        child.full_path, recursive=True, gc_chunks=gc_chunks
                    )
                self.store.delete_folder_children(entry.full_path)
            self.store.delete(directory, name)
            self._entry_cache_drop(directory, name)
            if gc_chunks:
                self._release_entry_chunks(entry)
        self._notify(directory, entry, None, delete_chunks=gc_chunks)

    def _release_entry_chunks(self, entry: Entry) -> None:
        """GC an entry's chunks — unless other hardlink names still
        reference them (reference filer_hardlink.go: counter in KV,
        data reclaimed only with the last name). The counter is
        maintained even for chunk-less (inlined/remote) entries so hl:
        rows never leak."""
        if entry.hard_link_id:
            key = b"hl:" + entry.hard_link_id
            with self._mutate_lock:
                n = int(self.store.kv_get(key) or b"1") - 1
                if n > 0:
                    self.store.kv_put(key, str(n).encode())
                    return
                self.store.kv_delete(key)
                # last name gone: the SHARED record is authoritative
                # for which chunks the inode holds (a write through a
                # sibling may have replaced this entry's snapshot)
                entry = self._hl_overlay(entry)
                self.store.kv_delete(b"hlmeta:" + entry.hard_link_id)
        if entry.chunks:
            self.gc_chunks(entry.chunks)

    def hard_link(self, src_path: str, dst_path: str) -> Entry:
        """Create another name for src's content (filer_hardlink.go).
        Both names share one chunk list; deleting either decrements the
        shared KV counter and the chunks outlive all but the last."""
        src_dir, src_name = split_path(src_path)
        dst_dir, dst_name = split_path(dst_path)
        notify: list = []
        with self._mutate_lock:
            src = self.store.find(src_dir, src_name)
            if src.is_directory:
                raise FilerError("cannot hardlink a directory")
            if self._try_find(dst_dir, dst_name) is not None:
                raise FilerError(f"{dst_path} exists")
            # anything that can fail happens BEFORE the counter bump —
            # a bumped counter with no inserted name would leak the
            # chunks forever
            self._ensure_parents(dst_dir)
            if not src.hard_link_id:
                import os as _os

                old_src = Entry(
                    directory=src.directory,
                    name=src.name,
                    chunks=list(src.chunks),
                    content=src.content,
                )
                old_src.attr.CopyFrom(src.attr)
                old_src.extended = dict(src.extended)
                src.hard_link_id = _os.urandom(16)
                src.hard_link_counter = 1
                self.store.kv_put(b"hl:" + src.hard_link_id, b"1")
                ts_src = self._stamp(src)
                self.store.update(src)
                self._hl_publish(src)  # the shared inode record
                # src just BECAME hardlinked: its cached (cacheable,
                # pre-link) entry is now stale and must not be served
                self._entry_cache_drop(src_dir, src_name)
                # peers must learn src's hardlink marker or their
                # delete path would GC the shared chunks
                notify.append((src_dir, old_src, src, ts_src))
            key = b"hl:" + src.hard_link_id
            n = int(self.store.kv_get(key) or b"1") + 1
            self.store.kv_put(key, str(n).encode())
            dst = Entry(
                directory=dst_dir,
                name=dst_name,
                chunks=list(src.chunks),
                content=src.content,
                hard_link_id=src.hard_link_id,
                hard_link_counter=n,
            )
            dst.attr.CopyFrom(src.attr)
            # extended attrs travel with the link: remote-mount markers
            # (sw-remote) and user xattrs must survive, or the new name
            # reads as empty ("sw-mts" is re-stamped below)
            dst.extended = dict(src.extended)
            ts_dst = self._stamp(dst)
            try:
                self.store.insert(dst)
            except BaseException:
                self.store.kv_put(key, str(n - 1).encode())
                raise
            self._entry_cache_drop(dst_dir, dst_name)
            notify.append((dst_dir, None, dst, ts_dst))
        for d, old, new, ts in notify:
            self._notify(d, old, new, ts_ns=ts)
        return dst

    def rename(self, old_path: str, new_path: str) -> None:
        """2-phase move (reference filer_rename.go): insert at the new
        location, then remove the old key. Chunks move by reference.
        An existing destination file is overwritten (chunks GC'd); a
        destination directory is never clobbered."""
        if normalize_path(old_path) == normalize_path(new_path):
            # inserting-then-deleting the same key would destroy the entry
            raise FilerError(f"rename source and destination are the same: {old_path}")
        old_dir, old_name = split_path(old_path)
        entry = self.store.find(old_dir, old_name)
        dest = self._try_find(*split_path(new_path))
        if dest is not None:
            if dest.is_directory:
                raise FilerError(f"{new_path} exists and is a directory")
            if entry.is_directory:
                raise FilerError(f"cannot rename directory over file {new_path}")
            self._release_entry_chunks(dest)
        if entry.is_directory:
            # move the whole subtree
            for child in list(self.store.list(entry.full_path, limit=1_000_000)):
                self.rename(
                    child.full_path, f"{normalize_path(new_path)}/{child.name}"
                )
        new_dir, new_name = split_path(new_path)
        self._ensure_parents(new_dir)
        moved = Entry(
            directory=new_dir,
            name=new_name,
            is_directory=entry.is_directory,
            chunks=entry.chunks,
            content=entry.content,
            hard_link_id=entry.hard_link_id,
            hard_link_counter=entry.hard_link_counter,
        )
        moved.attr.CopyFrom(entry.attr)
        moved.extended = entry.extended
        # two distinct timestamps: a subscriber resuming between the
        # delete and the create (strict > watermark) must not lose the
        # create half of the rename
        ts_del = self._next_ts()
        ts_cre = self._stamp(moved)
        self.store.insert(moved)
        self.store.delete(old_dir, old_name)
        self._entry_cache_drop(old_dir, old_name)
        self._entry_cache_drop(new_dir, new_name)
        self._notify(old_dir, entry, None, ts_ns=ts_del)
        self._notify(new_dir, None, moved, ts_ns=ts_cre)

    # ----------------------------------------------------------- multi-filer

    def apply_remote_event(self, ev: fpb.FullEventNotification) -> bool:
        """Apply a peer filer's metadata event to the local store
        (MetaAggregator entry point; reference meta_aggregator.go).

        Last-writer-wins: an event older than the local entry's
        nanosecond meta timestamp is dropped, so two filers replaying
        each other's logs converge on the newest write. Chunk GC is the
        originating filer's job — a remote delete never touches blobs.
        Returns True if the event mutated the local store."""
        directory = ev.directory
        new_p, old_p = ev.event.new_entry, ev.event.old_entry
        has_new, has_old = bool(new_p.name), bool(old_p.name)
        if has_new:
            self._ensure_parents(directory)
        with self._mutate_lock:
            if has_new:
                entry = Entry.from_proto(directory, new_p)
                local = self._try_find(directory, entry.name)
                if local is not None and self.meta_ts(local) >= ev.ts_ns:
                    return False
                if local is not None and local.is_directory != entry.is_directory:
                    return False  # type conflict: keep local
                self.store.insert(entry)
                if entry.hard_link_id:
                    # replicated hardlink writes must refresh the local
                    # shared-inode record too, or the overlay would keep
                    # serving this peer's stale content over the newer
                    # replicated chunks
                    self._hl_publish(entry)
                self._entry_cache_drop(directory, entry.name)
                applied_old, applied_new = local, entry
            elif has_old:
                local = self._try_find(directory, old_p.name)
                if local is None or self.meta_ts(local) > ev.ts_ns:
                    return False
                if local.is_directory:
                    # remote recursive deletes arrive child-first; an
                    # already-emptied dir deletes cleanly, a non-empty
                    # one means local writes raced — keep it
                    if list(self.store.list(local.full_path, limit=1)):
                        return False
                self.store.delete(directory, old_p.name)
                self._entry_cache_drop(directory, old_p.name)
                applied_old, applied_new = local, None
            else:
                return False
        # re-log with a LOCAL timestamp: the meta log must stay
        # monotonic (watermark resume + sealed-segment naming depend on
        # it); the origin's LWW timestamp still rides the entry's
        # sw-mts extended attr
        self._notify(directory, applied_old, applied_new, remote=True)
        return True

    # -------------------------------------------------------------- content

    def write_file(
        self,
        full_path: str,
        data: bytes,
        mime: str = "",
        mode: int = 0o644,
        collection: str | None = None,
        inline: bool = True,
        extended: dict | None = None,
        ttl_sec: int = 0,
    ) -> Entry:
        """inline=False forces chunked storage even for tiny payloads —
        chunk-splicing consumers (S3 multipart parts) require chunks."""
        """Slice into chunk_size pieces, assign+upload each, create the
        entry (reference uploadRequestToChunks)."""
        full_path = normalize_path(full_path)
        # fs.configure path rules fill in what the caller left default
        rule = self.path_conf(full_path)
        if rule:
            if collection is None and rule.get("collection"):
                collection = rule["collection"]
            if not ttl_sec and rule.get("ttl_sec"):
                ttl_sec = int(rule["ttl_sec"])
        replication = (
            rule.get("replication") or self.replication
            if rule
            else self.replication
        )
        old = self._try_find(*split_path(full_path))
        if old is not None and old.is_directory:
            # fail BEFORE uploading chunks that create_entry would orphan
            raise FilerError(f"{full_path}: type conflict with existing entry")
        if old is not None and old.hard_link_id:
            # resolve the SHARED record now (pre-republish): those are
            # the chunks this overwrite supersedes, not the per-name
            # snapshot (which may be stale after a sibling's write)
            self._hl_overlay(old)
        if inline and len(data) <= INLINE_LIMIT:
            entry = new_entry(full_path, mode=mode, mime=mime)
            entry.attr.ttl_sec = ttl_sec
            if extended:
                entry.extended.update(extended)
            entry.content = data
            entry.attr.file_size = len(data)
            entry.attr.md5 = hashlib.md5(data).digest()
            self.create_entry(entry)
            self._gc_overwritten(old)
            return entry
        chunks = []
        ts = time.time_ns()
        for off in range(0, len(data), self.chunk_size) or [0]:
            piece = data[off : off + self.chunk_size]
            if not piece and off > 0:
                break
            fid = self.ops.upload(
                piece,
                name=full_path.rsplit("/", 1)[-1],
                collection=self.collection if collection is None else collection,
                replication=replication,
            )
            chunks.append(
                fpb.FileChunk(
                    fid=fid,
                    offset=off,
                    size=len(piece),
                    modified_ts_ns=ts,
                    etag=hashlib.md5(piece).hexdigest(),
                )
            )
        entry = new_entry(full_path, mode=mode, mime=mime)
        entry.attr.ttl_sec = ttl_sec
        if extended:
            entry.extended.update(extended)
        entry.chunks = chunks
        entry.attr.file_size = len(data)
        entry.attr.md5 = hashlib.md5(data).digest()
        try:
            self.create_entry(entry)
        except BaseException:
            # a losing race still must not leak the uploaded chunks
            self.gc_chunks(chunks)
            raise
        self._gc_overwritten(old)
        return entry

    def read_file(
        self, full_path: str, offset: int = 0, size: int = -1
    ) -> bytes:
        entry = self.find_entry(full_path)
        return self.read_entry(entry, offset, size)

    def read_entry(self, entry: Entry, offset: int = 0, size: int = -1) -> bytes:
        # Flight-recorder child span for the filer data-plane layer:
        # inside an S3/filer HTTP root span this is where lookup-vs-
        # chunk-fetch time splits; the chunk fetches propagate the trace
        # over HTTP to the volume servers (TracingSession).
        sp = trace.start(
            "filer.read", name=entry.full_path,
            offset=offset, size=size,
        )
        if sp is not None:
            # the filer data-plane layer is its own logical server even
            # when embedded (S3/WebDAV gateways construct a Filer
            # in-process): label it so a trace shows the layer hop
            sp.server = "filer"
        try:
            with trace.activate(sp):
                return self._read_entry_traced(entry, offset, size, sp)
        finally:
            trace.finish(sp)

    def _read_entry_traced(
        self, entry: Entry, offset: int, size: int, sp
    ) -> bytes:
        if entry.is_directory:
            raise FilerError(f"{entry.full_path} is a directory")
        if entry.content:
            end = len(entry.content) if size < 0 else offset + size
            return entry.content[offset:end]
        if not entry.chunks and "sw-remote" in entry.extended:
            # lazy remote mount: stream through from the cloud object
            # (reference read_remote.go); `remote.cache` pins it local
            from ..remote.mount import read_remote

            return read_remote(self, entry, offset=offset, size=size)
        file_size = entry.file_size
        if size < 0:
            size = max(file_size - offset, 0)
        size = min(size, max(file_size - offset, 0))
        if size == 0:
            return b""
        chunks = entry.chunks
        from .manifest import has_manifests, resolve_manifests

        if has_manifests(chunks):
            chunks = resolve_manifests(self._read_chunk_cached, chunks)
        buf = bytearray(size)
        for view in read_chunk_views(chunks, offset, size):
            chunk_data, src = self.chunk_cache.get_or_load(
                view.fid,
                lambda fid=view.fid: self._fetch_chunk_traced(fid, sp),
                # admit only modest chunks: one large streaming read
                # must not flush the whole hot set out of the LRU
                admit=lambda d: len(d) <= self.chunk_cache.capacity // 8,
            )
            if src != "load" and sp is not None:
                sp.event(
                    "chunk_cache_hit" if src == "hit"
                    else "chunk_singleflight_wait",
                    fid=view.fid,
                )
            piece = chunk_data[view.offset_in_chunk : view.offset_in_chunk + view.size]
            lo = view.logical_offset - offset
            buf[lo : lo + len(piece)] = piece
        return bytes(buf)

    def _fetch_chunk_traced(self, fid: str, sp) -> bytes:
        with trace.stage(sp, "chunk.fetch"):
            return self.ops.read(fid)

    def _read_chunk_cached(self, fid: str) -> bytes:
        data, _src = self.chunk_cache.get_or_load(
            fid,
            lambda: self._fetch_chunk_traced(fid, trace.current()),
            admit=lambda d: len(d) <= self.chunk_cache.capacity // 8,
        )
        return data

    def resolve_chunks(self, entry: Entry):
        """Entry's chunk list with manifest chunks expanded (callers
        that stream views themselves: mount, webdav)."""
        from .manifest import has_manifests, resolve_manifests

        if has_manifests(entry.chunks):
            return resolve_manifests(self._read_chunk_cached, entry.chunks)
        return entry.chunks

    # ------------------------------------------------------------------ gc

    def gc_chunks(self, chunks) -> None:
        """Enqueue chunk fids for async deletion on the volume servers.
        Manifest chunks expand to their referenced chunks plus the
        manifest blob itself."""
        from .manifest import gc_expand, has_manifests

        if has_manifests(chunks):
            chunks = gc_expand(self.ops.read, chunks)
        for c in chunks:
            self.chunk_cache.drop(c.fid)  # dead bytes must not pin the LRU
            self._gc_queue.put((c.fid, 0))

    _GC_MAX_ATTEMPTS = 5

    def _gc_loop(self) -> None:
        while not self._gc_stop.is_set():
            try:
                fid, attempts = self._gc_queue.get(timeout=0.5)
            except queue.Empty:
                continue
            try:
                self.ops.delete(fid)
            except Exception:
                # transient outage must not leak blobs: requeue with
                # backoff (reference filer_deletion.go retries)
                if attempts + 1 < self._GC_MAX_ATTEMPTS:
                    t = threading.Timer(
                        2.0 * (attempts + 1),
                        self._gc_queue.put,
                        args=((fid, attempts + 1),),
                    )
                    t.daemon = True
                    t.start()

    def flush_gc(self, timeout: float = 10.0) -> None:
        deadline = time.time() + timeout
        while not self._gc_queue.empty() and time.time() < deadline:
            time.sleep(0.05)

    def close(self) -> None:
        self._gc_stop.set()
        self._gc_thread.join(timeout=2)
        self.ops.close()
        self.store.close()
