"""gRPC filer metadata service + streaming metadata subscription.

Reference: weed/pb/filer.proto service SeaweedFiler (Lookup/List/
Create/Update/Delete/AtomicRename, SubscribeMetadata at
weed/server/filer_grpc_server_sub_meta.go). The mount layer, peer
filers (MetaAggregator) and filer.sync all ride this surface; the HTTP
file API stays the byte data plane.
"""

from __future__ import annotations

import grpc

from ..pb import filer_pb2 as fpb
from .entry import Entry, normalize_path
from .filer import Filer, FilerError
from .filer_store import NotFound
from .notification import json_to_event


class FilerGrpcService:
    """Servicer for rpc.FILER_SERVICE (hand-rolled table wiring)."""

    def __init__(self, filer: Filer, meta_log=None, lock_ring=None):
        self.filer = filer
        self.meta_log = meta_log
        # distributed lock ring (filer/lock_ring.py); a ring with no
        # peers serves single-filer deployments locally
        self.lock_ring = lock_ring

    def DistributedLock(self, request, context):
        if self.lock_ring is None:
            return fpb.DlmResponse(error="lock ring not configured")
        return self.lock_ring.handle(request)

    # ------------------------------------------------------------ metadata

    def LookupDirectoryEntry(self, request, context):
        try:
            e = self.filer.store.find(
                normalize_path(request.directory), request.name
            )
        except NotFound:
            return fpb.LookupEntryResponse(error="not found")
        e = self.filer._hl_overlay(e)  # shared-inode content/attrs
        proto = e.to_proto()
        if e.hard_link_id:
            # the per-entry counter is a snapshot from link time; the
            # LIVE name count lives in the shared hl: KV row (mounts
            # report it as st_nlink)
            n = self.filer.store.kv_get(b"hl:" + e.hard_link_id)
            if n is not None:
                proto.hard_link_counter = int(n)
        return fpb.LookupEntryResponse(entry=proto)

    def ListEntries(self, request, context):
        limit = request.limit or 1024
        for e in self.filer.list_entries(
            request.directory,
            start_from=request.start_from,
            limit=limit,
            prefix=request.prefix,
        ):
            yield fpb.ListEntriesResponse(entry=e.to_proto())

    def CreateEntry(self, request, context):
        try:
            entry = Entry.from_proto(
                normalize_path(request.directory), request.entry
            )
            self.filer.create_entry(entry)
        except FilerError as e:
            return fpb.FilerOpResponse(error=str(e))
        return fpb.FilerOpResponse()

    def UpdateEntry(self, request, context):
        directory = normalize_path(request.directory)
        try:
            self.filer.store.find(directory, request.entry.name)
        except NotFound:
            return fpb.FilerOpResponse(error="not found")
        try:
            entry = Entry.from_proto(directory, request.entry)
            self.filer.create_entry(entry, ensure_parents=False)
        except FilerError as e:
            return fpb.FilerOpResponse(error=str(e))
        return fpb.FilerOpResponse()

    def DeleteEntry(self, request, context):
        path = f"{normalize_path(request.directory)}/{request.name}"
        try:
            self.filer.delete_entry(
                path,
                recursive=request.is_recursive,
                gc_chunks=request.is_delete_data,
            )
        except FilerError as e:
            return fpb.FilerOpResponse(error=str(e))
        return fpb.FilerOpResponse()

    def AtomicRenameEntry(self, request, context):
        try:
            self.filer.rename(
                f"{normalize_path(request.old_directory)}/{request.old_name}",
                f"{normalize_path(request.new_directory)}/{request.new_name}",
            )
        except (FilerError, NotFound) as e:
            return fpb.FilerOpResponse(error=str(e))
        return fpb.FilerOpResponse()

    def AssignVolume(self, request, context):
        """Proxy an assign to the filer's master so mounts can place
        chunks without a master address (reference filer_pb
        AssignVolume; used by the mount page writer)."""
        try:
            a = self.filer.ops.master.assign(
                count=request.count or 1,
                collection=request.collection or self.filer.collection,
                replication=self.filer.replication,
                ttl=request.ttl,
            )
        except Exception as e:  # noqa: BLE001 — surfaced to the client
            return fpb.AssignVolumeResponse(error=str(e))
        return fpb.AssignVolumeResponse(fid=a.fid, url=a.url, jwt=a.jwt)

    def KvGet(self, request, context):
        v = self.filer.store.kv_get(bytes(request.key))
        if v is None:
            return fpb.FilerKvGetResponse(found=False)
        return fpb.FilerKvGetResponse(value=v, found=True)

    def KvPut(self, request, context):
        if request.value:
            self.filer.store.kv_put(bytes(request.key), bytes(request.value))
        else:
            self.filer.store.kv_delete(bytes(request.key))
        return fpb.FilerOpResponse()

    def LookupVolume(self, request, context):
        """Volume-location passthrough (reference filer_grpc_server.go
        LookupVolume): mounts resolve fids to volume-server URLs here
        so chunk reads can go DIRECT (and peer-to-peer) instead of
        proxying every byte through the filer."""
        from ..pb import cluster_pb2 as cpb

        resp = cpb.LookupVolumeResponse()
        for vid in request.volume_ids:
            vl = resp.volume_locations.add()
            vl.volume_id = vid
            try:
                for loc in self.filer.ops.master.lookup(vid):
                    vl.locations.add().CopyFrom(loc)
            except Exception as e:  # noqa: BLE001 — per-vid error
                vl.error = str(e)
        return resp

    def RunLifecycle(self, request, context):
        """Apply stored S3 lifecycle rules here, where the metadata
        lives — the execution half of the worker fleet's s3_lifecycle
        task kind (reference weed/worker/tasks registry)."""
        from ..s3.lifecycle import LifecycleScanner

        try:
            stats = LifecycleScanner(self.filer).run_once(
                bucket=request.bucket
            )
        except Exception as e:  # noqa: BLE001 — surfaced to the worker
            return fpb.LifecycleRunResponse(error=str(e))
        return fpb.LifecycleRunResponse(
            expired=stats.get("expired", 0),
            noncurrent_expired=stats.get("noncurrent_expired", 0),
            aborted_uploads=stats.get("aborted_uploads", 0),
        )

    def HardLink(self, request, context):
        """Create another name for src's content (reference
        filer_hardlink.go); FUSE link() rides this. Error strings are
        prefixed so clients can map them to errno."""
        try:
            self.filer.hard_link(
                normalize_path(request.src_path),
                normalize_path(request.dst_path),
            )
        except NotFound as e:
            return fpb.FilerOpResponse(error=f"not found: {e}")
        except FilerError as e:
            return fpb.FilerOpResponse(error=str(e))
        return fpb.FilerOpResponse()

    def LockRange(self, request, context):
        """POSIX advisory locks (filer_grpc_server_posix_lock.go):
        op 0 = lock, 1 = unlock, 2 = test, 3 = renew lease."""
        lm = self.filer.lock_manager
        lease = float(request.lease_seconds or 0)
        if request.op == 0:
            granted, who = lm.lock(
                request.path,
                request.owner,
                request.start,
                request.end,
                exclusive=request.exclusive,
                lease=lease,
            )
            return fpb.LockRangeResponse(granted=granted, conflict_owner=who)
        if request.op == 1:
            n = lm.unlock(
                request.path, request.owner, request.start, request.end
            )
            return fpb.LockRangeResponse(granted=True, count=n)
        if request.op == 2:
            who = lm.test(
                request.path,
                request.start,
                request.end,
                exclusive=request.exclusive,
                owner=request.owner,
            )
            return fpb.LockRangeResponse(granted=not who, conflict_owner=who)
        if request.op == 3:
            n = lm.renew(request.path, request.owner, lease=lease)
            return fpb.LockRangeResponse(granted=n > 0, count=n)
        return fpb.LockRangeResponse(error=f"bad op {request.op}")

    # --------------------------------------------------------- subscription

    def SubscribeMetadata(self, request, context):
        """Long-lived event stream from the persisted meta log
        (reference filer_grpc_server_sub_meta.go). Replays history from
        since_ns, then follows live appends."""
        if self.meta_log is None:
            context.abort(
                grpc.StatusCode.UNIMPLEMENTED, "filer runs without a meta log"
            )
        watermark = request.since_ns
        if 0 < watermark < self.meta_log.dropped_before_ts:
            # events in (since_ns, dropped_before_ts] were rotated away:
            # continuing silently would present a complete-looking but
            # gapped stream (HTTP tail exposes droppedBeforeTsNs for the
            # same reason)
            context.abort(
                grpc.StatusCode.OUT_OF_RANGE,
                f"resync required: events before "
                f"{self.meta_log.dropped_before_ts} were rotated away",
            )
        prefix = request.path_prefix
        while context.is_active():
            records = self.meta_log.read_since(watermark, limit=1000)
            for rec in records:
                watermark = max(watermark, rec.get("tsNs", 0))
                if request.local_only and rec.get("remote"):
                    continue
                if prefix and not (
                    rec.get("directory", "").startswith(prefix.rstrip("/"))
                    or prefix.rstrip("/").startswith(rec.get("directory", ""))
                ):
                    continue
                ev = json_to_event(rec)
                if ev is None:
                    continue  # legacy record without full payload
                yield ev
            if not records:
                self.meta_log.wait_for_events(watermark, timeout=1.0)


