"""Generic DB-API FilerStore: any driver drops in via a dialect.

Reference: weed/filer/abstract_sql/abstract_sql_store.go — one SQL
shape (directory + name + meta blob, plus a KV table) shared by the
mysql/postgres/sqlite/cockroach backends, each contributing only its
dialect quirks. Here the dialect is a small declarative object:
paramstyle + upsert syntax + table DDL; the store body is written once
against it. ``SqliteStore`` in filer_store.py is the first concrete
instance; anything with a PEP-249 connection factory (psycopg2,
pymysql, mariadb, ...) is a ~10-line subclass away.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Callable, Iterator, Optional

from .entry import Entry
from .filer_store import NotFound


@dataclass
class SqlDialect:
    """Driver quirks. `paramstyle`: qmark (?), format (%s), numbered
    ($1...), or named (:p0...). `upsert`: statement template with
    {table}; must insert-or-replace on the (directory, name) / (k)
    primary key."""

    paramstyle: str = "qmark"
    upsert_meta: str = (
        "INSERT OR REPLACE INTO {table} (directory, name, meta) "
        "VALUES (?,?,?)"
    )
    upsert_kv: str = "INSERT OR REPLACE INTO kv (k, v) VALUES (?,?)"
    insert_ignore_kv: str = "INSERT OR IGNORE INTO kv (k, v) VALUES (?,?)"
    ddl: tuple = (
        "CREATE TABLE IF NOT EXISTS {table} ("
        " directory TEXT NOT NULL,"
        " name TEXT NOT NULL,"
        " meta BLOB,"
        " PRIMARY KEY (directory, name))",
        "CREATE TABLE IF NOT EXISTS kv (k BLOB PRIMARY KEY, v BLOB)",
    )
    # escape char for LIKE is unused: prefix ranges use >= / < bounds
    pragmas: tuple = ()


POSTGRES_DIALECT = SqlDialect(
    # psycopg2 / pg8000 are DB-API 'format' drivers (%s placeholders);
    # $N is the raw PG wire syntax, which PEP-249 drivers do not take
    paramstyle="format",
    upsert_meta=(
        "INSERT INTO {table} (directory, name, meta) VALUES (?,?,?) "
        "ON CONFLICT (directory, name) DO UPDATE SET meta = EXCLUDED.meta"
    ),
    upsert_kv=(
        "INSERT INTO kv (k, v) VALUES (?,?) "
        "ON CONFLICT (k) DO UPDATE SET v = EXCLUDED.v"
    ),
    insert_ignore_kv=(
        "INSERT INTO kv (k, v) VALUES (?,?) ON CONFLICT (k) DO NOTHING"
    ),
    ddl=(
        "CREATE TABLE IF NOT EXISTS {table} ("
        " directory TEXT NOT NULL,"
        " name TEXT NOT NULL,"
        " meta BYTEA,"
        " PRIMARY KEY (directory, name))",
        "CREATE TABLE IF NOT EXISTS kv (k BYTEA PRIMARY KEY, v BYTEA)",
    ),
)

MYSQL_DIALECT = SqlDialect(
    paramstyle="format",
    upsert_meta=(
        "REPLACE INTO {table} (directory, name, meta) VALUES (?,?,?)"
    ),
    upsert_kv="REPLACE INTO kv (k, v) VALUES (?,?)",
    insert_ignore_kv="INSERT IGNORE INTO kv (k, v) VALUES (?,?)",
    ddl=(
        "CREATE TABLE IF NOT EXISTS {table} ("
        " directory VARCHAR(512) NOT NULL,"
        " name VARCHAR(255) NOT NULL,"
        " meta LONGBLOB,"
        " PRIMARY KEY (directory, name))",
        "CREATE TABLE IF NOT EXISTS kv ("
        " k VARBINARY(512) PRIMARY KEY, v LONGBLOB)",
    ),
)


class AbstractSqlStore:
    """FilerStore written once against SqlDialect + a PEP-249
    connection factory. Connections are per-thread (most drivers are
    not thread-safe at the connection level)."""

    HIGH = "\U0010ffff"  # above any valid name character

    def __init__(
        self,
        connect: Callable[[], object],
        dialect: SqlDialect | None = None,
        table: str = "filemeta",
    ):
        self.connect = connect
        self.dialect = dialect or SqlDialect()
        self.table = table
        self._local = threading.local()
        con = self._con()
        cur = con.cursor()
        for stmt in self.dialect.ddl:
            cur.execute(stmt.format(table=self.table))
        for stmt in self.dialect.pragmas:
            cur.execute(stmt)
        con.commit()

    # ------------------------------------------------------- plumbing

    def _con(self):
        con = getattr(self._local, "con", None)
        if con is None:
            con = self.connect()
            self._local.con = con
        return con

    def _sql(self, q: str) -> str:
        """Adapt the canonical qmark text to the driver's paramstyle."""
        style = self.dialect.paramstyle
        if style == "qmark":
            return q
        if style == "format":
            return q.replace("?", "%s")
        if style == "numbered":
            out, i = [], 0
            for ch in q:
                if ch == "?":
                    i += 1
                    out.append(f"${i}")
                else:
                    out.append(ch)
            return "".join(out)
        if style == "named":
            out, i = [], 0
            for ch in q:
                if ch == "?":
                    out.append(f":p{i}")
                    i += 1
                else:
                    out.append(ch)
            return "".join(out)
        raise ValueError(f"unknown paramstyle {style!r}")

    def _params(self, params: tuple):
        if self.dialect.paramstyle == "named":
            return {f"p{i}": v for i, v in enumerate(params)}
        return params

    def _exec(self, con, q: str, params: tuple = ()):
        cur = con.cursor()
        cur.execute(self._sql(q), self._params(params))
        return cur

    # ------------------------------------------------- FilerStore SPI

    def insert(self, entry: Entry) -> None:
        con = self._con()
        self._exec(
            con,
            self.dialect.upsert_meta.format(table=self.table),
            (entry.directory, entry.name, entry.to_bytes()),
        )
        con.commit()

    update = insert

    def find(self, directory: str, name: str) -> Entry:
        row = self._exec(
            self._con(),
            f"SELECT meta FROM {self.table} WHERE directory=? AND name=?",
            (directory, name),
        ).fetchone()
        if row is None:
            raise NotFound(f"{directory}/{name}")
        return Entry.from_bytes(directory, row[0])

    def delete(self, directory: str, name: str) -> None:
        con = self._con()
        self._exec(
            con,
            f"DELETE FROM {self.table} WHERE directory=? AND name=?",
            (directory, name),
        )
        con.commit()

    def delete_folder_children(self, directory: str) -> None:
        con = self._con()
        prefix = directory if directory.endswith("/") else directory + "/"
        self._exec(
            con,
            f"DELETE FROM {self.table} WHERE directory=? "
            "OR (directory>=? AND directory<?)",
            (directory, prefix, prefix + self.HIGH),
        )
        con.commit()

    def list(
        self,
        directory: str,
        start_from: str = "",
        limit: int = 1024,
        prefix: str = "",
    ) -> Iterator[Entry]:
        # prefix as a half-open range: LIKE is case-insensitive for
        # ASCII in some drivers and treats %/_ as wildcards
        q = (
            f"SELECT name, meta FROM {self.table} "
            "WHERE directory=? AND name>?"
        )
        params: list = [directory, start_from]
        if prefix:
            q += " AND name>=? AND name<?"
            params += [prefix, prefix + self.HIGH]
        q += " ORDER BY name LIMIT ?"
        params.append(limit)
        for _name, meta in self._exec(self._con(), q, tuple(params)):
            yield Entry.from_bytes(directory, meta)

    def kv_put(self, key: bytes, value: bytes) -> None:
        con = self._con()
        self._exec(con, self.dialect.upsert_kv, (key, value))
        con.commit()

    def kv_get(self, key: bytes) -> Optional[bytes]:
        row = self._exec(
            self._con(), "SELECT v FROM kv WHERE k=?", (key,)
        ).fetchone()
        return row[0] if row else None

    def kv_delete(self, key: bytes) -> None:
        con = self._con()
        self._exec(con, "DELETE FROM kv WHERE k=?", (key,))
        con.commit()

    def kv_put_if_absent(self, key: bytes, value: bytes) -> bytes:
        con = self._con()
        self._exec(con, self.dialect.insert_ignore_kv, (key, value))
        con.commit()
        row = self._exec(
            con, "SELECT v FROM kv WHERE k=?", (key,)
        ).fetchone()
        return row[0] if row else value

    def close(self) -> None:
        con = getattr(self._local, "con", None)
        if con is not None:
            con.close()
            self._local.con = None
