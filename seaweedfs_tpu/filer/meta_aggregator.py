"""MetaAggregator: converge this filer's namespace with its peers.

Reference: weed/filer/meta_aggregator.go — each filer subscribes to
every peer's metadata stream and merges the events. Here the merge
applies peer events to the local store with last-writer-wins semantics
(Filer.apply_remote_event); applied events are re-logged locally with
is_from_other_cluster=true, and peer subscriptions request
local_only=true, so events propagate exactly one hop in a full mesh —
no echo loops, no relays needed.
"""

from __future__ import annotations

import threading

import grpc

from ..pb import filer_pb2 as fpb
from ..pb import rpc
from ..utils.glog import logger
from .filer import Filer

log = logger("filer.aggregator")


class MetaAggregator:
    def __init__(self, filer: Filer, peers: list[str], client_name: str = ""):
        """peers: list of peer filer gRPC addresses (host:port)."""
        self.filer = filer
        self.peers = [p for p in peers if p]
        self.client_name = client_name or "aggregator"
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        # per-peer replication watermark — reconnects resume, and
        # replayed events below the watermark are skipped
        self._watermark: dict[str, int] = {}
        self.applied = 0

    def start(self) -> None:
        for peer in self.peers:
            t = threading.Thread(
                target=self._follow_peer, args=(peer,), daemon=True
            )
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()

    def _follow_peer(self, peer: str) -> None:
        while not self._stop.is_set():
            try:
                with grpc.insecure_channel(peer) as ch:
                    stub = rpc.filer_stub(ch)
                    stream = stub.SubscribeMetadata(
                        fpb.SubscribeMetadataRequest(
                            client_name=self.client_name,
                            since_ns=self._watermark.get(peer, 0),
                            local_only=True,
                        )
                    )
                    for ev in stream:
                        if self._stop.is_set():
                            return
                        if self.filer.apply_remote_event(ev):
                            self.applied += 1
                        self._watermark[peer] = max(
                            self._watermark.get(peer, 0), ev.ts_ns
                        )
            except grpc.RpcError:
                # peer down or restarting: retry with backoff, resuming
                # from the watermark
                self._stop.wait(1.0)
            except Exception as e:  # noqa: BLE001
                log.warning("peer %s: %s", peer, e)
                self._stop.wait(1.0)
