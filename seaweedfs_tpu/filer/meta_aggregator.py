"""MetaAggregator: converge this filer's namespace with its peers.

Reference: weed/filer/meta_aggregator.go — each filer subscribes to
every peer's metadata stream and merges the events. Here the merge
applies peer events to the local store with last-writer-wins semantics
(Filer.apply_remote_event); applied events are re-logged locally with
is_from_other_cluster=true, and peer subscriptions request
local_only=true, so events propagate exactly one hop in a full mesh —
no echo loops, no relays needed.
"""

from __future__ import annotations

import threading

import grpc

from ..pb import filer_pb2 as fpb
from ..pb import rpc
from ..utils.glog import logger
from .filer import Filer

log = logger("filer.aggregator")


class MetaAggregator:
    def __init__(self, filer: Filer, peers: list[str], client_name: str = ""):
        """peers: list of peer filer gRPC addresses (host:port)."""
        self.filer = filer
        self.peers = [p for p in peers if p]
        self.client_name = client_name or "aggregator"
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        # per-peer replication watermark, persisted in the filer KV so
        # a restart resumes instead of replaying each peer's whole log
        self._watermark: dict[str, int] = {}
        self._saved: dict[str, int] = {}
        for p in self.peers:
            raw = filer.store.kv_get(f"meta-agg/{p}".encode())
            if raw:
                try:
                    self._watermark[p] = int(raw)
                except ValueError:
                    pass
        self.applied = 0

    def _advance(self, peer: str, ts_ns: int) -> None:
        cur = max(self._watermark.get(peer, 0), ts_ns)
        self._watermark[peer] = cur
        # throttled persistence: every second of log time is plenty
        if cur - self._saved.get(peer, 0) > 1_000_000_000:
            self.filer.store.kv_put(
                f"meta-agg/{peer}".encode(), str(cur).encode()
            )
            self._saved[peer] = cur

    def start(self) -> None:
        for peer in self.peers:
            t = threading.Thread(
                target=self._follow_peer, args=(peer,), daemon=True
            )
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        for p, ts in self._watermark.items():
            if ts != self._saved.get(p):
                try:
                    self.filer.store.kv_put(
                        f"meta-agg/{p}".encode(), str(ts).encode()
                    )
                except Exception:  # noqa: BLE001 — store may be closing
                    pass

    def _follow_peer(self, peer: str) -> None:
        while not self._stop.is_set():
            try:
                with grpc.insecure_channel(peer) as ch:
                    stub = rpc.filer_stub(ch)
                    stream = stub.SubscribeMetadata(
                        fpb.SubscribeMetadataRequest(
                            client_name=self.client_name,
                            since_ns=self._watermark.get(peer, 0),
                            local_only=True,
                        )
                    )
                    for ev in stream:
                        if self._stop.is_set():
                            return
                        if self.filer.apply_remote_event(ev):
                            self.applied += 1
                        self._advance(peer, ev.ts_ns)
            except grpc.RpcError as e:
                if e.code() == grpc.StatusCode.OUT_OF_RANGE:
                    # our watermark predates the peer's log retention:
                    # events were rotated away. Replay what remains —
                    # LWW apply makes the replay idempotent; entries
                    # mutated only inside the gap stay divergent until
                    # the next write (full resync is filer.sync's job).
                    log.warning(
                        "peer %s rotated past our watermark %d; replaying",
                        peer,
                        self._watermark.get(peer, 0),
                    )
                    self._watermark[peer] = 0
                # peer down or restarting: retry with backoff, resuming
                # from the watermark
                self._stop.wait(1.0)
            except Exception as e:  # noqa: BLE001
                log.warning("peer %s: %s", peer, e)
                self._stop.wait(1.0)
