"""Filer: POSIX-ish metadata namespace over the blob store (layer 5)."""

from .chunks import ChunkView, read_chunk_views, total_size, visible_intervals
from .entry import Entry, new_entry, normalize_path, split_path
from .filer import DEFAULT_CHUNK_SIZE, Filer, FilerError
from .filer_store import FilerStore, MemoryStore, NotFound, SqliteStore
from .abstract_sql_store import (
    MYSQL_DIALECT,
    POSTGRES_DIALECT,
    AbstractSqlStore,
    SqlDialect,
)
from .sstable_store import SSTableStore
