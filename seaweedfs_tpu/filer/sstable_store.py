"""Embedded ordered-KV filer store: WAL + memtable + immutable SSTables.

The reference ships LevelDB-family embedded stores
(weed/filer/leveldb/leveldb_store.go, leveldb2, leveldb3); this is the
same class of engine built the immutable-segment way: every mutation is
journaled to a CRC'd WAL, absorbed into an in-memory table, and flushed
as a sorted, immutable segment file with a sparse index. Readers merge
memtable + segments newest-first; size-tiered compaction folds segments
together and drops tombstones. No external dependencies.

Keyspace: entries are ``E<dir>\\x00<name>`` so a directory's children
are one contiguous key range (the reference's leveldb store uses the
same dir-prefix trick); KV pairs live under ``K``.
"""

from __future__ import annotations

import bisect
import os
import struct
import threading
from typing import Iterator, Optional

from ..utils.crc import crc32c
from ..utils.fs import fsync_dir
from .entry import Entry
from .filer_store import NotFound

_WAL_HDR = struct.Struct("<II")  # payload_len, crc32c(payload)
_SEG_MAGIC = b"SST1"
_SPARSE_EVERY = 16

_PUT, _DEL = 1, 0


def _entry_key(directory: str, name: str) -> bytes:
    return b"E" + directory.encode() + b"\x00" + name.encode()


def _kv_key(key: bytes) -> bytes:
    return b"K" + key


class _Segment:
    """One immutable sorted segment: records ``[klen u32][key][vlen i32]
    [value]`` (vlen -1 = tombstone), then a sparse index of every Nth
    key, then ``[index_offset u64][count u32][magic]``."""

    def __init__(self, path: str):
        self.path = path
        self._f = open(path, "rb")
        self._f.seek(0, os.SEEK_END)
        file_end = self._f.tell()
        self._f.seek(file_end - 16)
        idx_off, count = struct.unpack("<QI", self._f.read(12))
        if self._f.read(4) != _SEG_MAGIC:
            raise OSError(f"bad segment magic in {path}")
        self._data_end = idx_off
        self._f.seek(idx_off)
        self.sparse_keys: list[bytes] = []
        self.sparse_offs: list[int] = []
        for _ in range(count):
            (klen,) = struct.unpack("<I", self._f.read(4))
            self.sparse_keys.append(self._f.read(klen))
            (off,) = struct.unpack("<Q", self._f.read(8))
            self.sparse_offs.append(off)
        self._lock = threading.Lock()

    @staticmethod
    def write(path: str, items: list[tuple[bytes, Optional[bytes]]]) -> None:
        """Persist sorted (key, value|None) pairs atomically."""
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            sparse: list[tuple[bytes, int]] = []
            for i, (k, v) in enumerate(items):
                if i % _SPARSE_EVERY == 0:
                    sparse.append((k, f.tell()))
                f.write(struct.pack("<I", len(k)) + k)
                if v is None:
                    f.write(struct.pack("<i", -1))
                else:
                    f.write(struct.pack("<i", len(v)) + v)
            idx_off = f.tell()
            for k, off in sparse:
                f.write(struct.pack("<I", len(k)) + k + struct.pack("<Q", off))
            f.write(struct.pack("<QI", idx_off, len(sparse)) + _SEG_MAGIC)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        fsync_dir(path)

    def _records_from(self, off: int) -> Iterator[tuple[bytes, Optional[bytes], int]]:
        """Yield (key, value, next_offset) from `off`; caller holds lock."""
        f = self._f
        f.seek(off)
        while off < self._data_end:
            (klen,) = struct.unpack("<I", f.read(4))
            k = f.read(klen)
            (vlen,) = struct.unpack("<i", f.read(4))
            v = f.read(vlen) if vlen >= 0 else None
            off = f.tell()
            yield k, v, off

    def get(self, key: bytes) -> tuple[bool, Optional[bytes]]:
        """-> (found, value|None-for-tombstone)."""
        if not self.sparse_keys or key < self.sparse_keys[0]:
            return False, None
        i = bisect.bisect_right(self.sparse_keys, key) - 1
        with self._lock:
            for k, v, _nxt in self._records_from(self.sparse_offs[i]):
                if k == key:
                    return True, v
                if k > key:
                    return False, None
        return False, None

    def range(self, lo: bytes, hi: bytes) -> Iterator[tuple[bytes, Optional[bytes]]]:
        """All (key, value) with lo <= key < hi, ascending. Materializes
        the qualifying records under the lock (segments are immutable
        and block-local, so this is bounded by the range size)."""
        if not self.sparse_keys:
            return iter(())
        i = max(bisect.bisect_right(self.sparse_keys, lo) - 1, 0)
        out: list[tuple[bytes, Optional[bytes]]] = []
        with self._lock:
            for k, v, _nxt in self._records_from(self.sparse_offs[i]):
                if k >= hi:
                    break
                if k >= lo:
                    out.append((k, v))
        return iter(out)

    def items(self) -> list[tuple[bytes, Optional[bytes]]]:
        with self._lock:
            return [(k, v) for k, v, _ in self._records_from(0)]

    def close(self) -> None:
        self._f.close()


class SSTableStore:
    """FilerStore over the WAL + memtable + segment engine."""

    def __init__(
        self,
        directory: str,
        memtable_limit: int = 4 << 20,
        compact_at: int = 8,
        fsync: bool = False,
    ):
        os.makedirs(directory, exist_ok=True)
        self.dir = directory
        self.memtable_limit = memtable_limit
        self.compact_at = compact_at
        self.fsync = fsync
        self._lock = threading.RLock()
        self._mem: dict[bytes, Optional[bytes]] = {}
        self._mem_bytes = 0
        self._segments: list[_Segment] = []  # oldest .. newest
        self._seq = 0
        for name in sorted(os.listdir(directory)):
            if name.startswith("seg-") and name.endswith(".sst"):
                self._segments.append(_Segment(os.path.join(directory, name)))
                self._seq = max(self._seq, int(name[4:-4]) + 1)
        self._wal_path = os.path.join(directory, "wal.log")
        self._replay_wal()
        self._wal = open(self._wal_path, "ab")

    # ------------------------------------------------------------- WAL

    def _replay_wal(self) -> None:
        if not os.path.exists(self._wal_path):
            return
        valid_end = 0
        with open(self._wal_path, "rb") as f:
            while True:
                hdr = f.read(_WAL_HDR.size)
                if len(hdr) < _WAL_HDR.size:
                    break
                ln, want = _WAL_HDR.unpack(hdr)
                payload = f.read(ln)
                if len(payload) < ln or crc32c(payload) != want:
                    break  # torn tail: everything before it is intact
                valid_end = f.tell()
                op = payload[0]
                (klen,) = struct.unpack_from("<I", payload, 1)
                k = payload[5 : 5 + klen]
                v = payload[5 + klen :] if op == _PUT else None
                self._mem_apply(k, v)
        if os.path.getsize(self._wal_path) > valid_end:
            # Truncate the torn record NOW: appending after it would
            # strand every post-crash write behind bytes the next
            # replay can never get past (acked writes would vanish on
            # the reopen after next).
            with open(self._wal_path, "r+b") as f:
                f.truncate(valid_end)
                f.flush()
                os.fsync(f.fileno())

    def _wal_append(self, key: bytes, value: Optional[bytes]) -> None:
        op = _PUT if value is not None else _DEL
        payload = (
            bytes([op]) + struct.pack("<I", len(key)) + key + (value or b"")
        )
        self._wal.write(_WAL_HDR.pack(len(payload), crc32c(payload)) + payload)
        self._wal.flush()
        if self.fsync:
            os.fsync(self._wal.fileno())

    # -------------------------------------------------------- memtable

    def _mem_apply(self, key: bytes, value: Optional[bytes]) -> None:
        if key not in self._mem:
            self._mem_bytes += len(key)
        else:
            self._mem_bytes -= len(self._mem[key] or b"")
        self._mem[key] = value
        self._mem_bytes += len(value or b"")

    def _write(self, key: bytes, value: Optional[bytes]) -> None:
        with self._lock:
            self._wal_append(key, value)
            self._mem_apply(key, value)
            if self._mem_bytes >= self.memtable_limit:
                self._flush_locked()

    def _flush_locked(self) -> None:
        if not self._mem:
            return
        path = os.path.join(self.dir, f"seg-{self._seq:08d}.sst")
        _Segment.write(path, sorted(self._mem.items()))
        self._seq += 1
        self._segments.append(_Segment(path))
        self._mem.clear()
        self._mem_bytes = 0
        self._wal.close()
        os.unlink(self._wal_path)
        self._wal = open(self._wal_path, "ab")
        fsync_dir(self._wal_path)
        if len(self._segments) > self.compact_at:
            self._compact_locked()

    def _compact_locked(self) -> None:
        """Size-tiered-to-one: merge every segment, newest value wins,
        tombstones dropped (nothing older remains to resurrect)."""
        merged: dict[bytes, Optional[bytes]] = {}
        for seg in self._segments:  # oldest -> newest
            for k, v in seg.items():
                merged[k] = v
        live = sorted(
            (k, v) for k, v in merged.items() if v is not None
        )
        path = os.path.join(self.dir, f"seg-{self._seq:08d}.sst")
        _Segment.write(path, live)
        self._seq += 1
        old = self._segments
        self._segments = [_Segment(path)]
        for seg in old:
            seg.close()
            os.unlink(seg.path)
        fsync_dir(path)

    def flush(self) -> None:
        with self._lock:
            self._flush_locked()

    # ----------------------------------------------------------- reads

    def _get(self, key: bytes) -> Optional[bytes]:
        with self._lock:
            if key in self._mem:
                return self._mem[key]
            for seg in reversed(self._segments):
                found, v = seg.get(key)
                if found:
                    return v
        return None

    def _range(self, lo: bytes, hi: bytes) -> Iterator[tuple[bytes, bytes]]:
        """Merged ascending scan of [lo, hi); newest layer wins,
        tombstones suppress."""
        with self._lock:
            layers: dict[bytes, Optional[bytes]] = {}
            for seg in self._segments:  # oldest first
                for k, v in seg.range(lo, hi):
                    layers[k] = v
            for k, v in self._mem.items():
                if lo <= k < hi:
                    layers[k] = v
        for k in sorted(layers):
            v = layers[k]
            if v is not None:
                yield k, v

    # ------------------------------------------------- FilerStore SPI

    def insert(self, entry: Entry) -> None:
        self._write(_entry_key(entry.directory, entry.name), entry.to_bytes())

    update = insert

    def find(self, directory: str, name: str) -> Entry:
        raw = self._get(_entry_key(directory, name))
        if raw is None:
            raise NotFound(f"{directory}/{name}")
        return Entry.from_bytes(directory, raw)

    def delete(self, directory: str, name: str) -> None:
        self._write(_entry_key(directory, name), None)

    def delete_folder_children(self, directory: str) -> None:
        prefix = directory if directory.endswith("/") else directory + "/"
        # children whose parent IS `directory`
        lo = b"E" + directory.encode() + b"\x00"
        for k, _v in list(self._range(lo, lo + b"\xff")):
            self._write(k, None)
        # children of every nested directory (dir string prefix match;
        # \xff exceeds any UTF-8 lead byte, so it is a safe upper bound)
        lo = b"E" + prefix.encode()
        for k, _v in list(self._range(lo, lo + b"\xff")):
            self._write(k, None)

    def list(
        self,
        directory: str,
        start_from: str = "",
        limit: int = 1024,
        prefix: str = "",
    ) -> Iterator[Entry]:
        base = b"E" + directory.encode() + b"\x00"
        # tighten the scan's lower bound with start_from so pagination
        # is O(page), not O(directory); the `name <= start_from` filter
        # below still enforces the exclusive boundary
        lo = base + max(prefix, start_from).encode()
        hi = base + (prefix.encode() + b"\xff" if prefix else b"\xff")
        n = 0
        for k, v in self._range(lo, hi):
            name = k[len(base):].decode()
            if start_from and name <= start_from:
                continue
            if n >= limit:
                return
            yield Entry.from_bytes(directory, v)
            n += 1

    def kv_put(self, key: bytes, value: bytes) -> None:
        self._write(_kv_key(key), value)

    def kv_get(self, key: bytes) -> Optional[bytes]:
        return self._get(_kv_key(key))

    def kv_delete(self, key: bytes) -> None:
        self._write(_kv_key(key), None)

    def kv_put_if_absent(self, key: bytes, value: bytes) -> bytes:
        with self._lock:
            got = self._get(_kv_key(key))
            if got is not None:
                return got
            self._write(_kv_key(key), value)
            return value

    def close(self) -> None:
        with self._lock:
            self._flush_locked()
            self._wal.close()
            for seg in self._segments:
                seg.close()
