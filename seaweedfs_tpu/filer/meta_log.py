"""Persisted filer metadata log: every mutation appended as one JSON
line, replayable from any timestamp.

Reference: weed/filer meta log (filer_notify*.go — events appended to
per-filer log files, consumed by SubscribeMetadata for mount cache
invalidation and filer.sync). Here: NDJSON segments with size-based
rotation; readers tail from a ts_ns watermark.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Iterator, Optional

from ..pb import filer_pb2 as fpb
from .notification import event_to_json

SEGMENT_BYTES = 64 * 1024 * 1024
KEEP_SEGMENTS = 8


class MetaLog:
    """Append-only NDJSON event log with rotation."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self._lock = threading.Condition()
        self._current_path = os.path.join(directory, "meta.log")
        self._f = open(self._current_path, "ab")
        self.last_ts_ns = self._scan_last_ts()
        # newest tsNs among segments retention has DELETED: a subscriber
        # whose watermark is below this has an unrecoverable gap.
        # In-memory only — a filer restart resets it (subscribers then
        # rely on oldest_retained_ts staying ahead of their watermark).
        self.dropped_before_ts = 0

    def _scan_last_ts(self) -> int:
        last = 0
        for path in self._segments():
            try:
                with open(path, "rb") as f:
                    for line in f:
                        try:
                            last = max(last, json.loads(line).get("tsNs", 0))
                        except json.JSONDecodeError:
                            continue
            except FileNotFoundError:
                continue
        return last

    def oldest_retained_ts(self) -> int:
        """tsNs of the oldest record still on disk (0 = empty log).
        A subscriber whose watermark is older than this has a GAP —
        events were rotated away — and must full-resync."""
        for path in self._segments():
            try:
                with open(path, "rb") as f:
                    for line in f:
                        try:
                            return json.loads(line).get("tsNs", 0)
                        except json.JSONDecodeError:
                            continue
            except FileNotFoundError:
                continue
        return 0

    # ------------------------------------------------------------- write

    def __call__(self, ev: fpb.FullEventNotification) -> None:
        """Filer listener entry point."""
        record = event_to_json(ev)
        line = (json.dumps(record, separators=(",", ":")) + "\n").encode()
        with self._lock:
            self._f.write(line)
            self._f.flush()
            # max(): a non-monotonic record must never roll the
            # watermark (or sealed-segment name) backwards
            self.last_ts_ns = max(self.last_ts_ns, record["tsNs"])
            if self._f.tell() > SEGMENT_BYTES:
                self._rotate_locked()
            self._lock.notify_all()

    def _rotate_locked(self) -> None:
        self._f.close()
        # sealed name carries the segment's newest tsNs so readers can
        # skip whole segments below their watermark
        sealed = os.path.join(
            self.directory, f"meta-{self.last_ts_ns:020d}.log"
        )
        os.replace(self._current_path, sealed)
        self._f = open(self._current_path, "ab")
        # bounded retention
        sealed_all = sorted(
            f for f in os.listdir(self.directory) if f.startswith("meta-")
        )
        for old in sealed_all[:-KEEP_SEGMENTS]:
            try:
                self.dropped_before_ts = max(
                    self.dropped_before_ts, int(old[5:-4])
                )
            except ValueError:
                pass
            os.unlink(os.path.join(self.directory, old))

    # -------------------------------------------------------------- read

    def _segments(self) -> list[str]:
        sealed = sorted(
            os.path.join(self.directory, f)
            for f in os.listdir(self.directory)
            if f.startswith("meta-")
        )
        return sealed + [self._current_path]

    def read_since(self, since_ns: int, limit: int = 10_000) -> list[dict]:
        """Events with tsNs > since_ns, oldest first."""
        if since_ns >= self.last_ts_ns:
            return []
        out: list[dict] = []
        for path in self._segments():
            # sealed segment names embed their max tsNs: skip whole
            # segments below the watermark instead of re-parsing them
            name = os.path.basename(path)
            if name.startswith("meta-"):
                try:
                    if int(name[5:-4]) <= since_ns:
                        continue
                except ValueError:
                    pass
            try:
                with open(path, "rb") as f:
                    for line in f:
                        try:
                            rec = json.loads(line)
                        except json.JSONDecodeError:
                            continue  # torn tail line
                        if rec.get("tsNs", 0) > since_ns:
                            out.append(rec)
                            if len(out) >= limit:
                                return out
            except FileNotFoundError:
                continue
        return out

    def wait_for_events(self, since_ns: int, timeout: float) -> bool:
        with self._lock:
            return self._lock.wait_for(
                lambda: self.last_ts_ns > since_ns, timeout=timeout
            )

    def close(self) -> None:
        with self._lock:
            self._f.close()
