"""POSIX advisory byte-range locks with leases.

Reference: weed/filer/filer_grpc_server_posix_lock.go + the cluster
lock manager (weed/cluster/lock_manager) — FUSE mounts and multi-writer
clients coordinate through the filer: shared/exclusive ranges keyed by
path, owned by a client identity, auto-expiring on a lease so a dead
client can never wedge a file."""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass

MAX_END = 2**63 - 1


@dataclass
class LockRange:
    owner: str
    start: int
    end: int  # exclusive
    exclusive: bool
    expires_at: float


class PosixLockManager:
    def __init__(self, default_lease: float = 30.0):
        self._lock = threading.Lock()
        self._by_path: dict[str, list[LockRange]] = {}
        self.default_lease = default_lease

    def _alive(self, path: str) -> list[LockRange]:
        now = time.monotonic()
        ranges = [
            r for r in self._by_path.get(path, []) if r.expires_at > now
        ]
        if ranges:
            self._by_path[path] = ranges
        else:
            self._by_path.pop(path, None)
        return ranges

    @staticmethod
    def _overlaps(a_start: int, a_end: int, b: LockRange) -> bool:
        return a_start < b.end and b.start < a_end

    def lock(
        self,
        path: str,
        owner: str,
        start: int = 0,
        end: int = 0,
        exclusive: bool = True,
        lease: float = 0.0,
    ) -> tuple[bool, str]:
        """(granted, conflicting_owner). end=0 means to-EOF. Re-locking
        by the same owner replaces its overlapping ranges (POSIX
        F_SETLK semantics: lock upgrade/downgrade in place)."""
        end = end or MAX_END
        if end <= start:
            return False, ""
        lease = lease or self.default_lease
        with self._lock:
            ranges = self._alive(path)
            for r in ranges:
                if r.owner == owner:
                    continue
                if not self._overlaps(start, end, r):
                    continue
                if exclusive or r.exclusive:
                    return False, r.owner
            # same-owner overlapping ranges are replaced
            kept = [
                r
                for r in ranges
                if r.owner != owner or not self._overlaps(start, end, r)
            ]
            kept.append(
                LockRange(
                    owner=owner,
                    start=start,
                    end=end,
                    exclusive=exclusive,
                    expires_at=time.monotonic() + lease,
                )
            )
            self._by_path[path] = kept
            return True, ""

    def unlock(
        self, path: str, owner: str, start: int = 0, end: int = 0
    ) -> int:
        """Release the owner's locks overlapping [start, end); returns
        how many ranges were dropped (POSIX splits are simplified to
        whole-range release, like the reference's per-fh unlock)."""
        end = end or MAX_END
        with self._lock:
            ranges = self._alive(path)
            kept = [
                r
                for r in ranges
                if r.owner != owner or not self._overlaps(start, end, r)
            ]
            dropped = len(ranges) - len(kept)
            if kept:
                self._by_path[path] = kept
            else:
                self._by_path.pop(path, None)
            return dropped

    def renew(self, path: str, owner: str, lease: float = 0.0) -> int:
        """Extend the owner's leases on a path; returns ranges renewed."""
        lease = lease or self.default_lease
        with self._lock:
            n = 0
            for r in self._alive(path):
                if r.owner == owner:
                    r.expires_at = time.monotonic() + lease
                    n += 1
            return n

    def test(
        self,
        path: str,
        start: int = 0,
        end: int = 0,
        exclusive: bool = True,
        owner: str = "",
    ) -> str:
        """First conflicting owner for a hypothetical lock ('' = none) —
        F_GETLK. The caller's OWN locks never conflict (POSIX: a
        process testing a range it holds must see it as lockable)."""
        end = end or MAX_END
        with self._lock:
            for r in self._alive(path):
                if r.owner == owner:
                    continue
                if self._overlaps(start, end, r) and (exclusive or r.exclusive):
                    return r.owner
            return ""

    def holders(self, path: str) -> list[LockRange]:
        with self._lock:
            return list(self._alive(path))
