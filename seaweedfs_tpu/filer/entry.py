"""Filer entry model: a path in the namespace plus attributes and the
chunk list that backs file content.

Reference: weed/filer/entry.go + entry_codec.go (protobuf-encoded into
the KV store).
"""

from __future__ import annotations

import stat
import time
from dataclasses import dataclass, field
from typing import Optional

from ..pb import filer_pb2 as fpb


def now_ns() -> int:
    return time.time_ns()


@dataclass
class Entry:
    directory: str  # parent dir, "/" rooted, no trailing slash (except root)
    name: str
    is_directory: bool = False
    chunks: list[fpb.FileChunk] = field(default_factory=list)
    attr: fpb.Attr = field(default_factory=fpb.Attr)
    extended: dict[str, bytes] = field(default_factory=dict)
    content: bytes = b""  # small-file inlining
    # hardlinks (reference filer_hardlink.go): entries sharing one
    # chunk list carry the same id; the live-name count lives in the
    # store's KV so chunk GC runs only when the last name goes
    hard_link_id: bytes = b""
    hard_link_counter: int = 0

    @property
    def full_path(self) -> str:
        if self.directory == "/":
            return "/" + self.name
        return f"{self.directory}/{self.name}"

    @property
    def file_size(self) -> int:
        if self.content:
            return len(self.content)
        if self.attr.file_size:
            return self.attr.file_size
        return max((c.offset + c.size for c in self.chunks), default=0)

    def mode(self) -> int:
        m = self.attr.file_mode
        if self.is_directory and not stat.S_ISDIR(m):
            m |= stat.S_IFDIR
        return m

    # ---- codec ----

    def to_proto(self) -> fpb.Entry:
        e = fpb.Entry(
            name=self.name,
            is_directory=self.is_directory,
            chunks=self.chunks,
            content=self.content,
            hard_link_id=self.hard_link_id,
            hard_link_counter=self.hard_link_counter,
        )
        e.attributes.CopyFrom(self.attr)
        for k, v in self.extended.items():
            e.extended[k] = v
        return e

    def to_bytes(self) -> bytes:
        return self.to_proto().SerializeToString()

    @classmethod
    def from_proto(cls, directory: str, p: fpb.Entry) -> "Entry":
        e = cls(
            directory=directory,
            name=p.name,
            is_directory=p.is_directory,
            chunks=list(p.chunks),
            content=p.content,
            hard_link_id=p.hard_link_id,
            hard_link_counter=p.hard_link_counter,
        )
        e.attr.CopyFrom(p.attributes)
        e.extended = dict(p.extended)
        return e

    @classmethod
    def from_bytes(cls, directory: str, raw: bytes) -> "Entry":
        return cls.from_proto(directory, fpb.Entry.FromString(raw))


def new_entry(
    full_path: str,
    is_directory: bool = False,
    mode: int = 0o644,
    mime: str = "",
) -> Entry:
    directory, _, name = full_path.rstrip("/").rpartition("/")
    e = Entry(directory=directory or "/", name=name, is_directory=is_directory)
    now = int(time.time())
    e.attr.mtime = now
    e.attr.crtime = now
    e.attr.file_mode = mode | (stat.S_IFDIR if is_directory else stat.S_IFREG)
    if mime:
        e.attr.mime = mime
    return e


def split_path(full_path: str) -> tuple[str, str]:
    full_path = normalize_path(full_path)
    if full_path == "/":
        return "/", ""
    directory, _, name = full_path.rpartition("/")
    return directory or "/", name


def normalize_path(p: str) -> str:
    if not p.startswith("/"):
        p = "/" + p
    while "//" in p:
        p = p.replace("//", "/")
    if len(p) > 1 and p.endswith("/"):
        p = p[:-1]
    return p
