"""Manifest chunks: chunk lists beyond a threshold are batched into
blobs so huge files don't bloat every metadata read.

Reference: weed/filer/filechunk_manifest.go — when a file exceeds
ManifestBatch (1000) chunks, groups of chunks are serialized into a
FileChunkManifest blob stored in the volume store; the entry keeps one
manifest FileChunk per batch (is_chunk_manifest=true) whose
offset/size cover the batch's logical span.
"""

from __future__ import annotations

import time

from ..pb import filer_pb2 as fpb

MANIFEST_BATCH = 1000


def has_manifests(chunks) -> bool:
    return any(c.is_chunk_manifest for c in chunks)


def maybe_manifestize(
    upload, chunks: list[fpb.FileChunk], threshold: int = MANIFEST_BATCH
) -> list[fpb.FileChunk]:
    """Batch data chunks into manifest blobs when there are more than
    `threshold`. `upload(data: bytes) -> fid`. Already-manifest chunks
    pass through untouched (no nested re-manifesting of a spliced
    entry's existing manifests)."""
    plain = [c for c in chunks if not c.is_chunk_manifest]
    out = [c for c in chunks if c.is_chunk_manifest]
    if len(plain) <= threshold:
        return chunks
    ts = time.time_ns()
    for i in range(0, len(plain), threshold):
        batch = plain[i : i + threshold]
        blob = fpb.FileChunkManifest(chunks=batch).SerializeToString()
        fid = upload(blob)
        lo = min(c.offset for c in batch)
        hi = max(c.offset + c.size for c in batch)
        out.append(
            fpb.FileChunk(
                fid=fid,
                offset=lo,
                size=hi - lo,
                modified_ts_ns=ts,
                is_chunk_manifest=True,
            )
        )
    out.sort(key=lambda c: c.offset)
    return out


def resolve_manifests(read, chunks) -> list[fpb.FileChunk]:
    """Expand manifest chunks into their underlying data chunks.
    `read(fid) -> bytes`. Recurses (a manifest may itself have been
    re-manifestized by a later splice)."""
    if not has_manifests(chunks):
        return list(chunks)
    out: list[fpb.FileChunk] = []
    for c in chunks:
        if not c.is_chunk_manifest:
            out.append(c)
            continue
        m = fpb.FileChunkManifest.FromString(read(c.fid))
        out.extend(resolve_manifests(read, list(m.chunks)))
    return out


def gc_expand(read, chunks) -> list[fpb.FileChunk]:
    """All chunks a GC must delete: data chunks, manifest-referenced
    chunks, and the manifest blobs themselves. A manifest blob that
    can't be read still yields its own fid (best effort — the data
    chunks it referenced are orphaned rather than crashing GC)."""
    out: list[fpb.FileChunk] = []
    for c in chunks:
        out.append(c)
        if c.is_chunk_manifest:
            try:
                m = fpb.FileChunkManifest.FromString(read(c.fid))
            except Exception:
                continue
            out.extend(gc_expand(read, list(m.chunks)))
    return out
