"""Filer event notification: publish mutations to external systems.

Reference: weed/notification (configuration.go; Kafka/SQS/PubSub/webhook
sinks) driven by the filer's meta-log events. Here: webhook (HTTP POST
of the JSON-rendered event) and an MQ sink (publish to a topic on the
framework's own broker) — both async with retry, never blocking the
mutation path.
"""

from __future__ import annotations

import base64
import dataclasses
import json
import queue
import threading
from typing import Optional

import requests

from ..pb import filer_pb2 as fpb
from ..utils.retry import RetryError, RetryPolicy, retry_call

# Delivery backoff: quick first retry, bounded tail — sinks are remote
# HTTP/broker endpoints whose blips last milliseconds to seconds.
DELIVERY_POLICY = RetryPolicy(max_attempts=3, base_delay=0.5, max_delay=5.0)


def event_to_json(ev: fpb.FullEventNotification) -> dict:
    def entry(e):
        if not e.name and not e.is_directory:
            return None
        return {
            "name": e.name,
            "isDirectory": e.is_directory,
            "size": max(
                (c.offset + c.size for c in e.chunks), default=len(e.content)
            ),
            "chunks": len(e.chunks),
        }

    return {
        "directory": ev.directory,
        "tsNs": ev.ts_ns,
        "oldEntry": entry(ev.event.old_entry),
        "newEntry": entry(ev.event.new_entry),
        "deleteChunks": ev.event.delete_chunks,
        "remote": ev.event.is_from_other_cluster,
        # full-fidelity event for gRPC SubscribeMetadata + aggregation
        # (the summary fields above stay cheap for the HTTP tail/sinks)
        "pb": base64.b64encode(ev.SerializeToString()).decode(),
    }


def json_to_event(rec: dict) -> Optional[fpb.FullEventNotification]:
    """Rebuild the protobuf event from a meta-log record; None for
    legacy records without the pb field."""
    raw = rec.get("pb")
    if not raw:
        return None
    try:
        return fpb.FullEventNotification.FromString(base64.b64decode(raw))
    except Exception:
        return None


class _AsyncNotifier:
    """Bounded queue + delivery thread: the mutation path only ever
    enqueues; a stalled sink can never block filer writes. Delivery
    retries run under the unified RetryPolicy (utils/retry.py), with
    the stop event as the sleep so close() aborts a backoff wait."""

    def __init__(
        self,
        max_queue: int = 10_000,
        retries: int = 3,
        policy: RetryPolicy | None = None,
    ):
        if policy is None:
            policy = dataclasses.replace(DELIVERY_POLICY, max_attempts=retries)
        self.policy = policy
        self._q: "queue.Queue" = queue.Queue(maxsize=max_queue)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        self.dropped = 0
        self.delivered = 0

    def __call__(self, ev: fpb.FullEventNotification) -> None:
        try:
            self._q.put_nowait(event_to_json(ev))
        except queue.Full:
            self.dropped += 1

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                payload = self._q.get(timeout=0.5)
            except queue.Empty:
                continue
            if self._deliver_with_retry(payload):
                self.delivered += 1
            else:
                self.dropped += 1

    def _deliver_with_retry(self, payload: dict) -> bool:
        # _deliver: True = delivered, False = PERMANENT rejection (no
        # retry — retry_call just returns it), exception = transient.
        try:
            return bool(
                retry_call(
                    lambda: self._deliver(payload),
                    self.policy,
                    sleep=self._stop.wait,
                    describe="notification delivery",
                )
            )
        except RetryError:
            return False

    def _deliver(self, payload: dict) -> bool:
        raise NotImplementedError

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2)


class WebhookNotifier(_AsyncNotifier):
    """POSTs each filer event to a URL."""

    def __init__(self, url: str, max_queue: int = 10_000, retries: int = 3):
        self.url = url
        self._http = requests.Session()
        super().__init__(max_queue, retries)

    def _deliver(self, payload: dict) -> bool:
        r = self._http.post(self.url, json=payload, timeout=10)
        if r.status_code >= 500:
            raise requests.HTTPError(f"{r.status_code}")  # transient: retry
        return r.status_code < 400  # 4xx = permanent rejection


class MqNotifier(_AsyncNotifier):
    """Publishes events to a topic on the framework's MQ broker."""

    def __init__(self, broker: str, topic: str = "filer-events", namespace: str = "default"):
        from ..mq import MqClient

        self.client = MqClient(broker)
        self.topic = topic
        self.namespace = namespace
        try:
            self.client.configure_topic(topic, partitions=4, namespace=namespace)
        except Exception:
            pass
        super().__init__()

    def _deliver(self, payload: dict) -> bool:
        self.client.publish(
            self.topic,
            json.dumps(payload).encode(),
            key=(payload.get("directory") or "").encode(),
            namespace=self.namespace,
        )
        return True

    def close(self) -> None:
        super().close()
        self.client.close()


class KafkaNotifier(_AsyncNotifier):
    """Publishes events to any Kafka-wire-protocol broker (reference
    weed/notification/kafka). Rides the framework's own Kafka client —
    the same wire encoding a Java client produces — so it works against
    real Kafka clusters AND this framework's Kafka gateway."""

    def __init__(
        self,
        broker: str,
        topic: str = "seaweedfs_filer",
        partitions: int = 1,
    ):
        from ..mq.kafka.client import KafkaClient

        host, _, port = broker.partition(":")
        self.client = KafkaClient(host, int(port or 9092))
        self.topic = topic
        self._partitions = max(partitions, 1)
        try:
            self.client.create_topic(topic, partitions=self._partitions)
        except Exception:  # noqa: BLE001 — exists / auto-create / ACL
            pass
        super().__init__()

    def _deliver(self, payload: dict) -> bool:
        import zlib

        from ..mq.kafka.records import Record

        key = (payload.get("directory") or "").encode()
        # stable across processes/restarts (builtin hash is seeded):
        # per-directory ordering needs a deterministic partition
        part = zlib.crc32(key) % self._partitions
        self.client.produce(
            self.topic,
            part,
            [Record(key=key, value=json.dumps(payload).encode())],
        )
        return True

    def close(self) -> None:
        super().close()
        self.client.close()


def make_notifier(kind: str, target: str, **kw):
    """Config-driven sink construction (reference notification
    configuration.go): kind in webhook|mq|kafka|sqs|pubsub. SQS and
    Google Pub/Sub need their cloud SDKs, which this image does not
    ship — they are GATED with an explicit error rather than silently
    absent."""
    if kind == "webhook":
        return WebhookNotifier(target, **kw)
    if kind == "mq":
        return MqNotifier(target, **kw)
    if kind == "kafka":
        return KafkaNotifier(target, **kw)
    if kind == "sqs":
        try:
            import boto3  # noqa: F401
        except ImportError as e:
            raise RuntimeError(
                "SQS notification requires boto3, which is not installed "
                "in this build; use webhook/kafka/mq sinks instead"
            ) from e
        raise NotImplementedError("SQS sink: boto3 present but unwired")
    if kind == "pubsub":
        try:
            import google.cloud.pubsub_v1  # noqa: F401
        except ImportError as e:
            raise RuntimeError(
                "Google Pub/Sub notification requires google-cloud-pubsub, "
                "which is not installed in this build; use webhook/kafka/mq"
            ) from e
        raise NotImplementedError("Pub/Sub sink: SDK present but unwired")
    raise ValueError(f"unknown notifier kind {kind!r}")
