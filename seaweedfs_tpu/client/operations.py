"""One-shot client operations: upload/read/delete blobs end to end.

Reference: weed/operation (Uploader upload_content.go:163, SubmitFiles,
DeleteFiles) — HTTP data plane against volume servers, gRPC to master.
"""

from __future__ import annotations

import requests

from ..storage.file_id import FileId
from .master_client import MasterClient


class Operations:
    def __init__(self, master: str = "localhost:9333"):
        self.master = MasterClient(master)
        self._http = requests.Session()

    def upload(
        self,
        data: bytes,
        name: str = "",
        mime: str = "",
        collection: str = "",
        replication: str = "",
    ) -> str:
        a = self.master.assign(collection=collection, replication=replication)
        url = f"http://{a.url}/{a.fid}"
        files = {"file": (name or "file", data, mime or "application/octet-stream")}
        r = self._http.post(url, files=files, timeout=60)
        r.raise_for_status()
        return a.fid

    def read(self, fid: str) -> bytes:
        f = FileId.parse(fid)
        for loc in self.master.lookup(f.volume_id):
            r = self._http.get(f"http://{loc.url}/{fid}", timeout=60)
            if r.status_code == 200:
                return r.content
        raise LookupError(f"fid {fid} unreadable on all locations")

    def delete(self, fid: str) -> None:
        f = FileId.parse(fid)
        for loc in self.master.lookup(f.volume_id):
            self._http.delete(f"http://{loc.url}/{fid}", timeout=60)
            return

    def close(self) -> None:
        self.master.close()
