"""One-shot client operations: upload/read/delete blobs end to end.

Reference: weed/operation (Uploader upload_content.go:163, SubmitFiles,
DeleteFiles) — HTTP data plane against volume servers, gRPC to master.
"""

from __future__ import annotations

import os
import time

import requests

from .. import faults
from ..ec import native_io
from ..ec import net_plane as _netp
from ..storage.file_id import FileId
from ..utils.retry import RetryError, RetryPolicy, retry_call
from ..utils.urls import service_url
from .master_client import MasterClient


class _PermanentUploadError(Exception):
    """Non-retryable upload failure (4xx); carries the HTTPError."""

    def __init__(self, err: Exception):
        super().__init__(str(err))
        self.err = err


class TracingSession(requests.Session):
    """requests.Session that stamps the active X-Request-ID AND (when
    the flight recorder is armed) the ambient span's trace context onto
    every outgoing call, so one id/trace follows
    client → filer → volume hops (reference weed/util/request_id)."""

    def request(self, method, url, **kw):  # type: ignore[override]
        from ..utils import request_id, trace

        headers = dict(kw.get("headers") or {})
        request_id.inject(headers)
        trace.http_headers(headers=headers)
        kw["headers"] = headers
        return super().request(method, url, **kw)


class Operations:
    def __init__(self, master: str = "localhost:9333", jwt_key: str = ""):
        """jwt_key: shared write-authz signing key; trusted components
        (filer, tools) self-sign tokens the way the reference's
        security.toml-holding services do."""
        self.master = MasterClient(master)
        self.jwt_key = jwt_key
        self._http = TracingSession()
        # chunk fetches over the shard net plane (ISSUE 13): the client
        # is connection-lazy (construction makes no sockets), so build
        # it eagerly — no init race to reason about. `_plane_refused`
        # negative-caches volumes the plane can never serve (EC/TTL'd/
        # tiered: the server refuses on EVERY read), TTL'd because a
        # volume's tier can change.
        self._plane_client = _netp.NetPlaneClient()
        self._plane_refused: dict[int, float] = {}

    def _auth_headers(self, token: str, fid: str) -> dict:
        if not token and self.jwt_key:
            from ..utils.security import sign_jwt

            token = sign_jwt(self.jwt_key, fid)
        return {"Authorization": f"Bearer {token}"} if token else {}

    # Transient failures only: assign errors, connection errors, 5xx.
    # 4xx is permanent and escapes via _PermanentUploadError (not in
    # retry_on), exactly like the old hand-rolled loop's early raise.
    _UPLOAD_POLICY = RetryPolicy(
        max_attempts=4,
        base_delay=0.1,
        max_delay=1.0,
        retry_on=(requests.RequestException, RuntimeError),
    )

    def upload(
        self,
        data: bytes,
        name: str = "",
        mime: str = "",
        collection: str = "",
        replication: str = "",
        ttl: str = "",
    ) -> str:
        """Assign + POST under the unified retry policy (reference
        UploadWithRetry, upload_content.go): a write can race a volume
        going readonly (vacuum, ec.encode) or a momentarily-unassignable
        master — re-assign and try again. 4xx responses are permanent
        and raise immediately."""

        def attempt() -> str:
            a = self.master.assign(
                collection=collection, replication=replication, ttl=ttl
            )
            if self._try_plane_write(a, data, name, mime):
                return a.fid
            url = service_url(a.url, f"/{a.fid}")
            files = {
                "file": (name or "file", data, mime or "application/octet-stream")
            }
            r = self._http.post(
                url,
                files=files,
                timeout=60,
                headers=self._auth_headers(a.jwt, a.fid),
            )
            if r.status_code < 400:
                return a.fid
            err = requests.HTTPError(f"{r.status_code} for {url}: {r.text[:200]}")
            if r.status_code < 500:  # permanent (auth, bad request)
                raise _PermanentUploadError(err)
            raise err

        try:
            return retry_call(attempt, self._UPLOAD_POLICY, describe="upload")
        except _PermanentUploadError as e:
            raise e.err from None
        except RetryError as e:
            # callers match on the underlying transport error, as with
            # the old loop's `raise last_exc`
            raise e.__cause__ from None

    def read(self, fid: str, fast: bool = True) -> bytes:
        f = FileId.parse(fid)
        for loc in self.master.lookup(f.volume_id):
            if fast:
                # net plane first: one 38-byte request on a persistent
                # TCP connection (locate resolved server-side) beats
                # the fastread sidecar's per-read HTTP ?locate round
                # trip; fastread remains the local bulk-read path when
                # the plane is absent.
                data = self._try_plane_read(loc, f)
                if data is not None:
                    return data
                data = self._try_fast_read(loc.url, fid)
                if data is not None:
                    return data
            r = self._http.get(service_url(loc.url, f"/{fid}"), timeout=60)
            if r.status_code == 200:
                return r.content
        raise LookupError(f"fid {fid} unreadable on all locations")

    # how long a VOLUME-level plane refusal (status 2: EC/TTL'd/tiered)
    # is negative-cached per vid — a volume's tier can change, so the
    # plane is re-probed after this instead of never
    _PLANE_REFUSAL_TTL_S = 60.0

    def _try_plane_read(self, loc, f: FileId) -> bytes | None:
        """Warm-path chunk fetch over the volume server's shard net
        plane (ISSUE 13): the needle payload lands straight in a pooled
        aligned buffer (sendfile -> sn_recv_into, CRC fused into the
        copy-in) instead of re-buffering through Python HTTP. None =
        fall back to the bit-identical `requests` path (plane disabled,
        sidecar absent, EC/TTL'd volume, CRC mismatch, armed faults —
        chaos belongs to the HTTP path's fault points)."""
        if (
            not native_io.enabled()
            or faults.active()
            or os.environ.get("SEAWEED_CHUNK_NET_PLANE", "1") == "0"
        ):
            return None
        gport = getattr(loc, "grpc_port", 0)
        if not gport:
            return None
        refused_at = self._plane_refused.get(f.volume_id)
        if refused_at is not None:
            if time.monotonic() - refused_at < self._PLANE_REFUSAL_TTL_S:
                return None
            self._plane_refused.pop(f.volume_id, None)
        addr = (loc.url.split(":")[0], _netp.derive_port(gport))
        try:
            return self._plane_client.read_needle(
                addr, f.volume_id, f.needle_id, f.cookie
            )
        except _netp.NetPlaneUnavailable:
            return None
        except _netp.NetPlaneError as e:
            if getattr(e, "volume_refusal", False):
                self._plane_refused[f.volume_id] = time.monotonic()
            return None

    def _try_plane_write(self, a, data: bytes, name: str, mime: str) -> bool:
        """PUT over the volume server's native write plane (ISSUE 18):
        header + payload on a pooled sidecar connection, CRC32C fused
        into the server's copy-in, replica fan-out running server-side
        exactly as for an HTTP POST. The needle record the server lands
        is bit-identical to the HTTP multipart path's (same
        name-or-"file" / mime defaults). False = fall back to the HTTP
        POST (plane disabled, sidecar absent, non-write chaos armed —
        those fault points belong to the HTTP path — or any plane
        error: the POST is the correctness path)."""
        if (
            not native_io.enabled()
            or os.environ.get("SEAWEED_CHUNK_NET_PLANE_WRITE", "1") == "0"
            or not _netp.write_plane_admissible()
        ):
            return False
        gport = getattr(a, "grpc_port", 0)
        if not gport:
            return False
        try:
            f = FileId.parse(a.fid)
        except Exception:  # noqa: BLE001 — odd fid: HTTP can cope
            return False
        refused_at = self._plane_refused.get(f.volume_id)
        if refused_at is not None:
            if time.monotonic() - refused_at < self._PLANE_REFUSAL_TTL_S:
                return False
            self._plane_refused.pop(f.volume_id, None)
        jwt = a.jwt
        if not jwt and self.jwt_key:
            from ..utils.security import sign_jwt

            jwt = sign_jwt(self.jwt_key, str(f.volume_id))
        addr = (a.url.split(":")[0], _netp.derive_port(gport))
        try:
            self._plane_client.write_needle(
                addr,
                f.volume_id,
                f.needle_id,
                f.cookie,
                data,
                name=(name or "file").encode(),
                mime=(mime or "application/octet-stream").encode(),
                jwt=jwt,
            )
            return True
        except _netp.NetPlaneUnavailable:
            return False
        except _netp.NetPlaneError as e:
            if getattr(e, "volume_refusal", False):
                self._plane_refused[f.volume_id] = time.monotonic()
            return False

    _LOCAL_HOSTS = None  # lazily-computed set of this machine's names

    @classmethod
    def _is_local(cls, url: str) -> bool:
        """Cheap locality check BEFORE paying a ?locate round trip —
        remote reads must not eat an extra RTT per chunk."""
        import socket as _socket

        host = url.split("//")[-1].split(":")[0]
        if cls._LOCAL_HOSTS is None:
            names = {"localhost", "127.0.0.1", "::1"}
            try:
                hn = _socket.gethostname()
                names.add(hn)
                names.update(_socket.gethostbyname_ex(hn)[2])
            except OSError:
                pass
            cls._LOCAL_HOSTS = names
        return host in cls._LOCAL_HOSTS

    def _try_fast_read(self, url: str, fid: str) -> bytes | None:
        """Same-host bulk-read bypass (RDMA sidecar analog): resolve
        the payload location over HTTP, then pull bytes through the
        native Unix-socket sendfile server, CRC-verified. None = fall
        back to HTTP (remote host, sidecar absent, EC volume, ...)."""
        import os

        if not self._is_local(url):
            return None
        if url in getattr(self, "_no_sidecar", set()):
            return None
        try:
            r = self._http.get(
                service_url(url, f"/{fid}?locate=true"), timeout=10
            )
            if r.status_code != 200:
                return None
            loc = r.json()
            sock = loc.get("socket", "")
            if not sock or not os.path.exists(sock):
                # negative-cache: this server has no reachable sidecar,
                # stop probing on every read
                self.__dict__.setdefault("_no_sidecar", set()).add(url)
                return None
            from ..utils.fastread import read_fid_fast

            return read_fid_fast(loc)
        except Exception:
            return None

    def delete(self, fid: str) -> None:
        f = FileId.parse(fid)
        canonical = str(f)  # tokens are scoped to the canonical fid form
        for loc in self.master.lookup(f.volume_id):
            r = self._http.delete(
                service_url(loc.url, f"/{canonical}"),
                timeout=60,
                headers=self._auth_headers("", canonical),
            )
            if r.status_code not in (200, 202, 204, 404):
                raise RuntimeError(
                    f"delete {canonical} on {loc.url}: HTTP {r.status_code} {r.text[:200]}"
                )
            return

    def close(self) -> None:
        self._plane_client.close()
        self.master.close()
