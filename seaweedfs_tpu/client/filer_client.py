"""Thin filer HTTP helpers shared by every component that walks the
namespace (sync daemon, MQ broker recovery, shell fs.* commands).

Reference: weed/filer_client — the minimal accessor package gateways use.
"""

from __future__ import annotations

import urllib.parse
from typing import Iterator, Optional

import requests
from ..utils.urls import service_url


class FilerListingError(requests.RequestException):
    """Subclasses RequestException so callers with transient-retry
    wrappers (e.g. the MQ broker's startup recovery) treat listing
    failures as retryable."""


def filer_url(filer: str, path: str) -> str:
    if not path.startswith("/"):
        path = "/" + path
    return service_url(filer, urllib.parse.quote(path))


def list_dir(
    filer: str,
    path: str,
    session: Optional[requests.Session] = None,
    strict: bool = False,
) -> Iterator[dict]:
    """Paginated directory listing (the filer caps pages at 1024).

    strict=True raises FilerListingError when the path is missing or not
    a directory — walkers that report success must not silently skip."""
    http = session or requests
    last = ""
    while True:
        r = http.get(
            filer_url(filer, path),
            params={"limit": "1024", "lastFileName": last},
            timeout=30,
        )
        if r.status_code == 404:
            if strict:
                raise FilerListingError(f"{path}: not found")
            return
        if r.status_code != 200:
            raise FilerListingError(f"{path}: HTTP {r.status_code}")
        if r.headers.get("X-Filer-Listing") != "true":
            if strict:
                raise FilerListingError(f"{path}: not a directory")
            return
        body = r.json()
        entries = body.get("Entries", [])
        yield from entries
        if not body.get("ShouldDisplayLoadMore") or not entries:
            return
        last = entries[-1]["FullPath"].rsplit("/", 1)[-1]


def walk(
    filer: str,
    root: str,
    session: Optional[requests.Session] = None,
    strict: bool = False,
) -> Iterator[dict]:
    """Depth-first recursive walk yielding every entry under root."""
    stack = [root]
    first = True
    while stack:
        d = stack.pop()
        for e in list_dir(filer, d, session, strict=strict and first):
            yield e
            if e["IsDirectory"]:
                stack.append(e["FullPath"])
        first = False
