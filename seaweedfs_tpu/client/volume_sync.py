"""Volume tail / incremental-sync client helpers.

Reference: weed/operation/tail_volume.go (TailVolumeFromSource — needle
reassembly from the VolumeTailSender chunk stream) and
weed/storage/volume_backup.go IncrementalBackup (byte-level follow).
"""

from __future__ import annotations

from typing import Iterator

import grpc

from ..pb import cluster_pb2 as pb
from ..pb import rpc
from ..storage.needle import Needle


def tail_volume(
    addr: str,
    volume_id: int,
    since_ns: int,
    idle_timeout_s: int = 3,
    timeout: float = 3600.0,
) -> Iterator[Needle]:
    """Yield needles (puts AND tombstones: the 0x40 flag bit)
    appended to `volume_id` on `addr` (host:grpcPort) after since_ns,
    following live appends until the source is idle for
    idle_timeout_s."""
    with grpc.insecure_channel(addr) as ch:
        stub = rpc.volume_stub(ch)
        pending = bytearray()
        version = 3
        for chunk in stub.VolumeTailSender(
            pb.VolumeTailRequest(
                volume_id=volume_id,
                since_ns=since_ns,
                idle_timeout_seconds=idle_timeout_s,
            ),
            timeout=timeout,
        ):
            version = chunk.version or version
            if chunk.needle_header or chunk.is_last_chunk:
                # a new record (or heartbeat) completes the pending one
                if pending:
                    yield Needle.from_bytes(bytes(pending), version)
                    pending.clear()
            if chunk.needle_header:
                pending += chunk.needle_header
            if chunk.needle_body:
                pending += chunk.needle_body
        if pending:
            yield Needle.from_bytes(bytes(pending), version)


def incremental_copy(
    addr: str,
    volume_id: int,
    since_ns: int,
    timeout: float = 3600.0,
) -> tuple[int, Iterator[bytes]]:
    """-> (start_offset, chunk iterator) of raw .dat bytes appended
    after since_ns. start_offset lets a byte-prefix follower verify it
    is appending at the right place before consuming the stream."""
    ch = grpc.insecure_channel(addr)
    stub = rpc.volume_stub(ch)
    stream = stub.VolumeIncrementalCopy(
        pb.VolumeIncrementalCopyRequest(
            volume_id=volume_id, since_ns=since_ns
        ),
        timeout=timeout,
    )
    try:
        first = next(stream)
    except StopIteration:
        ch.close()
        return 0, iter(())
    if not first.has_start:
        ch.close()
        raise RuntimeError("incremental copy stream missing start_offset")

    def chunks() -> Iterator[bytes]:
        try:
            if first.file_content:
                yield first.file_content
            for c in stream:
                if c.file_content:
                    yield c.file_content
        finally:
            ch.close()

    return first.start_offset, chunks()


def sync_replica(
    target_addr: str,
    source_addr: str,
    volume_id: int,
    since_ns: int = 0,
    idle_timeout_s: int = 3,
    timeout: float = 3600.0,
) -> int:
    """Ask the TARGET server to pull the tail from SOURCE (the
    volume.sync verb); returns needles applied."""
    with grpc.insecure_channel(target_addr) as ch:
        stub = rpc.volume_stub(ch)
        resp = stub.VolumeTailReceiver(
            pb.VolumeTailReceiverRequest(
                volume_id=volume_id,
                since_ns=since_ns,
                idle_timeout_seconds=idle_timeout_s,
                source_volume_server=source_addr,
            ),
            timeout=timeout,
        )
    if resp.error:
        raise RuntimeError(resp.error)
    return resp.received
