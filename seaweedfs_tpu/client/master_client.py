"""Client-side master session: assign/lookup with a streaming vid map.

Reference: weed/wdclient — MasterClient.KeepConnectedToMaster
(masterclient.go:483) feeds a vidMap (vid_map.go:35) with location
deltas so lookups are local and never stale-after-TTL; leader redirect
(masterclient.go:223) re-homes the session when masters fail over.
Unary lookups remain as the fallback while the stream is (re)connecting
and for EC shard-level locations (the stream carries vid-level EC
presence only).
"""

from __future__ import annotations

import threading
import time
import uuid
from dataclasses import dataclass

import grpc

from ..pb import cluster_pb2 as pb
from ..pb import rpc
from ..utils.retry import RetryError, RetryPolicy, retry_call

_CACHE_TTL = 10.0

# Leader-chasing policy: quick retries with mild backoff. The old
# hand-rolled loop slept a flat 0.1s x4; the unified policy keeps the
# same attempt budget but backs off under a persistent partition
# instead of hammering a dead leader at a fixed cadence.
_LEADER_POLICY = RetryPolicy(
    max_attempts=4, base_delay=0.05, max_delay=0.5, multiplier=2.0, jitter=0.2
)


@dataclass
class AssignResult:
    fid: str
    url: str
    public_url: str
    grpc_port: int
    replicas: list
    jwt: str = ""


def _grpc_addr(master: str) -> str:
    host, _, port = master.partition(":")
    return f"{host}:{int(port) + 10000}"


class MasterClient:
    def __init__(self, master: str = "localhost:9333", keepconnected: bool = True):
        """`master` may be a comma-separated HA group
        ("h1:9333,h2:9334,...")."""
        self.masters = [m.strip() for m in master.split(",") if m.strip()]
        self.http_addr = self.masters[0]
        self._keep = keepconnected
        self._lock = threading.Lock()
        self._channels: dict[str, grpc.Channel] = {}
        self._leader = self.masters[0]
        # unary fallback caches (TTL'd)
        self._vid_cache: dict[int, tuple[float, list[pb.Location]]] = {}
        self._ec_cache: dict[int, tuple[float, dict[int, list[pb.Location]]]] = {}
        # stream-fed vid map: authoritative while the session is synced
        self._vidmap: dict[int, dict[str, pb.Location]] = {}
        self._ec_present: dict[int, set[str]] = {}
        self._by_url: dict[str, set[int]] = {}
        self._session_thread: threading.Thread | None = None
        self._synced = threading.Event()
        self._stop = threading.Event()

    @property
    def grpc_addr(self) -> str:
        """gRPC address of the master this client currently considers
        leader (shell/worker open ancillary channels here)."""
        return _grpc_addr(self._leader)

    # ------------------------------------------------------ connections

    def _channel(self, master: str) -> grpc.Channel:
        with self._lock:
            ch = self._channels.get(master)
            if ch is None:
                ch = grpc.insecure_channel(_grpc_addr(master))
                self._channels[master] = ch
            return ch

    def _raft_status(self, master: str) -> pb.RaftStatusResponse | None:
        try:
            return rpc.Stub(self._channel(master), rpc.RAFT_SERVICE).RaftStatus(
                pb.RaftStatusRequest(), timeout=2
            )
        except grpc.RpcError:
            return None

    def _resolve_leader(self, skip: str | None = None) -> str:
        hint: str | None = None
        for m in self.masters:
            if m == skip and len(self.masters) > 1:
                continue
            st = self._raft_status(m)
            if st is None:
                continue
            if st.role == "leader":
                self._leader = m
                return m
            if st.leader and hint is None:
                hint = st.leader
        # a follower's hint may be stale (a dead ex-leader): only trust
        # it if that node itself claims leadership
        if hint and hint != skip:
            st = self._raft_status(hint)
            if st is not None and st.role == "leader":
                self._leader = hint
                return hint
        return self._leader

    def _note_leader_hint(self, error: str) -> bool:
        """Parse 'not leader; leader=X' app errors; True if redirected."""
        if "leader=" in error:
            hint = error.split("leader=", 1)[1].strip()
            if hint:
                self._leader = hint
                return True
        self._resolve_leader(skip=self._leader)
        return True

    def _leader_stub(self):
        return rpc.master_stub(self._channel(self._leader))

    def _with_leader(self, call):
        """Run `call(stub)`; on transport failure or not-leader error,
        re-resolve and retry (unified policy, utils/retry.py)."""
        policy = _LEADER_POLICY

        def on_retry(e: BaseException, attempt: int) -> None:
            # recovery differs by failure class: an app-level not-leader
            # error carries a redirect hint; a transport error means the
            # node itself is sick and must be skipped during re-resolve
            if isinstance(e, NotLeaderError):
                self._note_leader_hint(str(e))
            else:
                self._resolve_leader(skip=self._leader)

        try:
            return retry_call(
                lambda: call(self._leader_stub()),
                policy,
                retry_on=(NotLeaderError, grpc.RpcError),
                on_retry=on_retry,
                describe="master RPC",
            )
        except RetryError as e:
            # run the recovery once more for the FINAL failure too (the
            # old loop did), so the NEXT call doesn't start at a leader
            # we already know is dead
            on_retry(e.__cause__, policy.max_attempts)
            # callers (and tests) expect the underlying grpc/leader
            # error class, not the retry wrapper
            raise e.__cause__ from None

    # ---------------------------------------------------- keepconnected

    def _ensure_session(self) -> None:
        if not self._keep or self._session_thread is not None:
            return
        with self._lock:
            if self._session_thread is not None:
                return
            self._session_thread = threading.Thread(
                target=self._session_loop, daemon=True
            )
            self._session_thread.start()

    def _session_loop(self) -> None:
        client_id = f"wdclient-{uuid.uuid4().hex[:8]}"
        while not self._stop.is_set():
            target = self._leader
            try:
                stream = rpc.master_stub(self._channel(target)).KeepConnected(
                    pb.KeepConnectedRequest(client_id=client_id),
                    timeout=None,
                )
                with self._lock:
                    self._vidmap.clear()
                    self._ec_present.clear()
                    self._by_url.clear()
                for u in stream:
                    if self._stop.is_set():
                        return
                    if u.leader:
                        if u.leader == target:
                            # snapshot-complete marker from the leader
                            self._synced.set()
                            continue
                        self._synced.clear()
                        self._leader = u.leader
                        break
                    self._apply_update(u)
                else:
                    # stream ended without redirect: re-resolve
                    self._synced.clear()
                    self._resolve_leader(skip=target)
            except (grpc.RpcError, ValueError):
                # ValueError = "cannot invoke RPC on closed channel"
                # during close(); RpcError = broken stream
                self._synced.clear()
                if self._stop.is_set():
                    return
                self._resolve_leader(skip=target)
            if self._stop.wait(0.3):
                return

    def _apply_update(self, u: pb.VolumeLocationUpdate) -> None:
        with self._lock:
            if u.server_gone:
                for vid in self._by_url.pop(u.url, set()):
                    held = self._vidmap.get(vid)
                    if held:
                        held.pop(u.url, None)
                        if not held:
                            del self._vidmap[vid]
                    ec = self._ec_present.get(vid)
                    if ec:
                        ec.discard(u.url)
                        if not ec:
                            del self._ec_present[vid]
                return
            loc = pb.Location(
                url=u.url, public_url=u.public_url, grpc_port=u.grpc_port
            )
            held = self._by_url.setdefault(u.url, set())
            for vid in u.new_vids:
                self._vidmap.setdefault(vid, {})[u.url] = loc
                held.add(vid)
            for vid in u.deleted_vids:
                m = self._vidmap.get(vid)
                if m:
                    m.pop(u.url, None)
                    if not m:
                        del self._vidmap[vid]
                held.discard(vid)
            for vid in u.new_ec_vids:
                self._ec_present.setdefault(vid, set()).add(u.url)
                held.add(vid)
            for vid in u.deleted_ec_vids:
                ec = self._ec_present.get(vid)
                if ec:
                    ec.discard(u.url)
                    if not ec:
                        del self._ec_present[vid]

    # ------------------------------------------------------------ assign

    def assign(
        self, count: int = 1, collection: str = "", replication: str = "",
        ttl: str = "", disk_type: str = "",
    ) -> AssignResult:
        self._ensure_session()

        def call(stub):
            resp = stub.Assign(
                pb.AssignRequest(
                    count=count,
                    collection=collection,
                    replication=replication,
                    ttl=ttl,
                    disk_type=disk_type,
                ),
                timeout=30,
            )
            if resp.error:
                if resp.error.startswith("not leader"):
                    raise NotLeaderError(resp.error)
                raise RuntimeError(f"assign: {resp.error}")
            return resp

        resp = self._with_leader(call)
        return AssignResult(
            fid=resp.fid,
            url=resp.location.url,
            public_url=resp.location.public_url,
            grpc_port=resp.location.grpc_port,
            replicas=list(resp.replicas),
            jwt=resp.jwt,
        )

    # ------------------------------------------------------------ lookup

    def lookup(self, vid: int, refresh: bool = False) -> list[pb.Location]:
        self._ensure_session()
        if self._synced.is_set() and not refresh:
            with self._lock:
                held = self._vidmap.get(vid)
                if held:
                    return list(held.values())
            # fall through: a just-grown volume's delta may not have
            # arrived yet — ask the master directly
        now = time.time()
        with self._lock:
            hit = self._vid_cache.get(vid)
            if hit and not refresh and now - hit[0] < _CACHE_TTL:
                return hit[1]

        def call(stub):
            resp = stub.LookupVolume(
                pb.LookupVolumeRequest(volume_ids=[vid]), timeout=30
            )
            vl = resp.volume_locations[0]
            if vl.error:
                if vl.error.startswith("not leader"):
                    raise NotLeaderError(vl.error)
                raise LookupError(vl.error)
            return list(vl.locations)

        locs = self._with_leader(call)
        with self._lock:
            self._vid_cache[vid] = (now, locs)
        return locs

    def lookup_ec(self, vid: int, refresh: bool = False) -> dict[int, list[pb.Location]]:
        now = time.time()
        with self._lock:
            hit = self._ec_cache.get(vid)
            if hit and not refresh and now - hit[0] < _CACHE_TTL:
                return hit[1]

        def call(stub):
            resp = stub.LookupEcVolume(
                pb.LookupEcVolumeRequest(volume_id=vid), timeout=30
            )
            if resp.error:
                if resp.error.startswith("not leader"):
                    raise NotLeaderError(resp.error)
                raise LookupError(resp.error)
            return {sl.shard_id: list(sl.locations) for sl in resp.shard_locations}

        out = self._with_leader(call)
        with self._lock:
            self._ec_cache[vid] = (now, out)
        return out

    # ------------------------------------------------------------- misc

    def topology(self) -> pb.TopologyResponse:
        return self._with_leader(
            lambda s: s.Topology(pb.TopologyRequest(), timeout=30)
        )

    def statistics(self) -> pb.StatisticsResponse:
        return self._with_leader(
            lambda s: s.Statistics(pb.StatisticsRequest(), timeout=30)
        )

    def raft_status(self) -> pb.RaftStatusResponse:
        """Status of the master this client considers leader."""
        return rpc.Stub(self._channel(self._leader), rpc.RAFT_SERVICE).RaftStatus(
            pb.RaftStatusRequest(), timeout=5
        )

    def grow(self, count: int = 1, collection: str = "", replication: str = "") -> list[int]:
        resp = self._with_leader(
            lambda s: s.VolumeGrow(
                pb.VolumeGrowRequest(
                    count=count, collection=collection, replication=replication
                ),
                timeout=60,
            )
        )
        return list(resp.volume_ids)

    def collections(self) -> list[str]:
        return list(
            self._with_leader(
                lambda s: s.CollectionList(pb.CollectionListRequest(), timeout=30)
            ).collections
        )

    def collection_delete(self, name: str) -> list[int]:
        """Drop every volume of a collection (fast bucket delete)."""

        def call(stub):
            resp = stub.CollectionDelete(
                pb.CollectionDeleteRequest(name=name), timeout=120
            )
            if resp.error.startswith("not leader"):
                raise NotLeaderError(resp.error)
            return resp

        resp = self._with_leader(call)
        if resp.error:
            raise RuntimeError(resp.error)
        return list(resp.deleted_volume_ids)

    # ------------------------------------------------------------- locks

    def lock(
        self, name: str, owner: str, ttl: float = 60.0, token: str = "",
        wait: float = 0.0,
    ) -> str:
        """Acquire (or renew with `token`) the named cluster lease;
        returns the token. Waits up to `wait` seconds for a busy lock.
        Raises LockHeldError when it stays held."""
        def call(stub):
            resp = stub.AdminLock(
                pb.LockRequest(
                    name=name, owner=owner, ttl_seconds=ttl, token=token
                ),
                timeout=10,
            )
            if resp.error.startswith("not leader"):
                raise NotLeaderError(resp.error)
            return resp

        def attempt() -> str:
            resp = self._with_leader(call)
            if not resp.ok:
                raise LockHeldError(name, resp.holder)
            return resp.token

        if wait <= 0:
            return attempt()
        # busy-lock polling rides the unified policy: short flat-ish
        # delays (a lease can free at any moment), total budget = wait
        policy = RetryPolicy(
            max_attempts=max(2, int(wait / 0.05) + 1),
            base_delay=0.05, max_delay=0.2, multiplier=1.5, jitter=0.2,
            deadline=wait,
        )
        try:
            return retry_call(
                attempt, policy, retry_on=(LockHeldError,),
                describe=f"lock {name!r}",
            )
        except RetryError as e:
            raise e.__cause__ from None

    def unlock(self, name: str, token: str) -> bool:
        def call(stub):
            resp = stub.AdminUnlock(
                pb.UnlockRequest(name=name, token=token), timeout=10
            )
            if resp.error.startswith("not leader"):
                raise NotLeaderError(resp.error)
            return resp

        try:
            return self._with_leader(call).ok
        except grpc.RpcError:
            return False  # lease expiry cleans up regardless

    def lock_status(self) -> list[tuple[str, str, float]]:
        resp = self._with_leader(
            lambda s: s.AdminLockStatus(pb.LockStatusRequest(), timeout=10)
        )
        return [(r.name, r.owner, r.expires_ns / 1e9) for r in resp.locks]

    def close(self) -> None:
        self._stop.set()
        # break any blocking stream first so the session thread exits,
        # THEN clear the dict — otherwise the loop can re-create
        # channels after close and leak them
        with self._lock:
            for ch in self._channels.values():
                ch.close()
        t = self._session_thread
        if t is not None:
            t.join(timeout=2)
        with self._lock:
            for ch in self._channels.values():
                ch.close()
            self._channels.clear()


class NotLeaderError(Exception):
    pass


class LockHeldError(Exception):
    def __init__(self, name: str, holder: str):
        super().__init__(f"cluster lock {name!r} is held by {holder}")
        self.name = name
        self.holder = holder


def volume_channel(loc: pb.Location) -> grpc.Channel:
    host = loc.url.split(":")[0]
    return grpc.insecure_channel(f"{host}:{loc.grpc_port}")
