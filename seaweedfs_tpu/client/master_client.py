"""Client-side master session: assign/lookup with a vid-location cache.

Reference: weed/wdclient (MasterClient masterclient.go:483, vidMap
vid_map.go:35) + weed/operation (assign_file_id.go:43).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass

import grpc

from ..pb import cluster_pb2 as pb
from ..pb import rpc

_CACHE_TTL = 10.0


@dataclass
class AssignResult:
    fid: str
    url: str
    public_url: str
    grpc_port: int
    replicas: list
    jwt: str = ""


class MasterClient:
    def __init__(self, master: str = "localhost:9333"):
        host, _, port = master.partition(":")
        self.http_addr = master
        self.grpc_addr = f"{host}:{int(port) + 10000}"
        self._channel = grpc.insecure_channel(self.grpc_addr)
        self._stub = rpc.master_stub(self._channel)
        self._lock = threading.Lock()
        self._vid_cache: dict[int, tuple[float, list[pb.Location]]] = {}
        self._ec_cache: dict[int, tuple[float, dict[int, list[pb.Location]]]] = {}

    def assign(
        self, count: int = 1, collection: str = "", replication: str = "",
        ttl: str = "",
    ) -> AssignResult:
        resp = self._stub.Assign(
            pb.AssignRequest(
                count=count, collection=collection, replication=replication,
                ttl=ttl,
            ),
            timeout=30,
        )
        if resp.error:
            raise RuntimeError(f"assign: {resp.error}")
        return AssignResult(
            fid=resp.fid,
            url=resp.location.url,
            public_url=resp.location.public_url,
            grpc_port=resp.location.grpc_port,
            replicas=list(resp.replicas),
            jwt=resp.jwt,
        )

    def lookup(self, vid: int, refresh: bool = False) -> list[pb.Location]:
        now = time.time()
        with self._lock:
            hit = self._vid_cache.get(vid)
            if hit and not refresh and now - hit[0] < _CACHE_TTL:
                return hit[1]
        resp = self._stub.LookupVolume(
            pb.LookupVolumeRequest(volume_ids=[vid]), timeout=30
        )
        vl = resp.volume_locations[0]
        if vl.error:
            raise LookupError(vl.error)
        locs = list(vl.locations)
        with self._lock:
            self._vid_cache[vid] = (now, locs)
        return locs

    def lookup_ec(self, vid: int, refresh: bool = False) -> dict[int, list[pb.Location]]:
        now = time.time()
        with self._lock:
            hit = self._ec_cache.get(vid)
            if hit and not refresh and now - hit[0] < _CACHE_TTL:
                return hit[1]
        resp = self._stub.LookupEcVolume(
            pb.LookupEcVolumeRequest(volume_id=vid), timeout=30
        )
        if resp.error:
            raise LookupError(resp.error)
        out = {sl.shard_id: list(sl.locations) for sl in resp.shard_locations}
        with self._lock:
            self._ec_cache[vid] = (now, out)
        return out

    def topology(self) -> pb.TopologyResponse:
        return self._stub.Topology(pb.TopologyRequest(), timeout=30)

    def statistics(self) -> pb.StatisticsResponse:
        return self._stub.Statistics(pb.StatisticsRequest(), timeout=30)

    def grow(self, count: int = 1, collection: str = "", replication: str = "") -> list[int]:
        resp = self._stub.VolumeGrow(
            pb.VolumeGrowRequest(
                count=count, collection=collection, replication=replication
            ),
            timeout=60,
        )
        return list(resp.volume_ids)

    def collections(self) -> list[str]:
        return list(
            self._stub.CollectionList(pb.CollectionListRequest(), timeout=30).collections
        )

    def collection_delete(self, name: str) -> list[int]:
        """Drop every volume of a collection (fast bucket delete)."""
        resp = self._stub.CollectionDelete(
            pb.CollectionDeleteRequest(name=name), timeout=120
        )
        if resp.error:
            raise RuntimeError(resp.error)
        return list(resp.deleted_volume_ids)

    def close(self) -> None:
        self._channel.close()


def volume_channel(loc: pb.Location) -> grpc.Channel:
    host = loc.url.split(":")[0]
    return grpc.insecure_channel(f"{host}:{loc.grpc_port}")
