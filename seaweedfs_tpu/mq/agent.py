"""MQ agent: a session facade in front of the broker group.

Reference: weed/mq/agent (agent_server.go, agent_grpc_publish.go,
agent_grpc_subscribe.go) — thin clients start a publish session, stream
records, and stream subscriptions WITHOUT carrying broker-balancing or
topic-configuration logic themselves; the agent owns the broker
connection.

Sessions auto-configure the topic at StartPublishSession (like the
reference's schema registration step); the publish stream acks every
record with its assigned offset; the subscribe stream replays from the
requested (or committed-group) offset and commits cumulative acks back
to the broker's offset store.
"""

from __future__ import annotations

import threading
import time
from concurrent import futures

import grpc

from ..pb import mq_pb2 as mq
from ..pb import rpc
from ..utils.glog import logger
from .client import MqClient

log = logger("mqagent")


class MqAgentService:
    def __init__(self, broker_addr: str):
        self.broker_addr = broker_addr
        self._client = MqClient(broker_addr)
        self._lock = threading.Lock()
        self._sessions: dict[int, tuple[str, str]] = {}  # id -> (ns, name)
        self._next_session = int(time.time()) << 16

    def _session(self, sid: int) -> tuple[str, str]:
        with self._lock:
            got = self._sessions.get(sid)
        if got is None:
            raise KeyError(sid)
        return got

    # ----------------------------------------------------------- publish

    def StartPublishSession(self, request, context):
        ns = request.ns or "default"
        try:
            self._client.configure_topic(
                request.name,
                partitions=max(request.partition_count, 1),
                namespace=ns,
            )
        except grpc.RpcError as e:
            return mq.AgentStartPublishResponse(error=e.details() or str(e))
        with self._lock:
            self._next_session += 1
            sid = self._next_session
            self._sessions[sid] = (ns, request.name)
        log.v(
            1,
            f"publish session {sid} -> {ns}/{request.name} "
            f"({request.publisher_name or 'anonymous'})",
        )
        return mq.AgentStartPublishResponse(session_id=sid)

    def ClosePublishSession(self, request, context):
        with self._lock:
            gone = self._sessions.pop(request.session_id, None)
        if gone is None:
            return mq.AgentClosePublishResponse(error="unknown session")
        return mq.AgentClosePublishResponse()

    def PublishRecord(self, request_iterator, context):
        """BIDI: each request publishes one record; each response acks
        with the assigned offset. The session id rides the FIRST
        message (later ones may omit it, like the reference)."""
        sid = 0
        seq = 0
        for req in request_iterator:
            seq += 1
            if req.session_id:
                sid = req.session_id
            try:
                ns, name = self._session(sid)
            except KeyError:
                yield mq.AgentPublishResponse(
                    ack_sequence=seq, error=f"unknown session {sid}"
                )
                return
            try:
                _part, off = self._client.publish(
                    name, bytes(req.value), key=bytes(req.key), namespace=ns
                )
            except (RuntimeError, grpc.RpcError) as e:
                yield mq.AgentPublishResponse(
                    ack_sequence=seq, error=str(e)
                )
                continue
            yield mq.AgentPublishResponse(ack_sequence=seq, offset=off)

    # --------------------------------------------------------- subscribe

    def SubscribeRecord(self, request_iterator, context):
        """BIDI: first message carries init; later messages carry
        cumulative acks which commit the group offset."""
        first = next(request_iterator, None)
        if first is None or not first.init.name:
            yield mq.AgentSubscribeResponse(
                error="first message must carry init", is_end_of_stream=True
            )
            return
        init = first.init
        ns = init.ns or "default"
        group = init.consumer_group

        reqs_done = threading.Event()

        def ack_pump():
            # acks commit the furthest offset the consumer has durably
            # handled — the agent owns the CommitOffset calls. The
            # request stream ENDING is a normal half-close (ack-less
            # consumers send only init), NOT a reason to stop records.
            try:
                for req in request_iterator:
                    # proto3 int64 has no presence: 0 means "no ack in
                    # this message" (committing 0 would REGRESS the
                    # group to the beginning)
                    if group and req.ack_sequence > 0:
                        self._client.commit(
                            init.name,
                            init.partition,
                            group,
                            int(req.ack_sequence),
                            namespace=ns,
                        )
            except (grpc.RpcError, RuntimeError):
                pass
            finally:
                reqs_done.set()

        threading.Thread(target=ack_pump, daemon=True).start()
        try:
            for rec in self._client.subscribe(
                init.name,
                init.partition,
                start_offset=init.start_offset,
                namespace=ns,
                consumer_group=group,
                follow=init.follow,
            ):
                if not context.is_active():
                    return  # client disconnected
                yield mq.AgentSubscribeResponse(
                    key=rec.message.key,
                    value=rec.message.value,
                    ts_ns=rec.message.ts_ns,
                    offset=rec.offset,
                )
        except grpc.RpcError as e:
            yield mq.AgentSubscribeResponse(
                error=e.details() or str(e), is_end_of_stream=True
            )
            return
        yield mq.AgentSubscribeResponse(is_end_of_stream=True)
        # Grace for the FINAL cumulative ack: the client typically acks
        # after the end marker, then half-closes; returning immediately
        # would cancel the RPC and discard that ack mid-flight. The
        # grace must be LOAD-TOLERANT: under a loaded host the client's
        # ack + half-close and the pump's CommitOffset RPC can take
        # well over the old fixed 2 s, and an expired grace silently
        # dropped the committed offset ("ack never committed" flake).
        # reqs_done is set the moment the pump drains the half-closed
        # request stream (ack-less consumers half-close immediately, so
        # the common case returns without waiting), and a DISCONNECTED
        # client stops the wait early — only a consumer that keeps its
        # request stream open without acking pays the full grace.
        deadline = time.monotonic() + 30.0
        while not reqs_done.wait(0.25):
            if time.monotonic() > deadline or not context.is_active():
                break

    def close(self) -> None:
        self._client.close()


class MqAgentServer:
    """Standalone agent process: gRPC server fronting one broker
    (group)."""

    def __init__(self, broker: str, ip: str = "localhost", port: int = 0):
        self.service = MqAgentService(broker)
        self._grpc = grpc.server(futures.ThreadPoolExecutor(max_workers=16))
        rpc.add_service(self._grpc, rpc.MQ_AGENT_SERVICE, self.service)
        self.port = self._grpc.add_insecure_port(f"{ip}:{port}")
        self.ip = ip

    def start(self) -> None:
        self._grpc.start()

    def stop(self) -> None:
        self._grpc.stop(grace=1)
        self.service.close()
