"""Durable-parity MQ log segments: the broker side of streaming EC.

A topic configured with ``durable_parity`` feeds every appended record
(the same `[len|offset|ts|key|value]` wire bytes the segment files use,
`mq/log_buffer.py`) into an :class:`~seaweedfs_tpu.ec.stream_encode.
EcStreamEncoder` per partition, so parity trails the append head by a
bounded lag (the flusher's bytes/deadline policy) instead of waiting
for segment seal. On a crash, the unsealed tail — records the filer
segments never saw — is replayed from the EC stream: the stripe-cursor
journal fences what was durable, a dense-offset frame scan finds the
true head, and parity that disagrees with the data is re-derived before
anything is published (see `ec/stream_encode.recover_stream`).

Stream generations: one encoder writes one `gen-%08d` directory in the
LARGE-stripe layout (never finalized — recoverability is the point);
when a generation reaches ``rotate_bytes`` it is flushed, closed, and a
fresh one started at the current record offset. Generations entirely
below the prune floor (records already durable in filer segments, or
fallen out of a memory-only broker's bounded tail) are deleted.
"""

from __future__ import annotations

import os
import shutil
import struct
import threading
import time

from ..ec.context import ECContext, ECError
from ..ec.stream_encode import (
    EcStreamEncoder,
    load_stream_journal,
    recover_stream,
    stream_block_size,
    stream_small_block_size,
)
from ..utils.glog import logger
from .log_buffer import _REC, encode_record

log = logger("mq.parity")

GEN_PREFIX = "gen-"


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, str(default)))
    except ValueError:
        return default


def flush_bytes_default() -> int:
    """SEAWEED_EC_STREAM_FLUSH_KB: pending bytes that trigger a parity
    flush ahead of the lag deadline (default 256 KiB)."""
    return max(_env_int("SEAWEED_EC_STREAM_FLUSH_KB", 256), 1) << 10


def max_lag_s_default() -> float:
    """SEAWEED_EC_STREAM_MAX_LAG_MS: the bounded parity lag — no
    appended record waits longer than this for durable parity while the
    flusher runs (default 200 ms)."""
    return max(_env_int("SEAWEED_EC_STREAM_MAX_LAG_MS", 200), 1) / 1000.0


def rotate_bytes_default() -> int:
    """SEAWEED_EC_STREAM_ROTATE_MB: stream-generation rotation size
    (default 64 MiB) — bounds recovery work and prune granularity."""
    return max(_env_int("SEAWEED_EC_STREAM_ROTATE_MB", 64), 1) << 20


def remote_roots() -> dict[str, str]:
    """SEAWEED_EC_STREAM_REMOTE_ROOTS ("name=/path[,name=/path...]"):
    remote-host roots that a durable-parity partition's stream SHARDS
    may be placed on, spread by the same `plan_shard_placement` scoring
    the cluster uses. Two root forms:

    - ``name=/path`` — a MOUNTED path (NFS/bind mount of another
      host's disk): the planned shard becomes a symlink the encoder's
      O_CREAT follows, headroom-gated by statvfs.
    - ``name=net:host:grpcport[/subdir]`` — a volume server's native
      write plane (ISSUE 18), replacing the shared-mount assumption:
      the shard stays a local file and every flush PUSHES its newly-
      durable extent over the plane's kind=blob opcode
      (``write_blob``, fsync-before-ACK), landing under the peer's
      stream-blob root. Pruned generations unlink their remote blobs.

    Unset (the default) keeps every shard in the local parity dir.
    Losing the local host then still leaves the remotely-placed shards
    of every unsealed tail recoverable — the scoped ISSUE 14 carry."""
    spec = os.environ.get("SEAWEED_EC_STREAM_REMOTE_ROOTS", "")
    roots: dict[str, str] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part or "=" not in part:
            continue
        name, _, path = part.partition("=")
        if name.strip() and path.strip():
            roots[name.strip()] = path.strip()
    return roots


def _statvfs_free(path: str) -> int:
    try:
        st = os.statvfs(path)
        return int(st.f_bavail) * int(st.f_frsize)
    except OSError:
        return -1


def _parse_net_root(spec: str):
    """``net:host:grpcport[/subdir]`` -> ((host, plane_port), subdir).
    Raises ValueError on a malformed spec."""
    from ..ec.net_plane import derive_port

    rest = spec[len("net:"):]
    hostport, _, sub = rest.partition("/")
    host, _, port = hostport.rpartition(":")
    if not host or not port:
        raise ValueError(f"malformed net root {spec!r}")
    return (host, derive_port(int(port))), sub.strip("/")


_NET_CLIENT = None
_NET_CLIENT_LOCK = threading.Lock()


def _net_client():
    """Lazy shared NetPlaneClient for net: shard pushes — pooled
    connections to peer sidecars, shared by every partition."""
    global _NET_CLIENT
    with _NET_CLIENT_LOCK:
        if _NET_CLIENT is None:
            from ..ec.net_plane import NetPlaneClient

            _NET_CLIENT = NetPlaneClient()
        return _NET_CLIENT


def _net_jwt() -> str:
    """Blob-scoped token for keyed clusters (SEAWEED_JWT_KEY): the
    receiving volume server's blob resolver verifies scope "blob"."""
    key = os.environ.get("SEAWEED_JWT_KEY", "")
    if not key:
        return ""
    from ..utils.security import sign_jwt

    return sign_jwt(key, "blob", ttl_seconds=60)


def parity_context() -> ECContext:
    """SEAWEED_EC_STREAM_SHARDS ("k+m", default 4+2): the EC geometry
    for broker log streams — smaller k than volume EC keeps the stripe
    (k x block) and therefore the seal cadence small."""
    spec = os.environ.get("SEAWEED_EC_STREAM_SHARDS", "4+2")
    try:
        k_s, m_s = spec.split("+", 1)
        return ECContext(int(k_s), int(m_s))
    except (ValueError, ECError):
        log.warning("bad SEAWEED_EC_STREAM_SHARDS %r; using 4+2", spec)
        return ECContext(4, 2)


def _iter_dense(raw: bytes, base_offset: int):
    """THE dense-frame parser (one acceptance rule for scan AND
    decode): yield (end_pos, offset, ts_ns, key, value) for the
    longest prefix of COMPLETE record frames whose offsets are dense
    from `base_offset`. A torn tail write fails the frame bound or the
    density check and everything after it is excluded."""
    pos = 0
    want = base_offset
    n = len(raw)
    while pos + _REC.size <= n:
        body_len, offset, ts_ns, key_len = _REC.unpack_from(raw, pos)
        end = pos + 4 + body_len
        if end > n or body_len < _REC.size - 4 + key_len:
            return
        if offset != want:
            return
        p = pos + _REC.size
        yield end, offset, ts_ns, raw[p : p + key_len], raw[p + key_len : end]
        want += 1
        pos = end


def dense_frame_scan(base_offset: int):
    """frame_scan for `recover_stream`: the byte length of the dense
    record prefix — everything past it is rolled back."""

    def scan(raw: bytes) -> int:
        pos = 0
        for end, *_rec in _iter_dense(raw, base_offset):
            pos = end
        return pos

    return scan


def decode_dense(raw: bytes, base_offset: int):
    """Yield (offset, ts_ns, key, value) for the dense prefix (the
    SAME parser `dense_frame_scan` measures with)."""
    for _end, off, ts_ns, key, value in _iter_dense(raw, base_offset):
        yield off, ts_ns, key, value


class PartitionParity:
    """One partition's durable-parity stream (rotating generations)."""

    def __init__(
        self,
        root: str,
        ns: str,
        name: str,
        partition: int,
        ctx: ECContext | None = None,
        backend=None,
        scheduler=None,
        block_size: int | None = None,
        small_block_size: int | None = None,
        flush_bytes: int | None = None,
        max_lag_s: float | None = None,
        rotate_bytes: int | None = None,
    ):
        self.ns, self.topic_name, self.partition = ns, name, partition
        self.dir = os.path.join(root, ns, name, f"{partition:04d}")
        os.makedirs(self.dir, exist_ok=True)
        # env-gated remote shard placement (see remote_roots): snapshot
        # at construction so one partition's gens place consistently
        self.remote_roots = remote_roots()
        self.ctx = ctx or parity_context()
        self.backend = backend
        self.scheduler = scheduler
        self.block_size = int(block_size or stream_block_size())
        # tail blocks can never exceed the stripe row block
        self.small_block_size = min(
            int(small_block_size or stream_small_block_size()),
            self.block_size,
        )
        self.flush_bytes = int(flush_bytes or flush_bytes_default())
        self.max_lag_s = float(max_lag_s or max_lag_s_default())
        self.rotate_bytes = int(rotate_bytes or rotate_bytes_default())
        self._lock = threading.RLock()
        self._enc: EcStreamEncoder | None = None
        self._gen = self._max_gen() + 1
        self._gen_base = -1  # first record offset of the open gen
        self.closed = False
        # net: roots (write-plane pushed shards): per-gen plan of
        # local shard path -> ((host, port), remote path), and the
        # per-path byte watermark already pushed+fsynced remotely
        self._net_shards: dict[int, dict[str, tuple]] = {}
        self._net_pushed: dict[str, int] = {}

    # --------------------------------------------------------- gen layout

    def _gen_base_path(self, gen: int) -> str:
        return os.path.join(self.dir, f"{GEN_PREFIX}{gen:08d}")

    def _gens(self) -> list[int]:
        out = []
        try:
            names = os.listdir(self.dir)
        except OSError:
            return out
        for n in names:
            if n.startswith(GEN_PREFIX):
                stem = n[len(GEN_PREFIX) :].split(".", 1)[0]
                try:
                    out.append(int(stem))
                except ValueError:
                    continue
        return sorted(set(out))

    def _max_gen(self) -> int:
        gens = self._gens()
        return gens[-1] if gens else -1

    def _backend_resolved(self):
        if self.backend is None:
            from ..ec.backend import get_backend

            name = os.environ.get("SEAWEED_EC_STREAM_BACKEND", "auto")
            self.backend = get_backend(
                name, self.ctx.data_shards, self.ctx.parity_shards
            )
        return self.backend

    # ------------------------------------------------------------ append

    def append_record(
        self, offset: int, ts_ns: int, key: bytes, value: bytes
    ) -> None:
        """Feed one appended record's wire bytes to the open stream.
        Called under the partition lock: buffering only — the parity
        math and fsync run on the flusher's schedule, outside both
        this lock and the encoder's buffer lock. Exception: the FIRST
        record of a generation pays the stream construction (shard
        file opens + placement + initial journal) inline — once per
        rotation, not per record."""
        with self._lock:
            if self.closed:
                return
            if self._enc is None:
                self._open_gen(offset)
            elif offset != self._gen_base + self._gen_records:
                # non-dense feed (e.g. a replayed follower gap): the
                # stream's recovery contract is dense offsets, so cut a
                # fresh generation at the new base
                self._rotate_locked(offset)
            self._enc.append(encode_record(offset, ts_ns, key, value))
            self._gen_records += 1

    def _open_gen(self, base_offset: int) -> None:
        self._gen_base = base_offset
        self._gen_records = 0
        self._place_gen_shards(self._gen_base_path(self._gen))
        self._enc = EcStreamEncoder(
            self._gen_base_path(self._gen),
            self.ctx,
            backend=self._backend_resolved(),
            block_size=self.block_size,
            small_block_size=self.small_block_size,
            scheduler=self.scheduler,
            meta=base_offset,
        )

    def _place_gen_shards(self, base: str) -> None:
        """Plan this generation's shard files across the local parity
        dir and the configured remote roots with the SAME scoring the
        cluster's shard placement uses (`plan_shard_placement`:
        spread-by-count, headroom-gated) — a shard planned remote
        becomes a symlink the encoder's O_CREAT follows, so the
        encoder/recovery byte paths are untouched. No roots configured
        (the default) = no-op; a root without headroom for its share of
        `rotate_bytes` is never chosen. Idempotent: existing links/
        files are left alone (re-opening a gen after recovery must not
        re-home bytes)."""
        if not self.remote_roots:
            return
        from ..ec.placement import NodeView, plan_shard_placement

        views = [
            NodeView(
                id="", free_slots=1 << 20,
                free_bytes=_statvfs_free(self.dir),
            )
        ]
        targets: dict[str, str] = {}
        net_targets: dict[str, tuple] = {}  # name -> (addr, remote dir)
        for name, root in sorted(self.remote_roots.items()):
            if root.startswith("net:"):
                # write-plane push target: no mount to probe, headroom
                # unknowable here — the peer refuses when full
                try:
                    addr, sub = _parse_net_root(root)
                except ValueError as e:
                    log.warning("remote parity root %s unusable: %s", root, e)
                    continue
                rdir = "/".join(
                    p for p in (
                        sub, self.ns, self.topic_name,
                        f"{self.partition:04d}",
                    ) if p
                )
                net_targets[name] = (addr, rdir)
                views.append(
                    NodeView(id=name, free_slots=1 << 20, free_bytes=1 << 50)
                )
                continue
            # absolute: the symlink target must resolve the same from
            # the parity dir (link resolution) and from the process cwd
            # (makedirs/prune) — a relative root would split the two
            tdir = os.path.abspath(
                os.path.join(
                    root, self.ns, self.topic_name, f"{self.partition:04d}"
                )
            )
            try:
                os.makedirs(tdir, exist_ok=True)
            except OSError as e:
                log.warning("remote parity root %s unusable: %s", root, e)
                continue
            targets[name] = tdir
            views.append(
                NodeView(
                    id=name, free_slots=1 << 20,
                    free_bytes=_statvfs_free(tdir),
                )
            )
        if len(views) < 2:
            return
        shard_b = max(self.rotate_bytes // self.ctx.data_shards, 1)
        plan = plan_shard_placement(
            views, self._gen, list(range(self.ctx.total)),
            shard_bytes=shard_b,
        )
        net_plan: dict[str, tuple] = {}
        for sid, node_id in sorted(plan.items()):
            if not node_id:
                continue  # planned local: a plain file
            path = base + self.ctx.to_ext(sid)
            if node_id in net_targets:
                # stays a local file the encoder appends to; flushes
                # push its durable extents over the write plane
                addr, rdir = net_targets[node_id]
                net_plan[path] = (
                    addr, "/".join((rdir, os.path.basename(path))),
                )
                continue
            if os.path.lexists(path):
                continue
            target = os.path.join(targets[node_id], os.path.basename(path))
            try:
                os.symlink(target, path)
            except OSError as e:
                log.warning(
                    "remote shard link %s -> %s failed: %s (local file "
                    "instead)", path, target, e,
                )
        if net_plan:
            self._net_shards[self._gen] = net_plan

    # one kind=blob write per extent chunk: bounds the peer's pooled
    # landing buffer and keeps a slow peer from stalling flush forever
    _NET_PUSH_CHUNK = 4 << 20

    def _push_net_shards(self) -> None:
        """Push every net-planned shard's newly-durable extent
        [watermark, size) over the write plane (kind=blob,
        fsync-before-ACK): once this returns, the pushed bytes are
        durable ON THE PEER. Best-effort: a failed push keeps the
        watermark so the next flush retries from the same offset; the
        local shard file remains authoritative either way."""
        with self._lock:
            work = [
                (path, addr, rpath)
                for plan in self._net_shards.values()
                for path, (addr, rpath) in sorted(plan.items())
            ]
        if not work:
            return
        from ..ec.net_plane import NetPlaneError, NetPlaneUnavailable

        jwt = _net_jwt()
        client = _net_client()
        for path, addr, rpath in work:
            try:
                size = os.path.getsize(path)
            except OSError:
                continue
            off = self._net_pushed.get(path, 0)
            if off >= size:
                continue
            try:
                with open(path, "rb") as f:
                    while off < size:
                        f.seek(off)
                        data = f.read(min(size - off, self._NET_PUSH_CHUNK))
                        if not data:
                            break
                        client.write_blob(
                            addr, rpath, off, data, fsync=True, jwt=jwt
                        )
                        off += len(data)
            except (NetPlaneUnavailable, NetPlaneError, OSError) as e:
                log.warning(
                    "net shard push %s -> %s stalled at %d: %s",
                    path, rpath, off, e,
                )
            self._net_pushed[path] = off

    def _rotate_locked(self, next_base: int) -> None:
        if self._enc is not None:
            self._enc.close(finalize=False)
            self._enc = None
        self._gen += 1
        self._open_gen(next_base)

    # ------------------------------------------------------------- flush

    def pending_bytes(self) -> int:
        with self._lock:
            return self._enc.pending_bytes if self._enc else 0

    def parity_lag_s(self) -> float:
        with self._lock:
            return self._enc.parity_lag_s() if self._enc else 0.0

    def needs_flush(self) -> bool:
        with self._lock:
            if self._enc is None:
                return False
            if self._enc.pending_bytes >= self.flush_bytes:
                return True
            return (
                self._enc.pending_bytes > 0
                and self._enc.parity_lag_s() >= self.max_lag_s
            )

    def flush(self) -> None:
        # The slow half (parity math + fsync) runs OUTSIDE this
        # object's lock: append_record holds the partition lock when it
        # lands here, so holding _lock through enc.flush() would stall
        # every publish on the partition behind the fsync. The encoder
        # itself serializes flush vs flush; appends ride its separate
        # buffer lock.
        with self._lock:
            enc = self._enc
        if enc is None:
            return
        enc.flush()
        # remote durability trails local: net-planned shards push their
        # newly-flushed extents before this flush returns
        self._push_net_shards()
        with self._lock:
            if self._enc is enc and enc.head >= self.rotate_bytes:
                # rotate at a flush boundary so the closed gen's
                # journal covers its whole extent; the next gen opens
                # lazily at the next appended record's offset. Appends
                # that raced in since the flush above land in the
                # CLOSING generation — close() flushes them, so
                # nothing is lost, but a generation may exceed
                # rotate_bytes by whatever arrived during one flush.
                self._enc.close(finalize=False)
                self._enc = None
                self._gen += 1

    def prune(self, keep_from_offset: int) -> int:
        """Delete closed generations whose records are ALL below
        `keep_from_offset` (already durable elsewhere / out of the
        retention window). A gen's coverage ends where the next gen
        begins (its journal `meta`)."""
        removed = 0
        with self._lock:
            gens = self._gens()
            open_gen = self._gen if self._enc is not None else None
            for g, nxt in zip(gens, gens[1:]):
                if g == open_gen:
                    continue
                nj = load_stream_journal(self._gen_base_path(nxt))
                if nj is None or nj.meta > keep_from_offset:
                    break
                self._remove_gen(g)
                removed += 1
        return removed

    def _remove_gen(self, gen: int) -> None:
        base = self._gen_base_path(gen)
        net_plan = self._net_shards.pop(gen, None) or {}
        for path in net_plan:
            self._net_pushed.pop(path, None)
        targets = set(net_plan.values())
        # a restarted partition has no in-memory plan for pre-restart
        # gens: derive every possible remote blob path from the net:
        # roots config so pruning never leaks peer bytes
        for _name, root in sorted(self.remote_roots.items()):
            if not root.startswith("net:"):
                continue
            try:
                addr, sub = _parse_net_root(root)
            except ValueError:
                continue
            rdir = "/".join(
                p for p in (
                    sub, self.ns, self.topic_name, f"{self.partition:04d}"
                ) if p
            )
            for i in range(self.ctx.total):
                bn = os.path.basename(base + self.ctx.to_ext(i))
                targets.add((addr, rdir + "/" + bn))
        if targets:
            from ..ec.net_plane import NetPlaneError, NetPlaneUnavailable

            jwt = _net_jwt()
            client = _net_client()
            for addr, rpath in sorted(targets):
                try:
                    client.unlink_blob(addr, rpath, jwt=jwt)
                except (NetPlaneUnavailable, NetPlaneError, OSError):
                    pass  # orphaned remote blob: GC'd with the root
        for i in range(self.ctx.total):
            path = base + self.ctx.to_ext(i)
            try:
                # remotely-placed shard: drop the TARGET bytes too, or
                # pruning would orphan them on the remote root forever
                if os.path.islink(path):
                    _unlink_quiet(os.readlink(path))
            except OSError:
                pass
            _unlink_quiet(path)
        _unlink_quiet(base + ".stream")
        _unlink_quiet(base + ".ecsum")

    # ---------------------------------------------------------- recovery

    def recover(self) -> list[tuple[int, int, bytes, bytes]]:
        """Replay every recoverable record from the on-disk stream
        generations, in offset order, verifying/repairing parity as it
        goes. Leaves the partition on a FRESH generation (recovered
        records re-enter the live stream as the broker re-appends
        them); old generations stay until pruned."""
        records: list[tuple[int, int, bytes, bytes]] = []
        backend = self._backend_resolved()
        with self._lock:
            for g in self._gens():
                base = self._gen_base_path(g)
                j = load_stream_journal(base)
                if j is None:
                    continue
                rec = recover_stream(
                    base, self.ctx, backend,
                    frame_scan=dense_frame_scan(j.meta),
                )
                if rec is None:
                    continue
                for r in decode_dense(rec.data, j.meta):
                    records.append(r)
            self._gen = self._max_gen() + 1
        records.sort(key=lambda r: r[0])
        # enforce global density across gens: a hole (unrecoverable
        # gen) ends the replay — the log cannot skip offsets
        dense: list[tuple[int, int, bytes, bytes]] = []
        for r in records:
            if dense and r[0] > dense[-1][0] + 1:
                break
            if dense and r[0] <= dense[-1][0]:
                continue
            dense.append(r)
        return dense

    # ------------------------------------------------------------- close

    def close(self) -> None:
        with self._lock:
            if self.closed:
                return
            self.closed = True
            if self._enc is not None:
                self._enc.close(finalize=False)
                self._enc = None
        # final tail extents (bytes the closing flush landed locally)
        self._push_net_shards()

    def delete(self) -> None:
        self.close()
        # remote-placed shard targets die with their gens; rmtree alone
        # would only remove the symlinks
        for g in self._gens():
            self._remove_gen(g)
        shutil.rmtree(self.dir, ignore_errors=True)


def _unlink_quiet(path: str) -> None:
    try:
        os.unlink(path)
    except OSError:
        pass


class ParityFlusher(threading.Thread):
    """One broker-wide daemon bounding every partition's parity lag:
    wakes at half the lag deadline, flushes partitions over their
    bytes/age policy, rotates full generations, prunes generations
    below the broker's durability floor."""

    def __init__(self, broker, interval: float | None = None):
        super().__init__(daemon=True, name="mq-parity-flusher")
        self.broker = broker
        self.interval = (
            interval
            if interval is not None
            else max(max_lag_s_default() / 2.0, 0.01)
        )
        self._stop = threading.Event()

    def stop(self) -> None:
        self._stop.set()

    def run(self) -> None:
        while not self._stop.wait(self._next_interval()):
            try:
                self.broker.parity_sweep()
            except Exception as e:  # noqa: BLE001 — never kill the broker
                log.warning("parity sweep failed: %r", e)

    def _next_interval(self) -> float:
        """Graceful-shed hook: under sustained device oversubscription
        (residency shed level > 0) the flusher stretches its cadence —
        stream-parity flush is BACKGROUND device work and must throttle
        before any foreground admission is shed. Bounded stretch: the
        lag deadline still holds eventually, it just stops compounding
        an overload."""
        try:
            from ..ec.device_queue import shed_level

            lvl = shed_level()
        except Exception:  # the shed signal must never stall flushing
            lvl = 0
        return self.interval * (1 + lvl) if lvl > 0 else self.interval
