"""Broker-side group commit for durable produce (mirrors PR 17's
volume ``_GroupCommitter``).

A Kafka produce against a durable-parity topic is acked only once its
records are replayable from the parity stream. Flushing the stream per
produce would serialize every producer behind an fsync; this committer
amortizes it over a bounded window: producers append (which feeds the
partition's ``PartitionParity`` buffer via the log's ``on_append``
observer), mark the parity stream dirty, take a WINDOW TICKET, and
block until one flush pass covering their window completes — N
producers inside one window cost one parity flush per dirty partition
instead of N.

Ordering argument (why a ticket-w producer's records are always
covered by window w's flush): the ticket is read under the condition
lock BEFORE the committer bumps ``_open_window`` (also under it), and
the bump happens-before the flush starts — so any append that took
ticket w had already landed in its parity buffer before window w's
flush began, and ``PartitionParity.flush`` drains everything buffered.

A failed flush fails EVERY producer waiting on that window — none of
the cohort's records are certified durable, and the gateway maps the
failure to a per-partition ``KAFKA_STORAGE_ERROR``.

``SEAWEED_MQ_GROUP_COMMIT_MS`` is read live per produce (0 disables
group commit: acks rely on the parity sweeper's lag bound instead of a
synchronous flush), so bench phases flip it without restarting the
broker.
"""

from __future__ import annotations

import os
import threading
import time

from ..faults import registry as faults


def group_commit_window_s() -> float:
    """SEAWEED_MQ_GROUP_COMMIT_MS as seconds (0 = no synchronous
    produce durability, the default). Read live per produce."""
    try:
        ms = float(os.environ.get("SEAWEED_MQ_GROUP_COMMIT_MS", "0"))
    except ValueError:
        ms = 0.0
    return max(0.0, ms) / 1000.0


class MqGroupCommitter:
    """One per broker; covers every durable-parity partition. See the
    module docstring for the protocol and ordering argument."""

    def __init__(self, window_s: float, name: str = "mq"):
        self._window_s = window_s
        self._cv = threading.Condition()
        self._open_window = 0
        self._completed = -1
        self._error_upto = -1
        self._last_error: BaseException | None = None
        self._pending = 0
        self._dirty: set = set()
        self._stop = False
        self.windows_committed = 0
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"mq-group-commit-{name}"
        )
        self._thread.start()

    @property
    def window_s(self) -> float:
        return self._window_s

    def mark_dirty(self, parity) -> None:
        """Register a parity stream that buffered records this window."""
        with self._cv:
            self._dirty.add(parity)

    def wait_durable(self) -> None:
        """Block the calling producer (which has already appended, so
        its records sit in a dirty parity buffer) until a flush pass
        covering its window completes; raise if that pass failed."""
        with self._cv:
            w = self._open_window
            self._pending += 1
            self._cv.notify_all()
            while self._completed < w:
                if self._stop and not self._thread.is_alive():
                    raise OSError(
                        "mq group committer stopped with produces in flight"
                    )
                self._cv.wait(timeout=0.5)
            failed = self._error_upto >= w
            err = self._last_error if failed else None
        if failed:
            raise OSError(f"mq group commit flush failed: {err!r}") from err

    def _run(self) -> None:
        while True:
            with self._cv:
                while self._pending == 0 and not self._stop:
                    self._cv.wait(timeout=0.5)
                if self._pending == 0 and self._stop:
                    return
                stopping = self._stop
            # accumulate the window OUTSIDE any lock: produces keep
            # landing and taking tickets for this window meanwhile
            if not stopping and self._window_s > 0:
                time.sleep(self._window_s)
            with self._cv:
                w = self._open_window
                self._open_window += 1
                self._pending = 0
                dirty = list(self._dirty)
                self._dirty.clear()
            err: BaseException | None = None
            try:
                faults.fire("mq.produce.before_flush", window=w)
                for parity in dirty:
                    parity.flush()
            except OSError as e:
                err = e
            from ..utils import metrics

            metrics.mq_group_commit_windows_total.inc()
            with self._cv:
                self._completed = w
                self.windows_committed += 1
                if err is not None:
                    self._error_upto = w
                    self._last_error = err
                    # a failed window's streams are still dirty
                    self._dirty.update(dirty)
                self._cv.notify_all()

    def stop(self) -> None:
        """Drain pending producers with a final commit, then exit."""
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        self._thread.join(timeout=5.0)
