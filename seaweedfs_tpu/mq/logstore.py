"""Parquet archival of sealed MQ log segments.

Reference: weed/mq/logstore — sealed in-memory log segments are
re-written as parquet files on the filer so long-retention topics cost
columnar-compressed storage and SQL scans read a columnar layout
instead of replaying raw record blobs. Archived segments remain fully
readable on the normal consume path: the broker's segment loader
falls back from `seg-N.log` to `seg-N.parquet` and re-materializes the
record stream bit-for-bit (offset, ts_ns, key, value).

Schema: offset int64 | ts_ns int64 | key binary | value binary, zstd
column compression, one row group per segment (segments are small).
"""

from __future__ import annotations

import io

from ..utils.glog import logger
from .log_buffer import decode_records, encode_record

log = logger("mq.logstore")


def segment_to_parquet(raw: bytes) -> bytes:
    """Sealed raw segment blob -> parquet bytes."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    offs, tss, keys, vals = [], [], [], []
    for off, ts_ns, key, value in decode_records(raw):
        offs.append(off)
        tss.append(ts_ns)
        keys.append(key)
        vals.append(value)
    table = pa.table(
        {
            "offset": pa.array(offs, pa.int64()),
            "ts_ns": pa.array(tss, pa.int64()),
            "key": pa.array(keys, pa.binary()),
            "value": pa.array(vals, pa.binary()),
        }
    )
    buf = io.BytesIO()
    pq.write_table(table, buf, compression="zstd")
    return buf.getvalue()


def parquet_to_segment(data: bytes) -> bytes:
    """Parquet bytes -> the original raw segment blob (re-encoded in
    offset order; the archival schema preserves every field)."""
    import pyarrow.parquet as pq

    table = pq.read_table(io.BytesIO(data))
    cols = [table.column(n).to_pylist() for n in ("offset", "ts_ns", "key", "value")]
    return b"".join(
        encode_record(o, t, k or b"", v or b"")
        for o, t, k, v in zip(*cols)
    )


def parquet_stats(data: bytes) -> dict:
    """Row count + offset/ts ranges straight from parquet metadata
    (no data decode) — used for scan pruning."""
    import pyarrow.parquet as pq

    f = pq.ParquetFile(io.BytesIO(data))
    md = f.metadata
    stats = {"rows": md.num_rows}
    try:
        rg = md.row_group(0)
        for i in range(rg.num_columns):
            col = rg.column(i)
            name = col.path_in_schema
            if name in ("offset", "ts_ns") and col.statistics is not None:
                stats[f"{name}_min"] = col.statistics.min
                stats[f"{name}_max"] = col.statistics.max
    except Exception:  # noqa: BLE001 — stats are an optimization only
        pass
    return stats


class SegmentArchiver:
    """Background conversion of sealed `.log` segments to `.parquet`.

    Idempotent and crash-safe: the parquet file is written BEFORE the
    raw segment is deleted, and the loader prefers `.log` when both
    exist. The live (unsealed) tail is never touched."""

    def __init__(self, broker, min_age_segments: int = 1):
        self.broker = broker
        # keep the newest N sealed segments raw (cheap re-reads for
        # tailing consumers); archive everything older
        self.min_age_segments = max(min_age_segments, 0)

    def run_once(self) -> int:
        archived = 0
        if not self.broker.filer:
            return 0
        for ns, name, count in self.broker.list_topics():
            for part in range(count):
                archived += self._archive_partition(ns, name, part)
        return archived

    def _archive_partition(self, ns: str, name: str, part: int) -> int:
        b = self.broker
        d = f"{b.topics_root()}/{ns}/{name}/{part:04d}"
        try:
            entries = b._list_dir(d)
        except Exception:  # noqa: BLE001 — directory may not exist yet
            return 0
        raw_segs = sorted(
            e["FullPath"]
            for e in entries
            if e["FullPath"].endswith(".log")
        )
        done = 0
        # leave the newest sealed segments raw
        for path in raw_segs[: len(raw_segs) - self.min_age_segments]:
            raw = b._get_file(path)
            if raw is None:
                continue
            try:
                parquet = segment_to_parquet(raw)
            except Exception as e:  # noqa: BLE001 — skip, keep the raw seg
                log.warning(f"archive {path}: {e!r}")
                continue
            pq_path = path[: -len(".log")] + ".parquet"
            b._put_file(pq_path, parquet)
            # stats sidecar BEFORE the raw delete: the query engine's
            # predicate pushdown prunes whole segments on it without
            # fetching the parquet bytes
            import json as _json

            try:
                b._put_file(
                    path[: -len(".log")] + ".stats.json",
                    _json.dumps(parquet_stats(parquet)).encode(),
                )
            except Exception as e:  # noqa: BLE001 — stats optional
                log.warning(f"stats for {path}: {e!r}")
            b._delete_file(path)
            done += 1
            log.v(1, f"archived {path} ({len(raw)} -> {len(parquet)} bytes)")
        return done
