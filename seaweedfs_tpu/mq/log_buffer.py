"""Per-partition append log: in-memory ring + sealed segment spill.

Reference: weed/util/log_buffer (MQ's in-memory segmented log) +
weed/mq/logstore (filer-backed segment files). Segments spill through a
pluggable `spill(segment_index, records_bytes)` callback — the broker
wires it to filer-backed storage; None keeps everything in memory.

Record wire format inside a segment (LE): [len u32 | offset i64 |
ts_ns i64 | key_len u16 | key | value]. Offsets are dense per partition.
"""

from __future__ import annotations

import struct
import threading
from typing import Callable, Iterator, Optional

_REC = struct.Struct("<IqqH")


def encode_record(offset: int, ts_ns: int, key: bytes, value: bytes) -> bytes:
    body_len = _REC.size - 4 + len(key) + len(value)
    return _REC.pack(body_len, offset, ts_ns, len(key)) + key + value


def decode_records(raw: bytes) -> Iterator[tuple[int, int, bytes, bytes]]:
    pos = 0
    while pos + 4 <= len(raw):
        (body_len,) = struct.unpack_from("<I", raw, pos)
        end = pos + 4 + body_len
        if end > len(raw):
            return
        _, offset, ts_ns, key_len = _REC.unpack_from(raw, pos)
        p = pos + _REC.size
        key = raw[p : p + key_len]
        value = raw[p + key_len : end]
        yield offset, ts_ns, key, value
        pos = end


class PartitionLog:
    """Dense-offset append log for one partition."""

    def __init__(
        self,
        segment_records: int = 4096,
        spill: Optional[Callable[[int, bytes], None]] = None,
        load: Optional[Callable[[int], Optional[bytes]]] = None,
        next_offset: int = 0,
        earliest_offset: int = 0,
    ):
        self._lock = threading.Condition()
        self.segment_records = segment_records
        self._spill = spill
        self._load = load
        self.next_offset = next_offset
        self.earliest_offset = earliest_offset
        # live (unsealed) tail records: list of (offset, ts, key, value)
        self._tail: list[tuple[int, int, bytes, bytes]] = []
        self._tail_base = next_offset
        # observer fed every accepted append UNDER the partition lock
        # (offset order guaranteed) — the durable-parity stream
        # (mq/stream_parity.py) buffers the record's wire bytes here;
        # parity math/fsync run on the flusher's schedule, not the
        # append path. None = no parity for this partition.
        self.on_append: Optional[
            Callable[[int, int, bytes, bytes], None]
        ] = None

    # ------------------------------------------------------------ write

    def append(self, ts_ns: int, key: bytes, value: bytes) -> int:
        with self._lock:
            off = self.next_offset
            self._tail.append((off, ts_ns, key, value))
            self.next_offset = off + 1
            if self.on_append is not None:
                self.on_append(off, ts_ns, key, value)
            if len(self._tail) >= self.segment_records:
                self._seal_locked()
            self._lock.notify_all()
            return off

    def append_at(self, offset: int, ts_ns: int, key: bytes, value: bytes) -> int:
        """Follower-side append at a LEADER-assigned offset: replicas
        mirror the leader's dense numbering. Duplicates are ignored; a
        GAP is refused (returns the expected offset) so the leader can
        backfill — a silently-accepted gap would surface as lost acked
        records after a failover promotion."""
        with self._lock:
            if offset < self.next_offset:
                return self.next_offset  # duplicate of a held record
            if offset > self.next_offset:
                return self.next_offset  # refuse: leader must backfill
            self._tail.append((offset, ts_ns, key, value))
            self.next_offset = offset + 1
            if self.on_append is not None:
                self.on_append(offset, ts_ns, key, value)
            if len(self._tail) >= self.segment_records:
                self._seal_locked()
            self._lock.notify_all()
            return self.next_offset

    def append_batch(
        self, records: list[tuple[int, bytes, bytes]]
    ) -> int:
        """Append [(ts_ns, key, value), ...] with CONTIGUOUS offsets
        under one lock hold; returns the first offset. Kafka clients
        compute record offsets as baseOffset + index-in-batch, so a
        batch must never interleave with a concurrent producer's."""
        with self._lock:
            base = self.next_offset
            for i, (ts_ns, key, value) in enumerate(records):
                self._tail.append((base + i, ts_ns, key, value))
            self.next_offset = base + len(records)
            if self.on_append is not None:
                for i, (ts_ns, key, value) in enumerate(records):
                    self.on_append(base + i, ts_ns, key, value)
            if len(self._tail) >= self.segment_records:
                self._seal_locked()
            self._lock.notify_all()
            return base

    def _seal_locked(self) -> None:
        if not self._tail or self._spill is None:
            if self._spill is None and len(self._tail) > self.segment_records * 4:
                # memory-only mode: bound the tail by dropping the oldest
                drop = len(self._tail) - self.segment_records * 4
                self._tail = self._tail[drop:]
                self._tail_base = self._tail[0][0]
                self.earliest_offset = self._tail_base
            return
        # Spill runs under the partition lock: readers must never observe
        # a cleared tail whose records have not yet landed in a segment.
        # The cost (appends stall during a slow spill) is bounded by one
        # segment per segment_records appends; async double-buffered
        # spill is a later optimization.
        # Every record lands in its offset-aligned segment, merging with
        # previously spilled partial content — a flush mid-segment (e.g.
        # broker shutdown) followed by post-restart appends must never
        # overwrite earlier records in that slot.
        groups: dict[int, list] = {}
        for r in self._tail:
            groups.setdefault(r[0] // self.segment_records, []).append(r)
        for seg, recs in sorted(groups.items()):
            raw = b"".join(encode_record(*r) for r in recs)
            if recs[0][0] % self.segment_records != 0 and self._load is not None:
                prev = self._load(seg)
                if prev:
                    # keep only records below our first (idempotent merge)
                    kept = b"".join(
                        encode_record(*pr)
                        for pr in decode_records(prev)
                        if pr[0] < recs[0][0]
                    )
                    raw = kept + raw
            self._spill(seg, raw)
        self._tail_base = self.next_offset
        self._tail = []

    def fast_forward(self, offset: int) -> bool:
        """Advance an EMPTY log to start at `offset` (parity-stream
        recovery whose retention window begins past 0: the records
        below it fell out of a bounded tail and are gone by design).
        Refused on a log that holds or held records — dense numbering
        must never skip over live state."""
        with self._lock:
            if self._tail or self.next_offset != self.earliest_offset:
                return False
            if offset <= self.next_offset:
                return False
            self.next_offset = offset
            self.earliest_offset = offset
            self._tail_base = offset
            return True

    def flush(self) -> None:
        with self._lock:
            self._seal_locked()

    def truncate_before(self, offset: int) -> int:
        """Drop records below `offset` (-1 = everything): earliest
        advances, in-memory tail records below it are freed. Durable
        segment files are the broker's to delete (segment-granular);
        returns the new earliest offset."""
        with self._lock:
            boundary = self.next_offset if offset < 0 else min(
                offset, self.next_offset
            )
            self._tail = [r for r in self._tail if r[0] >= boundary]
            self._tail_base = (
                self._tail[0][0] if self._tail else self.next_offset
            )
            self.earliest_offset = max(self.earliest_offset, boundary)
            return self.earliest_offset

    # ------------------------------------------------------------- read

    def read_from(
        self, offset: int, max_records: int = 1024
    ) -> list[tuple[int, int, bytes, bytes]]:
        """Records with offset >= `offset` (up to max_records); pulls
        sealed segments through `load` when the tail has rotated past.
        Reads below earliest_offset clamp up to it: after a truncation
        the deleted whole segments would otherwise read as an
        empty-break and silently skip the retained partial segment."""
        offset = max(offset, self.earliest_offset)
        with self._lock:
            if offset >= self._tail_base:
                start = 0
                for i, r in enumerate(self._tail):
                    if r[0] >= offset:
                        start = i
                        break
                else:
                    return []
                return self._tail[start : start + max_records]
            tail_snapshot = list(self._tail)
        out: list[tuple[int, int, bytes, bytes]] = []
        if self._load is not None:
            seg = offset // self.segment_records
            while len(out) < max_records:
                raw = self._load(seg)
                if raw is None:
                    break
                for rec in decode_records(raw):
                    if rec[0] >= offset and len(out) < max_records:
                        out.append(rec)
                seg += 1
                if out and out[-1][0] + 1 >= self._tail_base:
                    break
        for rec in tail_snapshot:
            if rec[0] >= offset and len(out) < max_records:
                if not out or rec[0] > out[-1][0]:
                    out.append(rec)
        return out

    def wait_for(self, offset: int, timeout: float) -> bool:
        """Block until next_offset > offset (new data) or timeout."""
        with self._lock:
            return self._lock.wait_for(
                lambda: self.next_offset > offset, timeout=timeout
            )
