"""Zero-copy catch-up fetch: sealed-segment batch spool.

A catch-up consumer reads offsets far behind the tail, i.e. out of
SEALED log segments that will never change again. The hot path used to
re-materialize those records through Python on every fetch: load the
segment, build Record objects, re-encode a Kafka record batch, copy it
into the response buffer — O(bytes) interpreter work per consumer per
pass.

The spool transcodes a sealed segment ONCE into its Kafka record-batch
v2 wire form, parks it in a local spool file, and hands fetches a
:class:`frame_pool.FileExtent` over it. Egress then goes
kernel-to-kernel via ``sn_send_file`` (native plane) or a plain
read+send (Python fallback) — the SAME bytes either way, so the two
planes are bit-identical by construction and the plane choice is
invisible to clients.

Serving a whole sealed segment as one batch is protocol-legal: Kafka
brokers may return batches that START BEFORE the fetch offset
(typically when serving from disk exactly like this); clients skip
records below their requested offset.

Entries are keyed by (topic, partition, segment) and pinned to the
PartitionLog instance they were built from — a deleted/recreated topic
gets fresh PartitionLog objects, which invalidates its spool entries
by identity. Total spool size is LRU-bounded by
``SEAWEED_MQ_FETCH_SPOOL_MB``.
"""

from __future__ import annotations

import os
import shutil
import tempfile
import threading
from collections import OrderedDict

from .frame_pool import FileExtent
from .records import Record, encode_batch


def spool_cap_bytes() -> int:
    return int(os.environ.get("SEAWEED_MQ_FETCH_SPOOL_MB", "64")) << 20


class _Entry:
    __slots__ = ("path", "length", "plog", "base_offset", "next_offset")

    def __init__(self, path, length, plog, base_offset, next_offset):
        self.path = path
        self.length = length
        self.plog = plog
        self.base_offset = base_offset
        self.next_offset = next_offset


class FetchSpool:
    def __init__(self, root: str | None = None):
        self._own_root = root is None
        self.root = root or tempfile.mkdtemp(prefix="kafka-spool-")
        os.makedirs(self.root, exist_ok=True)
        self._lock = threading.Lock()
        self._entries: "OrderedDict[tuple, _Entry]" = OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.builds = 0

    # ------------------------------------------------------------- lookup

    def extent_for(
        self, topic: str, partition: int, plog, offset: int
    ) -> tuple[FileExtent, int, int] | None:
        """(extent, batch_base_offset, next_offset_after_batch) serving
        `offset` out of a sealed segment, or None when the offset's
        segment is not fully sealed (tail data, or partially truncated)
        — the caller then takes the ordinary in-memory path."""
        seg_size = getattr(plog, "segment_records", 0)
        tail_base = getattr(plog, "_tail_base", 0)
        if seg_size <= 0 or offset >= tail_base:
            return None
        seg = offset // seg_size
        seg_base = seg * seg_size
        seg_end = seg_base + seg_size
        if seg_end > tail_base or seg_base < plog.earliest_offset:
            return None
        key = (topic, partition, seg)
        with self._lock:
            e = self._entries.get(key)
            if e is not None and e.plog is plog:
                self._entries.move_to_end(key)
                self.hits += 1
                return (
                    FileExtent(e.path, 0, e.length),
                    e.base_offset,
                    e.next_offset,
                )
        e = self._build(key, plog, seg_base, seg_end)
        if e is None:
            return None
        return FileExtent(e.path, 0, e.length), e.base_offset, e.next_offset

    def _build(self, key, plog, seg_base: int, seg_end: int) -> _Entry | None:
        from .gateway import _unpack_null

        recs = plog.read_from(seg_base, max_records=seg_end - seg_base)
        recs = [r for r in recs if r[0] < seg_end]
        if not recs or recs[0][0] != seg_base:
            return None  # segment not intact on this path; don't cache
        batch = encode_batch(
            [
                Record(
                    key=_unpack_null(k),
                    value=_unpack_null(val),
                    timestamp_ms=ts // 1_000_000,
                    offset=o,
                )
                for o, ts, k, val in recs
            ],
            base_offset=seg_base,
        )
        topic, partition, seg = key
        path = os.path.join(self.root, f"{topic}-{partition}-{seg}.batch")
        tmp = path + ".tmp"
        try:
            with open(tmp, "wb") as f:
                f.write(batch)
            os.replace(tmp, path)
        except OSError:
            return None
        e = _Entry(path, len(batch), plog, seg_base, recs[-1][0] + 1)
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.length
            self._entries[key] = e
            self._bytes += e.length
            self.builds += 1
            self._evict_locked()
        return e

    def _evict_locked(self) -> None:
        cap = spool_cap_bytes()
        while self._bytes > cap and len(self._entries) > 1:
            _key, old = self._entries.popitem(last=False)
            self._bytes -= old.length
            try:
                os.unlink(old.path)
            except OSError:
                pass

    # ------------------------------------------------------------- status

    def status(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "bytes": self._bytes,
                "cap_bytes": spool_cap_bytes(),
                "hits": self.hits,
                "builds": self.builds,
            }

    def close(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0
        if self._own_root:
            shutil.rmtree(self.root, ignore_errors=True)
