"""Kafka record-batch compression codecs.

Reference: weed/mq/kafka record batch attributes bits 0-2 (none/gzip/
snappy/lz4/zstd). gzip and zstd ride the stdlib / the bundled
`zstandard` package; snappy (raw block + xerial framing) and the LZ4
frame format are implemented here in pure Python — full decoders, plus
minimal valid ENCODERS (snappy all-literals, LZ4 stored blocks) so
tests and the fetch path can produce well-formed streams without the
native libraries.
"""

from __future__ import annotations

import struct

# ---------------------------------------------------------------- snappy

_XERIAL_MAGIC = b"\x82SNAPPY\x00"


def _snappy_uvarint(data: bytes, pos: int) -> tuple[int, int]:
    shift = value = 0
    while True:
        b = data[pos]
        pos += 1
        value |= (b & 0x7F) << shift
        if not b & 0x80:
            return value, pos
        shift += 7
        if shift > 63:
            raise ValueError("snappy: uvarint too long")


def _snappy_decompress_block(data: bytes) -> bytes:
    want, pos = _snappy_uvarint(data, 0)
    out = bytearray()
    n = len(data)
    while pos < n:
        tag = data[pos]
        pos += 1
        kind = tag & 0x03
        if kind == 0:  # literal
            length = tag >> 2
            if length >= 60:
                extra = length - 59  # 1..4 length bytes, little-endian
                length = int.from_bytes(data[pos : pos + extra], "little")
                pos += extra
            length += 1
            out += data[pos : pos + length]
            pos += length
            continue
        if kind == 1:  # copy, 1-byte offset
            length = 4 + ((tag >> 2) & 0x07)
            offset = ((tag & 0xE0) << 3) | data[pos]
            pos += 1
        elif kind == 2:  # copy, 2-byte offset
            length = 1 + (tag >> 2)
            offset = int.from_bytes(data[pos : pos + 2], "little")
            pos += 2
        else:  # copy, 4-byte offset
            length = 1 + (tag >> 2)
            offset = int.from_bytes(data[pos : pos + 4], "little")
            pos += 4
        if offset == 0 or offset > len(out):
            raise ValueError("snappy: bad copy offset")
        # overlapping copies are legal (RLE): copy byte-at-a-time when
        # the match overlaps the output tail
        start = len(out) - offset
        if offset >= length:
            out += out[start : start + length]
        else:
            for i in range(length):
                out.append(out[start + i])
    if len(out) != want:
        raise ValueError(
            f"snappy: declared {want} bytes, produced {len(out)}"
        )
    return bytes(out)


def snappy_decompress(data: bytes) -> bytes:
    """Raw snappy block, or the xerial-framed stream java/python
    clients emit (magic + concatenated [len|block] chunks)."""
    if data.startswith(_XERIAL_MAGIC):
        pos = len(_XERIAL_MAGIC) + 8  # magic + version + compat
        out = bytearray()
        while pos < len(data):
            (blen,) = struct.unpack_from(">i", data, pos)
            pos += 4
            out += _snappy_decompress_block(data[pos : pos + blen])
            pos += blen
        return bytes(out)
    return _snappy_decompress_block(data)


def snappy_compress(data: bytes) -> bytes:
    """Valid snappy stream using literal elements only (the format
    permits a compressor to emit any mix; correctness over ratio)."""
    from .protocol import write_uvarint

    out = bytearray(write_uvarint(len(data)))
    pos = 0
    while pos < len(data):
        chunk = data[pos : pos + (1 << 16)]
        pos += len(chunk)
        n = len(chunk) - 1
        if n < 60:
            out.append(n << 2)
        else:
            out.append(62 << 2)  # 3-byte extended literal length
            out += (n & 0xFFFFFF).to_bytes(3, "little")
        out += chunk
    return bytes(out)


# ------------------------------------------------------------------- lz4


def xxh32(data: bytes, seed: int = 0) -> int:
    """XXH32 (LZ4 frame header/content checksums)."""
    P1, P2, P3, P4, P5 = (
        2654435761,
        2246822519,
        3266489917,
        668265263,
        374761393,
    )
    mask = 0xFFFFFFFF

    def rotl(x, r):
        return ((x << r) | (x >> (32 - r))) & mask

    n = len(data)
    pos = 0
    if n >= 16:
        v1 = (seed + P1 + P2) & mask
        v2 = (seed + P2) & mask
        v3 = seed & mask
        v4 = (seed - P1) & mask
        while pos + 16 <= n:
            a, b, c, d = struct.unpack_from("<IIII", data, pos)
            v1 = (rotl((v1 + a * P2) & mask, 13) * P1) & mask
            v2 = (rotl((v2 + b * P2) & mask, 13) * P1) & mask
            v3 = (rotl((v3 + c * P2) & mask, 13) * P1) & mask
            v4 = (rotl((v4 + d * P2) & mask, 13) * P1) & mask
            pos += 16
        h = (rotl(v1, 1) + rotl(v2, 7) + rotl(v3, 12) + rotl(v4, 18)) & mask
    else:
        h = (seed + P5) & mask
    h = (h + n) & mask
    while pos + 4 <= n:
        (k,) = struct.unpack_from("<I", data, pos)
        h = (rotl((h + k * P3) & mask, 17) * P4) & mask
        pos += 4
    while pos < n:
        h = (rotl((h + data[pos] * P5) & mask, 11) * P1) & mask
        pos += 1
    h ^= h >> 15
    h = (h * P2) & mask
    h ^= h >> 13
    h = (h * P3) & mask
    h ^= h >> 16
    return h


_LZ4_MAGIC = 0x184D2204


def _lz4_decompress_block(
    data: bytes, out: bytearray | None = None, window_base: int | None = None
) -> bytes | None:
    """Decode one LZ4 block, appending to `out` in place. Matches may
    reach back to out[window_base:] — 0 for block-LINKED frames
    (lz4.frame / librdkafka default), len(out)-at-entry for independent
    blocks. In-place append avoids re-copying the 64 KiB window per
    block on large messages."""
    external = out is not None
    if out is None:
        out = bytearray()
    base = len(out)  # where this block's output starts
    floor = base if window_base is None else window_base
    pos = 0
    n = len(data)
    while pos < n:
        token = data[pos]
        pos += 1
        lit_len = token >> 4
        if lit_len == 15:
            while True:
                b = data[pos]
                pos += 1
                lit_len += b
                if b != 255:
                    break
        out += data[pos : pos + lit_len]
        pos += lit_len
        if pos >= n:
            break  # last sequence: literals only
        offset = int.from_bytes(data[pos : pos + 2], "little")
        pos += 2
        if offset == 0:
            raise ValueError("lz4: zero match offset")
        match_len = token & 0x0F
        if match_len == 15:
            while True:
                b = data[pos]
                pos += 1
                match_len += b
                if b != 255:
                    break
        match_len += 4
        start = len(out) - offset
        if start < floor:
            raise ValueError("lz4: match offset before start")
        if offset >= match_len:
            out += out[start : start + match_len]
        else:  # overlapping (RLE) match
            for i in range(match_len):
                out.append(out[start + i])
    # frame-path callers read `out` in place; only standalone use gets
    # (and pays for) a materialized copy
    return None if external else bytes(out)


def lz4_decompress(data: bytes) -> bytes:
    """LZ4 FRAME format (what Kafka record batches carry for codec 3)."""
    (magic,) = struct.unpack_from("<I", data, 0)
    if magic != _LZ4_MAGIC:
        raise ValueError(f"lz4: bad frame magic {magic:#x}")
    flg = data[4]
    if (flg >> 6) != 0b01:
        raise ValueError("lz4: unsupported frame version")
    has_content_size = bool(flg & 0x08)
    has_content_checksum = bool(flg & 0x04)
    block_checksum = bool(flg & 0x10)
    block_independent = bool(flg & 0x20)
    has_dict = bool(flg & 0x01)
    pos = 6  # magic + FLG + BD
    if has_content_size:
        pos += 8
    if has_dict:
        pos += 4
    pos += 1  # HC byte (not verified: we tolerate legacy Kafka v1
    #           framing quirks the same way librdkafka does)
    out = bytearray()
    while True:
        (bsize,) = struct.unpack_from("<I", data, pos)
        pos += 4
        if bsize == 0:
            break  # EndMark
        stored = bool(bsize & 0x80000000)
        bsize &= 0x7FFFFFFF
        block = data[pos : pos + bsize]
        pos += bsize
        if block_checksum:
            pos += 4
        if stored:
            out += block
        else:
            # linked frames: matches may reach back into previously
            # produced output (offsets are format-capped at 64 KiB, so
            # the whole buffer serves as the window with no slicing);
            # independent blocks may only reference themselves
            _lz4_decompress_block(
                block, out, 0 if not block_independent else len(out)
            )
    if has_content_checksum:
        pos += 4
    return bytes(out)


def lz4_compress(data: bytes) -> bytes:
    """Valid LZ4 frame using STORED (uncompressed) blocks — the frame
    format's escape hatch; every decoder must accept it."""
    flg = 0x60  # version 01, block-independent, no checksums/size/dict
    bd = 0x70  # 4 MiB max block size
    header = struct.pack("<I", _LZ4_MAGIC) + bytes([flg, bd])
    hc = (xxh32(bytes([flg, bd])) >> 8) & 0xFF
    parts = [header, bytes([hc])]
    pos = 0
    while pos < len(data):
        chunk = data[pos : pos + (4 << 20)]
        pos += len(chunk)
        parts.append(struct.pack("<I", 0x80000000 | len(chunk)))
        parts.append(chunk)
    parts.append(struct.pack("<I", 0))  # EndMark
    return b"".join(parts)


# ------------------------------------------------------------------ zstd


def zstd_decompress(data: bytes) -> bytes:
    import zstandard

    # decompressobj: no declared content size required in the frame
    return zstandard.ZstdDecompressor().decompressobj().decompress(data)


def zstd_compress(data: bytes) -> bytes:
    import zstandard

    return zstandard.ZstdCompressor().compress(data)
