"""A minimal Kafka wire-protocol client.

The counterpart of the gateway, usable standalone against any
single-broker Kafka endpoint: metadata/create/delete topics,
produce/fetch with record batches v2, list offsets, committed offsets,
and classic group membership (join/sync/heartbeat). The test suite
drives the gateway with it the way the reference's test/kafka drives
theirs with real client libraries.
"""

from __future__ import annotations

import socket
import struct
import threading
import time

from . import protocol as kp
from .protocol import Reader, Writer
from .records import Record, decode_batches, encode_batch


class KafkaError(Exception):
    def __init__(self, code: int, where: str = ""):
        self.code = code
        super().__init__(f"kafka error {code} {where}".strip())


class KafkaClient:
    def __init__(self, host: str, port: int, client_id: str = "sw-client"):
        self.client_id = client_id
        self._sock = socket.create_connection((host, port), timeout=30)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._corr = 0
        self._lock = threading.Lock()
        self.api_versions = self._fetch_api_versions()

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    # ------------------------------------------------------------ framing

    def _call(
        self,
        api_key: int,
        api_version: int,
        body: bytes,
        oneway: bool = False,
        flexible: bool = False,
        resp_header_tags: bool | None = None,
    ) -> Reader | None:
        """flexible: request header v2 (tagged fields after client_id).
        resp_header_tags: response header v1; defaults to `flexible`
        except for ApiVersions whose response header is always v0."""
        with self._lock:
            self._corr += 1
            corr = self._corr
            head = (
                Writer()
                .i16(api_key)
                .i16(api_version)
                .i32(corr)
                .nullable_string(self.client_id)
            )
            if flexible:
                head.tags()
            frame = head.done() + body
            self._sock.sendall(struct.pack(">i", len(frame)) + frame)
            if oneway:
                return None
            (size,) = struct.unpack(">i", self._read_exact(4))
            resp = self._read_exact(size)
        r = Reader(resp)
        got = r.i32()
        if got != corr:
            raise KafkaError(-1, f"correlation mismatch {got} != {corr}")
        if resp_header_tags is None:
            resp_header_tags = flexible and api_key != kp.API_VERSIONS
        if resp_header_tags:
            r.tagged_fields()
        return r

    def _read_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self._sock.recv(n - len(buf))
            if not chunk:
                raise EOFError("connection closed")
            buf += chunk
        return buf

    def _fetch_api_versions(self, version: int = 3) -> dict[int, tuple[int, int]]:
        if version >= 3:
            body = (
                Writer()
                .compact_string("seaweedfs-tpu")
                .compact_string("r4")
                .tags()
                .done()
            )
            r = self._call(kp.API_VERSIONS, 3, body, flexible=True)
            err = r.i16()
            if err == kp.UNSUPPORTED_VERSION:
                return self._fetch_api_versions(version=0)
            if err:
                raise KafkaError(err, "ApiVersions")
            out = {}
            for _ in range(max(r.uvarint() - 1, 0)):
                key = r.i16()
                lo = r.i16()
                hi = r.i16()
                r.tagged_fields()
                out[key] = (lo, hi)
            r.i32()  # throttle
            r.tagged_fields()
            return out
        r = self._call(kp.API_VERSIONS, 0, b"")
        err = r.i16()
        if err:
            raise KafkaError(err, "ApiVersions")
        out = {}
        for _ in range(r.i32()):
            key = r.i16()
            lo = r.i16()
            hi = r.i16()
            out[key] = (lo, hi)
        return out

    # ------------------------------------------------------------- topics

    def metadata(self, topics: list[str] | None = None) -> dict:
        w = Writer()
        if topics is None:
            w.i32(-1)
        else:
            w.array(topics, lambda ww, t: ww.string(t))
        w.i8(1)  # allow_auto_topic_creation (v4+)
        r = self._call(kp.METADATA, 4, w.done())
        r.i32()  # throttle
        brokers = [
            (r.i32(), r.string(), r.i32(), r.nullable_string())
            for _ in range(r.i32())
        ]
        cluster_id = r.nullable_string()
        controller = r.i32()
        out_topics = {}
        for _ in range(r.i32()):
            err = r.i16()
            name = r.string()
            r.i8()  # is_internal
            parts = {}
            for _p in range(r.i32()):
                perr = r.i16()
                idx = r.i32()
                leader = r.i32()
                r.array(r.i32)  # replicas
                r.array(r.i32)  # isr
                parts[idx] = {"error": perr, "leader": leader}
            out_topics[name] = {"error": err, "partitions": parts}
        return {
            "brokers": brokers,
            "cluster_id": cluster_id,
            "controller": controller,
            "topics": out_topics,
        }

    def create_topic(self, name: str, partitions: int = 1) -> int:
        w = Writer()
        w.array(
            [name],
            lambda ww, t: ww.string(t)
            .i32(partitions)
            .i16(1)
            .i32(0)
            .i32(0),
        )
        w.i32(10_000)
        r = self._call(kp.CREATE_TOPICS, 0, w.done())
        r.i32()  # array count (1)
        r.string()
        return r.i16()

    def delete_topic(self, name: str) -> int:
        w = Writer().array([name], lambda ww, t: ww.string(t)).i32(10_000)
        r = self._call(kp.DELETE_TOPICS, 0, w.done())
        r.i32()
        r.string()
        return r.i16()

    # ------------------------------------------------------------ produce

    def produce(
        self,
        topic: str,
        partition: int,
        records: list[Record],
        acks: int = -1,
        version: int = 9,
        compression: int = 0,
    ) -> int:
        """Returns the base offset assigned to the first record.
        version 9 uses the flexible (KIP-482) encoding; compression is
        the batch codec id (0 none, 1 gzip, 2 snappy, 3 lz4, 4 zstd)."""
        base = encode_batch(records, base_offset=0, compression=compression)
        flex = version >= 9
        w = Writer()
        if flex:
            w.compact_nullable_string(None)  # transactional_id
            w.i16(acks).i32(10_000)
            w.compact_array(
                [(topic, partition, base)],
                lambda ww, tp: ww.compact_string(tp[0])
                .compact_array(
                    [tp],
                    lambda w3, tp2: w3.i32(tp2[1])
                    .compact_nullable_bytes(tp2[2])
                    .tags(),
                )
                .tags(),
            )
            w.tags()
        else:
            w.nullable_string(None)  # transactional_id
            w.i16(acks).i32(10_000)
            w.array(
                [(topic, partition, base)],
                lambda ww, tp: ww.string(tp[0]).array(
                    [tp],
                    lambda w3, tp2: w3.i32(tp2[1]).bytes_(tp2[2]),
                ),
            )
        r = self._call(
            kp.PRODUCE, version, w.done(), oneway=(acks == 0), flexible=flex
        )
        if r is None:
            return -1
        if flex:
            r.uvarint()  # topics count (compact)
            r.compact_string()
            r.uvarint()  # partitions count
            r.i32()  # index
            err = r.i16()
            base_offset = r.i64()
            if err:
                raise KafkaError(err, "Produce")
            return base_offset
        r.i32()  # topics count
        r.string()
        r.i32()  # partitions count
        r.i32()  # index
        err = r.i16()
        base_offset = r.i64()
        if err:
            raise KafkaError(err, "Produce")
        return base_offset

    # -------------------------------------------------------------- fetch

    def fetch(
        self,
        topic: str,
        partition: int,
        offset: int,
        max_wait_ms: int = 100,
        max_bytes: int = 4 * 1024 * 1024,
        version: int = 11,
    ) -> tuple[int, list[Record]]:
        """Returns (high_watermark, records)."""
        w = Writer()
        w.i32(-1).i32(max_wait_ms).i32(1).i32(max_bytes).i8(0)
        if version >= 7:
            w.i32(0)  # session_id
            w.i32(-1)  # session_epoch (-1 = full fetch, no session)

        def part_fields(w3: Writer, tp2):
            w3.i32(tp2[1])
            if version >= 9:
                w3.i32(-1)  # current_leader_epoch
            w3.i64(tp2[2])
            if version >= 5:
                w3.i64(0)  # log_start_offset
            w3.i32(max_bytes)

        w.array(
            [(topic, partition, offset)],
            lambda ww, tp: ww.string(tp[0]).array([tp], part_fields),
        )
        if version >= 7:
            w.array([], lambda *_: None)  # forgotten_topics_data
        if version >= 11:
            w.nullable_string(None)  # rack_id
        r = self._call(kp.FETCH, version, w.done())
        r.i32()  # throttle
        if version >= 7:
            top_err = r.i16()
            r.i32()  # session_id
            if top_err:
                raise KafkaError(top_err, "Fetch")
        r.i32()  # topics count
        r.string()
        r.i32()  # partitions count
        r.i32()  # index
        err = r.i16()
        hw = r.i64()
        r.i64()  # last_stable
        if version >= 5:
            r.i64()  # log_start_offset
        r.array(lambda: (r.i64(), r.i64()))  # aborted txns (pid, first_offset)
        if version >= 11:
            r.i32()  # preferred_read_replica
        blob = r.nullable_bytes()
        if err:
            raise KafkaError(err, "Fetch")
        # a broker may legally return a whole batch that STARTS BEFORE
        # the fetch offset (disk-backed serving — our sealed-segment
        # spool does exactly this); skipping records below the
        # requested offset is the client's job
        return hw, [
            rec
            for rec in decode_batches(blob or b"")
            if rec.offset >= offset
        ]

    def list_offset(self, topic: str, partition: int, ts: int = -1) -> int:
        """ts -1 = latest, -2 = earliest, >=0 = first offset at/after."""
        w = Writer().i32(-1)
        w.array(
            [(topic, partition, ts)],
            lambda ww, tp: ww.string(tp[0]).array(
                [tp], lambda w3, tp2: w3.i32(tp2[1]).i64(tp2[2])
            ),
        )
        r = self._call(kp.LIST_OFFSETS, 1, w.done())
        r.i32()  # topics
        r.string()
        r.i32()  # parts
        r.i32()  # index
        err = r.i16()
        r.i64()  # timestamp
        off = r.i64()
        if err:
            raise KafkaError(err, "ListOffsets")
        return off

    # ------------------------------------------------------------ offsets

    def commit_offset(
        self, group: str, topic: str, partition: int, offset: int
    ) -> int:
        w = Writer().string(group)
        w.array(
            [(topic, partition, offset)],
            lambda ww, tp: ww.string(tp[0]).array(
                [tp],
                lambda w3, tp2: w3.i32(tp2[1])
                .i64(tp2[2])
                .nullable_string(None),
            ),
        )
        r = self._call(kp.OFFSET_COMMIT, 0, w.done())
        r.i32()
        r.string()
        r.i32()
        r.i32()
        return r.i16()

    def fetch_offset(self, group: str, topic: str, partition: int) -> int:
        w = Writer().string(group)
        w.array(
            [(topic, partition)],
            lambda ww, tp: ww.string(tp[0]).array(
                [tp], lambda w3, tp2: w3.i32(tp2[1])
            ),
        )
        r = self._call(kp.OFFSET_FETCH, 1, w.done())
        r.i32()
        r.string()
        r.i32()
        r.i32()
        off = r.i64()
        r.nullable_string()
        err = r.i16()
        if err:
            raise KafkaError(err, "OffsetFetch")
        return off

    def find_coordinator(self, group: str) -> tuple[str, int]:
        r = self._call(kp.FIND_COORDINATOR, 0, Writer().string(group).done())
        err = r.i16()
        if err:
            raise KafkaError(err, "FindCoordinator")
        r.i32()  # node id
        return r.string(), r.i32()

    # -------------------------------------------------------------- groups

    def join_group(
        self,
        group: str,
        member_id: str = "",
        topics: list[str] | None = None,
        session_timeout_ms: int = 10_000,
    ) -> dict:
        meta = (
            Writer()
            .i16(0)
            .array(topics or [], lambda ww, t: ww.string(t))
            .bytes_(b"")
            .done()
        )
        w = Writer().string(group).i32(session_timeout_ms)
        w.string(member_id).string("consumer")
        w.array([("range", meta)], lambda ww, p: ww.string(p[0]).bytes_(p[1]))
        r = self._call(kp.JOIN_GROUP, 0, w.done())
        err = r.i16()
        gen = r.i32()
        protocol = r.string()
        leader = r.string()
        me = r.string()
        members = [(r.string(), r.bytes_()) for _ in range(r.i32())]
        if err:
            raise KafkaError(err, "JoinGroup")
        return {
            "generation": gen,
            "protocol": protocol,
            "leader": leader,
            "member_id": me,
            "members": members,
        }

    def sync_group(
        self,
        group: str,
        generation: int,
        member_id: str,
        assignments: list[tuple[str, bytes]] | None = None,
    ) -> bytes:
        w = Writer().string(group).i32(generation).string(member_id)
        w.array(
            assignments or [],
            lambda ww, a: ww.string(a[0]).bytes_(a[1]),
        )
        r = self._call(kp.SYNC_GROUP, 0, w.done())
        err = r.i16()
        blob = r.bytes_()
        if err:
            raise KafkaError(err, "SyncGroup")
        return blob

    def heartbeat(self, group: str, generation: int, member_id: str) -> int:
        w = Writer().string(group).i32(generation).string(member_id)
        r = self._call(kp.HEARTBEAT, 0, w.done())
        return r.i16()

    def leave_group(self, group: str, member_id: str) -> int:
        w = Writer().string(group).string(member_id)
        r = self._call(kp.LEAVE_GROUP, 0, w.done())
        return r.i16()


def assign_range(
    members: list[tuple[str, bytes]], partitions: dict[str, int]
) -> list[tuple[str, bytes]]:
    """Leader-side range assignment: partitions of each topic split
    contiguously across members (Kafka's RangeAssignor), encoded as
    ConsumerProtocolAssignment v0 blobs."""
    member_ids = sorted(m for m, _ in members)
    per_member: dict[str, dict[str, list[int]]] = {m: {} for m in member_ids}
    for topic, count in sorted(partitions.items()):
        n = len(member_ids)
        per = count // n
        extra = count % n
        start = 0
        for i, m in enumerate(member_ids):
            take = per + (1 if i < extra else 0)
            if take:
                per_member[m].setdefault(topic, []).extend(
                    range(start, start + take)
                )
            start += take
    out = []
    for m in member_ids:
        w = Writer().i16(0)  # version
        w.array(
            sorted(per_member[m].items()),
            lambda ww, tp: ww.string(tp[0]).array(
                tp[1], lambda w3, p: w3.i32(p)
            ),
        )
        w.bytes_(b"")  # user_data
        out.append((m, w.done()))
    return out


def parse_assignment(blob: bytes) -> dict[str, list[int]]:
    r = Reader(blob)
    r.i16()  # version
    out: dict[str, list[int]] = {}
    for _ in range(r.i32()):
        topic = r.string()
        out[topic] = r.array(r.i32)
    return out
