"""Kafka protocol primitives (non-flexible encodings).

Reference: weed/mq/kafka/protocol — the Kafka binary protocol's
big-endian primitives: INT8/16/32/64, STRING (i16 length), NULLABLE_
STRING, BYTES (i32 length), ARRAY (i32 count), the zigzag varints used
inside record batches, and the KIP-482 flexible (compact/tagged)
encodings used by Produce v9+ and ApiVersions v3+.
"""

from __future__ import annotations

import struct


class Reader:
    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def _take(self, n: int) -> bytes:
        if self.pos + n > len(self.buf):
            raise EOFError(
                f"need {n} bytes at {self.pos}, have {len(self.buf)}"
            )
        b = self.buf[self.pos : self.pos + n]
        self.pos += n
        return b

    def i8(self) -> int:
        return struct.unpack(">b", self._take(1))[0]

    def i16(self) -> int:
        return struct.unpack(">h", self._take(2))[0]

    def i32(self) -> int:
        return struct.unpack(">i", self._take(4))[0]

    def i64(self) -> int:
        return struct.unpack(">q", self._take(8))[0]

    def u32(self) -> int:
        return struct.unpack(">I", self._take(4))[0]

    def string(self) -> str:
        n = self.i16()
        if n < 0:
            raise ValueError("non-nullable string was null")
        return self._take(n).decode("utf-8")

    def nullable_string(self) -> str | None:
        n = self.i16()
        if n < 0:
            return None
        return self._take(n).decode("utf-8")

    def bytes_(self) -> bytes:
        n = self.i32()
        if n < 0:
            raise ValueError("non-nullable bytes was null")
        return self._take(n)

    def nullable_bytes(self) -> bytes | None:
        n = self.i32()
        if n < 0:
            return None
        return self._take(n)

    def array(self, fn) -> list:
        n = self.i32()
        if n < 0:
            return []
        return [fn() for _ in range(n)]

    def remaining(self) -> int:
        return len(self.buf) - self.pos

    # record-batch varints (zigzag)
    def uvarint(self) -> int:
        shift = value = 0
        while True:
            b = self._take(1)[0]
            value |= (b & 0x7F) << shift
            if not b & 0x80:
                return value
            shift += 7
            if shift > 63:
                raise ValueError("varint too long")

    def varint(self) -> int:
        u = self.uvarint()
        return (u >> 1) ^ -(u & 1)

    def varlong(self) -> int:
        return self.varint()

    # KIP-482 flexible (compact) encodings: length+1 as uvarint, 0=null
    def compact_string(self) -> str:
        n = self.uvarint()
        if n == 0:
            raise ValueError("non-nullable compact string was null")
        return self._take(n - 1).decode("utf-8")

    def compact_nullable_string(self) -> str | None:
        n = self.uvarint()
        if n == 0:
            return None
        return self._take(n - 1).decode("utf-8")

    def compact_bytes(self) -> bytes:
        n = self.uvarint()
        if n == 0:
            raise ValueError("non-nullable compact bytes was null")
        return self._take(n - 1)

    def compact_nullable_bytes(self) -> bytes | None:
        n = self.uvarint()
        if n == 0:
            return None
        return self._take(n - 1)

    def compact_array(self, fn) -> list:
        n = self.uvarint()
        if n == 0:
            return []
        return [fn() for _ in range(n - 1)]

    def tagged_fields(self) -> None:
        """Skip a tagged-field section (we define none)."""
        for _ in range(self.uvarint()):
            self.uvarint()  # tag
            size = self.uvarint()
            self._take(size)


class Writer:
    def __init__(self):
        self.parts: list[bytes] = []

    def raw(self, b: bytes) -> "Writer":
        self.parts.append(b)
        return self

    def i8(self, v: int) -> "Writer":
        return self.raw(struct.pack(">b", v))

    def i16(self, v: int) -> "Writer":
        return self.raw(struct.pack(">h", v))

    def i32(self, v: int) -> "Writer":
        return self.raw(struct.pack(">i", v))

    def i64(self, v: int) -> "Writer":
        return self.raw(struct.pack(">q", v))

    def u32(self, v: int) -> "Writer":
        return self.raw(struct.pack(">I", v))

    def string(self, s: str) -> "Writer":
        b = s.encode("utf-8")
        return self.i16(len(b)).raw(b)

    def nullable_string(self, s: str | None) -> "Writer":
        if s is None:
            return self.i16(-1)
        return self.string(s)

    def bytes_(self, b: bytes) -> "Writer":
        return self.i32(len(b)).raw(b)

    def nullable_bytes(self, b: bytes | None) -> "Writer":
        if b is None:
            return self.i32(-1)
        return self.bytes_(b)

    def array(self, items, fn) -> "Writer":
        self.i32(len(items))
        for it in items:
            fn(self, it)
        return self

    # KIP-482 flexible (compact) encodings
    def uvarint(self, v: int) -> "Writer":
        return self.raw(write_uvarint(v))

    def compact_string(self, s: str) -> "Writer":
        b = s.encode("utf-8")
        return self.uvarint(len(b) + 1).raw(b)

    def compact_nullable_string(self, s: str | None) -> "Writer":
        if s is None:
            return self.uvarint(0)
        return self.compact_string(s)

    def compact_bytes(self, b: bytes) -> "Writer":
        return self.uvarint(len(b) + 1).raw(b)

    def compact_nullable_bytes(self, b: bytes | None) -> "Writer":
        if b is None:
            return self.uvarint(0)
        return self.compact_bytes(b)

    def compact_array(self, items, fn) -> "Writer":
        self.uvarint(len(items) + 1)
        for it in items:
            fn(self, it)
        return self

    def tags(self) -> "Writer":
        """Empty tagged-field section."""
        return self.raw(b"\x00")

    def done(self) -> bytes:
        return b"".join(self.parts)


def write_uvarint(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def write_varint(v: int) -> bytes:
    return write_uvarint((v << 1) ^ (v >> 63) if v < 0 else (v << 1))


# api keys
PRODUCE = 0
FETCH = 1
LIST_OFFSETS = 2
METADATA = 3
OFFSET_COMMIT = 8
OFFSET_FETCH = 9
FIND_COORDINATOR = 10
JOIN_GROUP = 11
HEARTBEAT = 12
LEAVE_GROUP = 13
SYNC_GROUP = 14
DESCRIBE_GROUPS = 15
LIST_GROUPS = 16
API_VERSIONS = 18
CREATE_TOPICS = 19
DELETE_TOPICS = 20

API_NAMES = {
    PRODUCE: "Produce",
    FETCH: "Fetch",
    LIST_OFFSETS: "ListOffsets",
    METADATA: "Metadata",
    OFFSET_COMMIT: "OffsetCommit",
    OFFSET_FETCH: "OffsetFetch",
    FIND_COORDINATOR: "FindCoordinator",
    JOIN_GROUP: "JoinGroup",
    HEARTBEAT: "Heartbeat",
    LEAVE_GROUP: "LeaveGroup",
    SYNC_GROUP: "SyncGroup",
    DESCRIBE_GROUPS: "DescribeGroups",
    LIST_GROUPS: "ListGroups",
    API_VERSIONS: "ApiVersions",
    CREATE_TOPICS: "CreateTopics",
    DELETE_TOPICS: "DeleteTopics",
}

# error codes (kafka protocol)
NONE = 0
OFFSET_OUT_OF_RANGE = 1
CORRUPT_MESSAGE = 2
UNKNOWN_TOPIC_OR_PARTITION = 3
REQUEST_TIMED_OUT = 7  # retriable; the saturation-reject answer
KAFKA_STORAGE_ERROR = 56  # retriable; a failed group-commit window
COORDINATOR_NOT_AVAILABLE = 15
NOT_COORDINATOR = 16
INVALID_TOPIC_EXCEPTION = 17
ILLEGAL_GENERATION = 22
INCONSISTENT_GROUP_PROTOCOL = 23
UNKNOWN_MEMBER_ID = 25
INVALID_SESSION_TIMEOUT = 26
REBALANCE_IN_PROGRESS = 27
TOPIC_ALREADY_EXISTS = 36
INVALID_REQUEST = 42
UNSUPPORTED_VERSION = 35
UNSUPPORTED_COMPRESSION_TYPE = 76
INVALID_RECORD = 87
