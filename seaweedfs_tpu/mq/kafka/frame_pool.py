"""Bounded worker-pool TCP front end for length-prefixed Kafka framing.

The gateway's original accept loop spawned one daemon thread per
connection and held it for the connection's whole life — the same
unbounded-growth failure mode ``utils/http_pool.py`` removed from the
HTTP data planes (ISSUE 11), plus a hygiene hole: a client that died
mid-frame parked its thread in a timeout-less ``recv`` forever.
:class:`PooledFrameServer` is that pool/parked-selector design
generalized to the Kafka wire format (i32 length prefix | frame):

- a FIXED worker pool (``workers``) handles frames; a connection
  occupies a worker only while a frame is actually being served;
- between frames the connection is PARKED in a selector — thousands of
  idle consumers cost file descriptors, not threads;
- a bounded admission budget (``workers + accept_queue`` live
  connections): past it, the first frame of a new connection is
  answered with a WELL-FORMED Kafka response (per-api error +
  throttle_time, built by the gateway's ``reject_handler``) and the
  connection is closed — explicit saturation backpressure a Kafka
  client parses and backs off from, instead of silent thread pile-up;
- connection hygiene: the frame length prefix is validated BEFORE any
  allocation (``max_frame_bytes`` cap), and every read runs under
  ``request_timeout`` so a peer dying mid-frame costs one timeout, not
  a stuck thread;
- zero-copy egress: a handler may return :class:`Parts` — a mix of
  byte chunks and :class:`FileExtent` spans — which the server sends
  via the native ``sn_sendv``/``sn_send_file`` plane when available,
  falling back to plain socket writes emitting the SAME wire bytes.

``workers=0`` opts out to :class:`NaiveFrameServer`, the original
thread-per-connection shape (kept as the bench baseline — the thing
``mq_sustained`` measures the pool against).
"""

from __future__ import annotations

import os
import queue
import selectors
import socket
import struct
import threading
import time

from ...faults import registry as faults
from ...utils.glog import logger

log = logger("kafka.pool")

_MAX_FRAMES_PER_DISPATCH = 32
_IDLE_SWEEP_INTERVAL = 5.0

# Below this many payload bytes a response is cheaper to push through
# the interpreter than to flush + cross the ctypes boundary (same
# threshold rationale as http_pool._NATIVE_BODY_MIN).
_NATIVE_MIN = 8 << 10


def default_workers() -> int:
    return int(os.environ.get("SEAWEED_MQ_KAFKA_WORKERS", "16"))


def default_accept_queue() -> int:
    return int(os.environ.get("SEAWEED_MQ_KAFKA_QUEUE", "64"))


def max_frame_bytes() -> int:
    return int(os.environ.get("SEAWEED_MQ_KAFKA_MAX_FRAME_MB", "64")) << 20


class FileExtent:
    """A [offset, offset+length) span of an on-disk file to egress
    verbatim — the zero-copy half of a fetch response."""

    __slots__ = ("path", "offset", "length")

    def __init__(self, path: str, offset: int, length: int):
        self.path = path
        self.offset = offset
        self.length = length

    def __len__(self) -> int:
        return self.length

    def read(self) -> bytes:
        with open(self.path, "rb") as f:
            f.seek(self.offset)
            return f.read(self.length)


class Parts:
    """An ordered response body: bytes chunks and FileExtents. The
    frame server length-prefixes the total and sends each part in
    order; which plane carries each part is an egress detail that never
    changes the wire bytes."""

    __slots__ = ("parts", "api")

    def __init__(self, parts=None, api: str = ""):
        self.parts = [p for p in (parts or []) if len(p)]
        self.api = api  # metrics attribution ("fetch", ...)

    def append(self, part) -> None:
        if len(part):
            self.parts.append(part)

    def total(self) -> int:
        return sum(len(p) for p in self.parts)


def _native_mod():
    if os.environ.get("SEAWEED_EC_NATIVE", "1") == "0":
        return None
    try:
        from ...utils import native

        return native
    except ImportError:
        return None


class _FConn:
    """One live client connection: socket, per-connection handler
    state (the gateway keeps request context here), idle bookkeeping."""

    __slots__ = ("sock", "state", "last_active")

    def __init__(self, sock):
        self.sock = sock
        self.state = {}
        self.last_active = time.monotonic()


def _read_exact(sock: socket.socket, n: int) -> bytes | None:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


def read_frame(sock: socket.socket, cap: int) -> bytes | None:
    """One length-prefixed frame, or None on EOF / bad prefix. The
    length is validated against `cap` BEFORE any payload allocation —
    an adversarial 2 GiB prefix costs 4 bytes of reading, not memory."""
    head = _read_exact(sock, 4)
    if head is None:
        return None
    (size,) = struct.unpack(">i", head)
    if size <= 0 or size > cap:
        return None
    return _read_exact(sock, size)


def send_response(sock: socket.socket, resp, timeout_ms: int = -1) -> int:
    """Length-prefix + send a handler response (bytes or Parts).
    Returns how many payload bytes went out on the native plane (0 on
    the Python fallback). FileExtent parts go kernel-to-kernel via
    sn_send_file when the native plane is up; byte chunks via sn_sendv;
    the Python fallback reads and sendall()s the SAME bytes. Raises
    OSError on a broken send — the framing is dead, the caller closes
    the connection."""
    if isinstance(resp, Parts):
        parts = resp.parts
    else:
        parts = [resp] if len(resp) else []
    total = sum(len(p) for p in parts)
    prefix = struct.pack(">i", total)
    native = _native_mod() if total >= _NATIVE_MIN else None
    if native is None:
        buf = bytearray(prefix)
        for p in parts:
            buf += p.read() if isinstance(p, FileExtent) else p
        sock.sendall(buf)
        return 0
    # native plane: coalesce adjacent byte chunks into one sendv, ship
    # file extents straight from the page cache
    fd = sock.fileno()
    native_sent = 0
    pending: list = [prefix]
    for p in parts:
        if isinstance(p, FileExtent):
            if pending:
                native_sent += native.sendv(fd, pending, timeout_ms=timeout_ms)
                pending = []
            in_f = open(p.path, "rb")
            try:
                sent = native.send_file(
                    fd, in_f.fileno(), p.offset, p.length, timeout_ms=timeout_ms
                )
            finally:
                in_f.close()
            if sent != p.length:
                raise OSError(
                    f"short sendfile {sent}/{p.length} for {p.path}"
                )
            native_sent += sent
        else:
            pending.append(p)
    if pending:
        native_sent += native.sendv(fd, pending, timeout_ms=timeout_ms)
    return max(native_sent - len(prefix), 0)


def _account(resp, native_sent: int) -> None:
    """Per-plane byte accounting for fetch responses (the api tag is
    set only by the fetch handler)."""
    if not isinstance(resp, Parts) or resp.api != "fetch":
        return
    from ...utils import metrics

    total = resp.total()
    if native_sent > 0:
        metrics.mq_fetch_bytes_total.inc(native_sent, plane="native")
    if total - native_sent > 0:
        metrics.mq_fetch_bytes_total.inc(total - native_sent, plane="python")


class PooledFrameServer:
    """The bounded front end. `handler(state, frame) -> bytes | Parts |
    None` serves one frame (None = no response frame, the acks=0
    produce case); `reject_handler(state, frame)` builds the
    well-formed saturation response for the first frame of an
    over-budget connection."""

    def __init__(
        self,
        sock: socket.socket,
        handler,
        reject_handler=None,
        workers: int = 16,
        accept_queue: int = 64,
        idle_timeout: float = 30.0,
        request_timeout: float = 120.0,
        server_kind: str = "kafka",
    ):
        self.sock = sock
        self.handler = handler
        self.reject_handler = reject_handler
        self.workers = max(1, int(workers))
        self.accept_queue = max(0, int(accept_queue))
        self.max_connections = self.workers + self.accept_queue
        self.idle_timeout = float(idle_timeout)
        self.request_timeout = float(request_timeout)
        self.server_kind = server_kind
        self._ready: "queue.Queue[_FConn | None]" = queue.Queue()
        self._park_q: "queue.Queue[_FConn]" = queue.Queue()
        self._conns: set[_FConn] = set()
        self._conns_lock = threading.Lock()
        self._stop_evt = threading.Event()
        self._loop_done = threading.Event()
        self._loop_done.set()
        self._threads: list[threading.Thread] = []
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        # a few threads may be busy answering rejects; never unbounded
        self._reject_slots = threading.Semaphore(4)
        self.rejected = 0
        self.frames_served = 0

    # ----------------------------------------------------------- lifecycle

    def start(self) -> None:
        self._stop_evt.clear()
        self._loop_done.clear()
        self._threads = [
            threading.Thread(
                target=self._worker,
                name=f"kafka-pool-{i}",
                daemon=True,
            )
            for i in range(self.workers)
        ]
        for t in self._threads:
            t.start()
        threading.Thread(
            target=self._loop, name="kafka-pool-loop", daemon=True
        ).start()

    def stop(self) -> None:
        self._stop_evt.set()
        self._wake()
        try:
            self.sock.close()
        except OSError:
            pass
        self._loop_done.wait(timeout=10.0)
        with self._conns_lock:
            leftover = list(self._conns)
        for c in leftover:
            self._close_conn(c)
        for s in (self._wake_r, self._wake_w):
            try:
                s.close()
            except OSError:
                pass

    def _loop(self) -> None:
        sel = selectors.DefaultSelector()
        self.sock.setblocking(False)
        try:
            sel.register(self.sock, selectors.EVENT_READ, "accept")
        except (ValueError, OSError):
            self._loop_done.set()
            return
        sel.register(self._wake_r, selectors.EVENT_READ, "wake")
        last_sweep = time.monotonic()
        try:
            while not self._stop_evt.is_set():
                for key, _ in sel.select(timeout=0.5):
                    if key.data == "accept":
                        self._accept()
                    elif key.data == "wake":
                        self._drain_wake(sel)
                    else:
                        sel.unregister(key.fileobj)
                        conn = key.data
                        conn.last_active = time.monotonic()
                        self._ready.put(conn)
                now = time.monotonic()
                if now - last_sweep >= _IDLE_SWEEP_INTERVAL:
                    last_sweep = now
                    self._sweep_idle(sel)
        finally:
            for _t in self._threads:
                self._ready.put(None)
            for key in list(sel.get_map().values()):
                if isinstance(key.data, _FConn):
                    self._close_conn(key.data)
            sel.close()
            for t in self._threads:
                t.join(timeout=2.0)
            while True:
                try:
                    c = self._ready.get_nowait()
                except queue.Empty:
                    break
                if c is not None:
                    self._close_conn(c)
            self._loop_done.set()

    # ------------------------------------------------------------- accept

    def _accept(self) -> None:
        while True:
            try:
                sock, addr = self.sock.accept()
            except (BlockingIOError, InterruptedError, OSError):
                return
            with self._conns_lock:
                saturated = len(self._conns) >= self.max_connections
            try:
                faults.fire(
                    "mq.gateway.accept", addr=addr, saturated=saturated
                )
            except Exception:
                try:
                    sock.close()
                except OSError:
                    pass
                continue
            if saturated:
                self._reject(sock)
                continue
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                sock.settimeout(self.request_timeout)
            except OSError:
                try:
                    sock.close()
                except OSError:
                    pass
                continue
            conn = _FConn(sock)
            with self._conns_lock:
                self._conns.add(conn)
            self._park_q.put(conn)
            self._wake()

    def _reject(self, sock: socket.socket) -> None:
        """Explicit saturation backpressure: answer the connection's
        FIRST frame with a well-formed per-api Kafka response carrying
        an error/throttle (built by the gateway), then close. Runs on a
        short-lived thread so the selector loop never blocks on a slow
        rejected peer; reject threads are capped — beyond the cap the
        socket is simply closed (the client sees a retriable reset)."""
        self.rejected += 1
        from ...utils import metrics

        metrics.gateway_rejected_total.inc(server=self.server_kind)
        if self.reject_handler is None or not self._reject_slots.acquire(
            blocking=False
        ):
            try:
                sock.close()
            except OSError:
                pass
            return

        def answer():
            try:
                sock.settimeout(2.0)
                frame = read_frame(sock, max_frame_bytes())
                if frame is not None:
                    resp = self.reject_handler({}, frame)
                    if resp is not None:
                        send_response(sock, resp, timeout_ms=2000)
            except (OSError, EOFError, ValueError):
                pass
            finally:
                self._reject_slots.release()
                try:
                    sock.close()
                except OSError:
                    pass

        threading.Thread(target=answer, daemon=True).start()

    # ----------------------------------------------------------- dispatch

    def _worker(self) -> None:
        while True:
            conn = self._ready.get()
            if conn is None:
                return
            try:
                self._serve_dispatch(conn)
            except Exception:
                self._close_conn(conn)

    def _serve_dispatch(self, conn: _FConn) -> None:
        from ...utils import metrics

        for _ in range(_MAX_FRAMES_PER_DISPATCH):
            try:
                conn.sock.settimeout(self.request_timeout)
                frame = read_frame(conn.sock, max_frame_bytes())
            except (OSError, ValueError):
                frame = None
            if frame is None:
                self._close_conn(conn)
                return
            metrics.gateway_inflight.inc(server=self.server_kind)
            try:
                resp = self.handler(conn.state, frame)
                if resp is not None:
                    native_sent = send_response(
                        conn.sock,
                        resp,
                        timeout_ms=int(self.request_timeout * 1000),
                    )
                    _account(resp, native_sent)
                with self._conns_lock:
                    self.frames_served += 1
            except (OSError, EOFError, ValueError, struct.error) as e:
                log.v(1, "connection dropped: %s", e)
                self._close_conn(conn)
                return
            finally:
                metrics.gateway_inflight.dec(server=self.server_kind)
            if not self._readable_now(conn):
                conn.last_active = time.monotonic()
                self._park_q.put(conn)
                self._wake()
                return
        # fairness: a client with more buffered frames goes to the back
        # of the ready queue instead of monopolizing this worker
        self._ready.put(conn)

    def _readable_now(self, conn: _FConn) -> bool:
        try:
            conn.sock.setblocking(False)
        except OSError:
            return False
        try:
            return bool(conn.sock.recv(1, socket.MSG_PEEK))
        except (BlockingIOError, InterruptedError):
            return False
        except OSError:
            return False
        finally:
            try:
                conn.sock.settimeout(self.request_timeout)
            except OSError:
                pass

    # ------------------------------------------------------------ parking

    def _wake(self) -> None:
        try:
            self._wake_w.send(b"x")
        except OSError:
            pass

    def _drain_wake(self, sel) -> None:
        try:
            while self._wake_r.recv(4096):
                pass
        except (BlockingIOError, InterruptedError, OSError):
            pass
        while True:
            try:
                conn = self._park_q.get_nowait()
            except queue.Empty:
                return
            try:
                sel.register(conn.sock, selectors.EVENT_READ, conn)
            except (ValueError, KeyError, OSError):
                self._close_conn(conn)

    def _sweep_idle(self, sel) -> None:
        now = time.monotonic()
        for key in list(sel.get_map().values()):
            conn = key.data
            if not isinstance(conn, _FConn):
                continue
            if now - conn.last_active > self.idle_timeout:
                try:
                    sel.unregister(key.fileobj)
                except (KeyError, ValueError):
                    continue
                self._close_conn(conn)

    def _close_conn(self, conn: _FConn) -> None:
        with self._conns_lock:
            self._conns.discard(conn)
        try:
            conn.sock.close()
        except OSError:
            pass

    # ------------------------------------------------------------- status

    def suggested_throttle_ms(self) -> int:
        """Backpressure hint for response throttle_time_ms: 0 while the
        pool has headroom, ramping with the ready backlog once frames
        queue behind busy workers."""
        backlog = self._ready.qsize()
        if backlog <= self.workers:
            return 0
        return min(1000, (backlog - self.workers) * 10)

    def pool_status(self) -> dict:
        with self._conns_lock:
            open_conns = len(self._conns)
            served = self.frames_served
        return {
            "kind": "pooled",
            "server": self.server_kind,
            "workers": self.workers,
            "accept_queue": self.accept_queue,
            "max_connections": self.max_connections,
            "open_connections": open_conns,
            "ready_backlog": self._ready.qsize(),
            "frames_served": served,
            "rejected_total": self.rejected,
            "throttle_ms": self.suggested_throttle_ms(),
        }


class NaiveFrameServer:
    """The original thread-per-connection accept loop, kept behind
    ``SEAWEED_MQ_KAFKA_WORKERS=0`` as the measured baseline. Frame
    reads still go through the capped/timed `read_frame` (hygiene is
    not optional), but there is no admission budget, no parking, no
    backpressure — every connection owns a thread for life."""

    def __init__(
        self,
        sock: socket.socket,
        handler,
        reject_handler=None,
        request_timeout: float = 120.0,
        server_kind: str = "kafka",
        **_ignored,
    ):
        self.sock = sock
        self.handler = handler
        self.request_timeout = float(request_timeout)
        self.server_kind = server_kind
        self._stop_evt = threading.Event()
        self.frames_served = 0
        self._conns = 0
        self._lock = threading.Lock()

    def start(self) -> None:
        threading.Thread(
            target=self._accept_loop, name="kafka-naive-accept", daemon=True
        ).start()

    def stop(self) -> None:
        self._stop_evt.set()
        try:
            self.sock.close()
        except OSError:
            pass

    def _accept_loop(self) -> None:
        while not self._stop_evt.is_set():
            try:
                conn, addr = self.sock.accept()
            except OSError:
                return
            try:
                faults.fire("mq.gateway.accept", addr=addr, saturated=False)
            except Exception:
                try:
                    conn.close()
                except OSError:
                    pass
                continue
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            ).start()

    def _serve_conn(self, sock: socket.socket) -> None:
        state: dict = {}
        with self._lock:
            self._conns += 1
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            sock.settimeout(self.request_timeout)
            while not self._stop_evt.is_set():
                frame = read_frame(sock, max_frame_bytes())
                if frame is None:
                    return
                resp = self.handler(state, frame)
                if resp is not None:
                    native_sent = send_response(
                        sock, resp, timeout_ms=int(self.request_timeout * 1000)
                    )
                    _account(resp, native_sent)
                with self._lock:
                    self.frames_served += 1
        except (OSError, EOFError, ValueError, struct.error) as e:
            log.v(1, "connection dropped: %s", e)
        finally:
            with self._lock:
                self._conns -= 1
            try:
                sock.close()
            except OSError:
                pass

    def suggested_throttle_ms(self) -> int:
        return 0

    def pool_status(self) -> dict:
        with self._lock:
            return {
                "kind": "naive",
                "server": self.server_kind,
                "workers": 0,
                "accept_queue": 0,
                "max_connections": -1,
                "open_connections": self._conns,
                "ready_backlog": 0,
                "frames_served": self.frames_served,
                "rejected_total": 0,
                "throttle_ms": 0,
            }


def build_frame_server(
    sock: socket.socket,
    handler,
    reject_handler=None,
    workers: int | None = None,
    accept_queue: int | None = None,
    request_timeout: float = 120.0,
    idle_timeout: float = 30.0,
    server_kind: str = "kafka",
):
    """Factory mirroring ``utils/http_pool.build_http_server``: the
    pooled server unless workers resolves to 0 (explicit opt-out to the
    unbounded thread-per-connection baseline)."""
    if workers is None:
        workers = default_workers()
    if accept_queue is None:
        accept_queue = default_accept_queue()
    cls = PooledFrameServer if workers else NaiveFrameServer
    return cls(
        sock,
        handler,
        reject_handler=reject_handler,
        workers=workers,
        accept_queue=accept_queue,
        request_timeout=request_timeout,
        idle_timeout=idle_timeout,
        server_kind=server_kind,
    )
