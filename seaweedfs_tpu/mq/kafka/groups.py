"""Classic Kafka consumer-group coordination (client-side assignment).

Reference: weed/mq/kafka/consumer — the JoinGroup/SyncGroup protocol:
the coordinator only herds members through a rebalance and relays the
leader-computed assignment; it never parses the embedded protocol
metadata. States per group: Empty → PreparingRebalance →
CompletingRebalance → Stable (same names as Kafka's GroupCoordinator).
"""

from __future__ import annotations

import threading
import time
import uuid
from dataclasses import dataclass, field

from . import protocol as kp

EMPTY = "Empty"
PREPARING = "PreparingRebalance"
COMPLETING = "CompletingRebalance"
STABLE = "Stable"

# how long a rebalance waits for the rest of the herd after the first
# join (the broker's group.initial.rebalance.delay.ms analog)
JOIN_SETTLE_SECONDS = 0.3


@dataclass
class Member:
    member_id: str
    client_id: str
    session_timeout: float
    protocols: list[tuple[str, bytes]]
    last_seen: float = field(default_factory=time.monotonic)
    assignment: bytes = b""
    joined_generation: int = -1


class Group:
    def __init__(self, group_id: str):
        self.group_id = group_id
        self.lock = threading.Condition()
        self.state = EMPTY
        self.generation = 0
        self.protocol_type = ""
        self.protocol_name = ""
        self.leader = ""
        self.members: dict[str, Member] = {}
        self._join_deadline = 0.0

    # ----------------------------------------------------------- joining

    def join(
        self,
        member_id: str,
        client_id: str,
        protocol_type: str,
        protocols: list[tuple[str, bytes]],
        session_timeout: float,
        rebalance_timeout: float,
    ) -> dict:
        """Blocks until the rebalance completes; returns the JoinGroup
        response fields."""
        with self.lock:
            if self.protocol_type and protocol_type != self.protocol_type:
                return {"error": kp.INCONSISTENT_GROUP_PROTOCOL}
            self.protocol_type = protocol_type
            if not member_id:
                member_id = f"{client_id or 'member'}-{uuid.uuid4().hex[:12]}"
            m = self.members.get(member_id)
            if m is None:
                m = Member(member_id, client_id, session_timeout, protocols)
                self.members[member_id] = m
            else:
                m.protocols = protocols
                m.session_timeout = session_timeout
            m.last_seen = time.monotonic()
            # any (re)join forces a new round
            if self.state in (EMPTY, STABLE, COMPLETING):
                self.state = PREPARING
                self._join_deadline = time.monotonic() + JOIN_SETTLE_SECONDS
                self.lock.notify_all()
            else:
                # extend the settle window for stragglers
                self._join_deadline = max(
                    self._join_deadline,
                    time.monotonic() + JOIN_SETTLE_SECONDS,
                )
            target_gen = self.generation + 1
            deadline = time.monotonic() + max(rebalance_timeout, 1.0)
            while True:
                if self.state == PREPARING:
                    now = time.monotonic()
                    if now >= self._join_deadline and all(
                        mm.last_seen >= now - mm.session_timeout
                        for mm in self.members.values()
                    ):
                        self._complete_join_locked()
                if (
                    self.state in (COMPLETING, STABLE)
                    and self.generation >= target_gen
                ):
                    break
                if time.monotonic() > deadline:
                    return {"error": kp.REBALANCE_IN_PROGRESS}
                self.lock.wait(timeout=0.05)
            m.joined_generation = self.generation
            resp = {
                "error": kp.NONE,
                "generation": self.generation,
                "protocol": self.protocol_name,
                "leader": self.leader,
                "member_id": member_id,
                "members": [],
            }
            if member_id == self.leader:
                resp["members"] = [
                    (mm.member_id, self._metadata_for(mm))
                    for mm in self.members.values()
                ]
            return resp

    def _metadata_for(self, m: Member) -> bytes:
        for name, meta in m.protocols:
            if name == self.protocol_name:
                return meta
        return m.protocols[0][1] if m.protocols else b""

    def _complete_join_locked(self) -> None:
        # drop members that never re-joined this round
        now = time.monotonic()
        self.members = {
            mid: m
            for mid, m in self.members.items()
            if m.last_seen >= now - m.session_timeout
        }
        if not self.members:
            self.state = EMPTY
            return
        # choose the protocol every member supports (first of leader's)
        common = None
        for m in self.members.values():
            names = [n for n, _ in m.protocols]
            common = names if common is None else [
                n for n in common if n in names
            ]
        self.protocol_name = common[0] if common else ""
        self.generation += 1
        self.leader = next(iter(self.members))
        self.state = COMPLETING
        self.lock.notify_all()

    # ------------------------------------------------------------ syncing

    def sync(
        self,
        member_id: str,
        generation: int,
        assignments: list[tuple[str, bytes]],
    ) -> tuple[int, bytes]:
        with self.lock:
            m = self.members.get(member_id)
            if m is None:
                return kp.UNKNOWN_MEMBER_ID, b""
            if generation != self.generation:
                return kp.ILLEGAL_GENERATION, b""
            if member_id == self.leader and assignments:
                for mid, blob in assignments:
                    if mid in self.members:
                        self.members[mid].assignment = blob
                self.state = STABLE
                self.lock.notify_all()
            deadline = time.monotonic() + 30.0
            while self.state == COMPLETING and self.generation == generation:
                if time.monotonic() > deadline:
                    return kp.REBALANCE_IN_PROGRESS, b""
                self.lock.wait(timeout=0.05)
            if self.generation != generation:
                return kp.REBALANCE_IN_PROGRESS, b""
            m.last_seen = time.monotonic()
            return kp.NONE, m.assignment

    # --------------------------------------------------------- liveness

    def heartbeat(self, member_id: str, generation: int) -> int:
        with self.lock:
            m = self.members.get(member_id)
            if m is None:
                return kp.UNKNOWN_MEMBER_ID
            m.last_seen = time.monotonic()
            if generation != self.generation:
                return kp.ILLEGAL_GENERATION
            if self.state in (PREPARING,):
                return kp.REBALANCE_IN_PROGRESS
            return kp.NONE

    def leave(self, member_id: str) -> int:
        with self.lock:
            if self.members.pop(member_id, None) is None:
                return kp.UNKNOWN_MEMBER_ID
            if self.state == STABLE and self.members:
                self.state = PREPARING
                self._join_deadline = (
                    time.monotonic() + JOIN_SETTLE_SECONDS
                )
            elif not self.members:
                self.state = EMPTY
            self.lock.notify_all()
            return kp.NONE

    def expire_dead_members(self) -> None:
        with self.lock:
            now = time.monotonic()
            dead = [
                mid
                for mid, m in self.members.items()
                if m.last_seen < now - m.session_timeout
            ]
            if not dead or self.state == PREPARING:
                return
            for mid in dead:
                del self.members[mid]
            if self.members:
                self.state = PREPARING
                self._join_deadline = now + JOIN_SETTLE_SECONDS
            else:
                self.state = EMPTY
            self.lock.notify_all()


class GroupCoordinator:
    def __init__(self):
        self._lock = threading.Lock()
        self.groups: dict[str, Group] = {}
        self._stop = threading.Event()
        self._reaper = threading.Thread(target=self._reap, daemon=True)
        self._reaper.start()

    def group(self, group_id: str) -> Group:
        """Get-or-create: only JoinGroup may instantiate a group."""
        with self._lock:
            g = self.groups.get(group_id)
            if g is None:
                g = Group(group_id)
                self.groups[group_id] = g
            return g

    def lookup(self, group_id: str) -> Group | None:
        """Non-creating lookup for heartbeat/sync/leave/describe — an
        unknown group must not leak a Group object per probe."""
        with self._lock:
            return self.groups.get(group_id)

    def list_groups(self) -> list[tuple[str, str]]:
        with self._lock:
            return [
                (g.group_id, g.protocol_type)
                for g in self.groups.values()
                if g.members
            ]

    def stop(self) -> None:
        self._stop.set()

    def _reap(self) -> None:
        while not self._stop.wait(1.0):
            with self._lock:
                groups = list(self.groups.values())
            for g in groups:
                g.expire_dead_members()
            # drop long-empty groups so probes/one-shot consumers don't
            # grow the dict for the life of the process
            with self._lock:
                for gid in [
                    gid
                    for gid, g in self.groups.items()
                    if g.state == EMPTY and not g.members
                ]:
                    del self.groups[gid]
