"""The Kafka wire-protocol gateway server.

Reference: weed/mq/kafka/gateway/server.go + protocol/ handlers — a TCP
listener speaking the Kafka binary protocol, mapping topics onto the
MqBroker's partition logs (namespace "kafka"). Kafka clients configure
it as a single-broker cluster: this gateway is every partition's leader
and every group's coordinator.

Framing: i32 length | request header (api_key i16, api_version i16,
correlation_id i32, client_id nullable-string) | body. Responses:
i32 length | correlation_id i32 | body. Only non-flexible request
versions are advertised (see _API_RANGES), so tagged fields never
appear on the wire.

Front end (ISSUE 20): connections are served by the bounded
worker-pool frame server (``frame_pool.PooledFrameServer``) instead of
one thread per connection. Saturation is answered with well-formed
per-api error/throttle responses (``_handle_reject``), pool pressure
surfaces as ``throttle_time_ms`` in every response, fetches of sealed
segments egress zero-copy via the batch spool, and durable-parity
produces ride the broker group committer.
"""

from __future__ import annotations

import socket
import struct
import threading
import time

from ...faults import registry as faults
from ...utils.glog import logger
from . import protocol as kp
from .fetch_spool import FetchSpool
from .frame_pool import Parts, build_frame_server
from .groups import GroupCoordinator
from .protocol import Reader, Writer
from .records import Record, UnsupportedCompression, decode_batches, encode_batch

log = logger("kafka")

NAMESPACE = "kafka"

# api_key -> (min_version, max_version) actually implemented
_API_RANGES: dict[int, tuple[int, int]] = {
    kp.PRODUCE: (3, 9),
    kp.FETCH: (4, 11),
    kp.LIST_OFFSETS: (0, 5),
    kp.METADATA: (0, 8),
    kp.OFFSET_COMMIT: (0, 7),
    kp.OFFSET_FETCH: (0, 5),
    kp.FIND_COORDINATOR: (0, 2),
    kp.JOIN_GROUP: (0, 5),
    kp.HEARTBEAT: (0, 3),
    kp.LEAVE_GROUP: (0, 3),
    kp.SYNC_GROUP: (0, 3),
    kp.DESCRIBE_GROUPS: (0, 4),
    kp.LIST_GROUPS: (0, 2),
    kp.API_VERSIONS: (0, 3),
    kp.CREATE_TOPICS: (0, 4),
    kp.DELETE_TOPICS: (0, 3),
}

# First FLEXIBLE (KIP-482 compact/tagged) version per api. Requests at
# or above it use request-header v2 (tagged fields after client_id) and
# response-header v1 — except ApiVersions, whose response header stays
# v0 so a downgrading client can always parse it.
_FLEXIBLE: dict[int, int] = {
    kp.PRODUCE: 9,
    kp.API_VERSIONS: 3,
}

NODE_ID = 0


class KafkaGateway:
    def __init__(
        self,
        broker,
        ip: str = "localhost",
        port: int = 9092,
        advertised_host: str | None = None,
        auto_create_partitions: int = 1,
        workers: int | None = None,
    ):
        self.broker = broker
        self.ip = ip
        self.advertised_host = advertised_host or ip
        self.auto_create_partitions = auto_create_partitions
        self.coordinator = GroupCoordinator()
        # Per-REQUEST context: every frame carries its own header, and a
        # frame is handled start-to-finish on one worker thread, so a
        # thread-local set at frame entry stays correct under the pool.
        self._tl = threading.local()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((ip, port))
        self.port = self._sock.getsockname()[1]
        self._sock.listen(64)
        self.spool = FetchSpool()
        self.server = build_frame_server(
            self._sock,
            self._handle,
            reject_handler=self._handle_reject,
            workers=workers,
            request_timeout=30.0,
            server_kind="kafka",
        )

    # ---------------------------------------------------------- lifecycle

    def start(self) -> None:
        self.server.start()

    def stop(self) -> None:
        self.coordinator.stop()
        self.server.stop()
        self.spool.close()

    def pool_status(self) -> dict:
        st = self.server.pool_status()
        st["fetch_spool"] = self.spool.status()
        return st

    # --------------------------------------------------------- connection

    def _handle(self, state: dict, frame: bytes) -> "bytes | Parts | None":
        self._tl.reject = False
        return self._handle_frame(frame)

    def _handle_reject(self, state: dict, frame: bytes) -> "bytes | Parts | None":
        """Saturation path: the frame server is over its admission
        budget, so this (first and only) frame is answered with the
        api's normal response shape carrying a retriable error and a
        non-zero throttle_time — explicit, parseable backpressure —
        and the connection is closed. Data-plane work is skipped
        (produce appends nothing, fetch reads nothing)."""
        self._tl.reject = True
        try:
            return self._handle_frame(frame)
        finally:
            self._tl.reject = False

    def _rejecting(self) -> bool:
        return bool(getattr(self._tl, "reject", False))

    def _throttle_ms(self) -> int:
        """throttle_time_ms for the current response: the reject hint
        when saturated, else the pool's live backpressure suggestion
        (0 while there is headroom — the common case, and the value
        golden tests pin)."""
        if self._rejecting():
            return 1000
        try:
            return self.server.suggested_throttle_ms()
        except Exception:
            return 0

    def _handle_frame(self, frame: bytes) -> "bytes | Parts | None":
        r = Reader(frame)
        api_key = r.i16()
        api_version = r.i16()
        correlation_id = r.i32()
        # client_id (NON-compact even in header v2); kept per-thread —
        # JoinGroup derives generated member ids from it, matching the
        # broker convention "<client.id>-<uuid>"
        self._tl.client_id = r.nullable_string() or ""
        out = Writer().i32(correlation_id)
        lo_hi = _API_RANGES.get(api_key)
        if lo_hi is None or not lo_hi[0] <= api_version <= lo_hi[1]:
            # KIP-511: answer an out-of-range ApiVersions with a v0 body
            # carrying UNSUPPORTED_VERSION + our ranges so the client
            # can downgrade; other apis get the error-only body. The
            # body (and any header tags) of an unknown future version
            # is never parsed — its layout is unknowable.
            if api_key == kp.API_VERSIONS:
                self._api_versions_body(out, 0, kp.UNSUPPORTED_VERSION)
                return out.done()
            out.i16(kp.UNSUPPORTED_VERSION)
            return out.done()
        flexible = api_version >= _FLEXIBLE.get(api_key, 1 << 30)
        if flexible:
            r.tagged_fields()  # request header v2
            if api_key != kp.API_VERSIONS:
                out.tags()  # response header v1
        handler = {
            kp.API_VERSIONS: self._h_api_versions,
            kp.METADATA: self._h_metadata,
            kp.PRODUCE: self._h_produce,
            kp.FETCH: self._h_fetch,
            kp.LIST_OFFSETS: self._h_list_offsets,
            kp.CREATE_TOPICS: self._h_create_topics,
            kp.DELETE_TOPICS: self._h_delete_topics,
            kp.FIND_COORDINATOR: self._h_find_coordinator,
            kp.OFFSET_COMMIT: self._h_offset_commit,
            kp.OFFSET_FETCH: self._h_offset_fetch,
            kp.JOIN_GROUP: self._h_join_group,
            kp.SYNC_GROUP: self._h_sync_group,
            kp.HEARTBEAT: self._h_heartbeat,
            kp.LEAVE_GROUP: self._h_leave_group,
            kp.LIST_GROUPS: self._h_list_groups,
            kp.DESCRIBE_GROUPS: self._h_describe_groups,
        }[api_key]
        body = handler(r, api_version)
        if body is None:  # acks=0 produce: no response frame at all
            return None
        if isinstance(body, Parts):  # zero-copy fetch: header + spans
            body.parts.insert(0, out.done())
            return body
        return out.raw(body).done()

    # ------------------------------------------------------- topic helpers

    def _log_for(self, topic: str, partition: int):
        try:
            st = self.broker.topic(NAMESPACE, topic)
        except KeyError:
            return None
        return st.logs.get(partition)

    def _partitions(self, topic: str) -> int:
        try:
            return self.broker.topic(NAMESPACE, topic).partition_count
        except KeyError:
            return -1

    def _parity_for(self, topic: str, part: int):
        try:
            st = self.broker.topic(NAMESPACE, topic)
        except KeyError:
            return None
        return st.parity.get(part)

    # ----------------------------------------------------------- handlers

    def _api_versions_body(self, w: Writer, version: int, error: int) -> None:
        w.i16(error)
        if version >= 3:
            # flexible body (compact array + per-entry tags)
            w.compact_array(
                sorted(_API_RANGES.items()),
                lambda ww, kv: ww.i16(kv[0])
                .i16(kv[1][0])
                .i16(kv[1][1])
                .tags(),
            )
            w.i32(self._throttle_ms())  # throttle_time_ms
            w.tags()
            return
        w.array(
            sorted(_API_RANGES.items()),
            lambda ww, kv: ww.i16(kv[0]).i16(kv[1][0]).i16(kv[1][1]),
        )
        if version >= 1:
            w.i32(self._throttle_ms())  # throttle_time_ms

    def _h_api_versions(self, r: Reader, v: int) -> bytes:
        if v >= 3:
            r.compact_string()  # client_software_name
            r.compact_string()  # client_software_version
            r.tagged_fields()
        w = Writer()
        self._api_versions_body(w, v, kp.NONE)
        return w.done()

    def _h_metadata(self, r: Reader, v: int) -> bytes:
        n = r.i32()
        wanted: list[str] | None
        if n < 0 or (n == 0 and v == 0):
            # null = all topics (v1+); v0 has no null encoding — an
            # empty array is its only way to say "all topics"
            wanted = None
        else:
            wanted = [r.string() for _ in range(n)]
        allow_auto = True
        if v >= 4:
            allow_auto = r.i8() != 0
        if v >= 8:
            r.i8()  # include_cluster_authorized_operations
            r.i8()  # include_topic_authorized_operations
        existing = {
            name
            for ns, name, _c in self.broker.list_topics()
            if ns == NAMESPACE
        }
        if wanted is None:
            topics = sorted(existing)
        else:
            topics = wanted
            if allow_auto:
                for t in wanted:
                    if t not in existing and _valid_topic(t):
                        self.broker.configure_topic(
                            NAMESPACE, t, self.auto_create_partitions
                        )
                        existing.add(t)
        w = Writer()
        if v >= 3:
            w.i32(self._throttle_ms())  # throttle
        # brokers: just us
        def broker_entry(ww: Writer, _):
            ww.i32(NODE_ID).string(self.advertised_host).i32(self.port)
            if v >= 1:
                ww.nullable_string(None)  # rack

        w.array([None], broker_entry)
        if v >= 2:
            w.nullable_string("seaweedfs-tpu-kafka")  # cluster_id
        if v >= 1:
            w.i32(NODE_ID)  # controller_id

        def topic_entry(ww: Writer, name: str):
            count = self._partitions(name)
            if count < 0:
                ww.i16(
                    kp.INVALID_TOPIC_EXCEPTION
                    if not _valid_topic(name)
                    else kp.UNKNOWN_TOPIC_OR_PARTITION
                )
                ww.string(name)
                if v >= 1:
                    ww.i8(0)  # is_internal
                ww.i32(0)  # empty partitions
                if v >= 8:
                    ww.i32(-2147483648)  # topic_authorized_operations
                return
            ww.i16(kp.NONE).string(name)
            if v >= 1:
                ww.i8(0)

            def part_entry(w3: Writer, p: int):
                w3.i16(kp.NONE).i32(p).i32(NODE_ID)
                if v >= 7:
                    w3.i32(0)  # leader_epoch
                w3.array([NODE_ID], lambda w4, nid: w4.i32(nid))  # replicas
                w3.array([NODE_ID], lambda w4, nid: w4.i32(nid))  # isr
                if v >= 5:
                    w3.array([], lambda w4, nid: w4.i32(nid))  # offline

            ww.array(list(range(count)), part_entry)
            if v >= 8:
                ww.i32(-2147483648)  # topic_authorized_operations (unset)

        w.array(topics, topic_entry)
        if v >= 8:
            w.i32(-2147483648)  # cluster_authorized_operations (unset)
        return w.done()

    def _h_produce(self, r: Reader, v: int) -> bytes | None:
        flex = v >= 9
        if flex:
            r.compact_nullable_string()  # transactional_id
        else:
            r.nullable_string()
        acks = r.i16()
        r.i32()  # timeout_ms
        from ...utils import metrics

        rejecting = self._rejecting()
        results: list[tuple[str, list[tuple[int, int, int]]]] = []
        dirty_parities: list = []
        ntopics = r.uvarint() - 1 if flex else r.i32()
        for _ in range(max(ntopics, 0)):
            topic = r.compact_string() if flex else r.string()
            parts: list[tuple[int, int, int]] = []  # (part, error, base)
            nparts = r.uvarint() - 1 if flex else r.i32()
            for _p in range(max(nparts, 0)):
                part = r.i32()
                blob = (
                    r.compact_nullable_bytes() if flex else r.nullable_bytes()
                ) or b""
                if flex:
                    r.tagged_fields()  # partition-struct tags
                if rejecting:
                    # saturation: parse (the reader must stay in sync)
                    # but append NOTHING — the retriable error + the
                    # throttle are the whole answer
                    parts.append((part, kp.REQUEST_TIMED_OUT, -1))
                    continue
                metrics.mq_produce_bytes_total.inc(
                    len(blob), plane="python"
                )
                plog = self._log_for(topic, part)
                if plog is None:
                    parts.append((part, kp.UNKNOWN_TOPIC_OR_PARTITION, -1))
                    continue
                try:
                    records = decode_batches(blob)
                except UnsupportedCompression:
                    parts.append(
                        (part, kp.UNSUPPORTED_COMPRESSION_TYPE, -1)
                    )
                    continue
                except (ValueError, EOFError, struct.error):
                    # a lying recordCount / truncated post-CRC section
                    # must fail ONE partition, not the connection
                    parts.append((part, kp.CORRUPT_MESSAGE, -1))
                    continue
                # enforced topic schemas apply to the Kafka path too —
                # otherwise any Kafka client could bypass what
                # MqService.Publish rejects (tombstones exempt: a null
                # value deletes, it doesn't carry a document)
                bad = next(
                    (
                        err
                        for rec in records
                        if rec.value is not None
                        and (
                            err := self.broker.validate_against_schema(
                                NAMESPACE, topic, rec.value
                            )
                        )
                    ),
                    "",
                )
                if bad:
                    parts.append((part, kp.INVALID_RECORD, -1))
                    continue
                base = -1
                if records:
                    # one lock hold: offsets must be contiguous so the
                    # client's baseOffset+index arithmetic holds under
                    # concurrent producers
                    base = plog.append_batch(
                        [
                            (
                                rec.timestamp_ms * 1_000_000
                                if rec.timestamp_ms
                                else time.time_ns(),
                                _pack_null(rec.key),
                                _pack_null(rec.value),
                            )
                            for rec in records
                        ]
                    )
                    parity = self._parity_for(topic, part)
                    if parity is not None:
                        dirty_parities.append(parity)
                parts.append((part, kp.NONE, base))
            if flex:
                r.tagged_fields()  # topic-struct tags
            results.append((topic, parts))
        if flex:
            r.tagged_fields()  # request tags
        if acks == 0:
            return None
        if dirty_parities:
            # durable-parity topics: the ack certifies replayability.
            # One group-commit window covers this produce's cohort; a
            # failed window fails every producer in it (none of the
            # cohort's records are certified durable).
            committer = self.broker.group_committer()
            if committer is not None:
                for parity in dirty_parities:
                    committer.mark_dirty(parity)
                try:
                    committer.wait_durable()
                except OSError:
                    results = [
                        (
                            topic,
                            [
                                (
                                    part,
                                    kp.KAFKA_STORAGE_ERROR
                                    if err == kp.NONE and base >= 0
                                    else err,
                                    -1 if err == kp.NONE and base >= 0 else base,
                                )
                                for part, err, base in parts
                            ],
                        )
                        for topic, parts in results
                    ]
        w = Writer()

        def topic_entry(ww: Writer, tp):
            name, parts = tp
            if flex:
                ww.compact_string(name)
            else:
                ww.string(name)

            def part_entry(w3: Writer, pr):
                part, err, base = pr
                w3.i32(part).i16(err).i64(base)
                if v >= 2:
                    w3.i64(-1)  # log_append_time
                if v >= 5:
                    w3.i64(0)  # log_start_offset
                if v >= 8:
                    # record_errors + error_message
                    if flex:
                        w3.compact_array([], lambda *_: None)
                        w3.compact_nullable_string(None)
                        w3.tags()
                    else:
                        w3.array([], lambda *_: None)
                        w3.nullable_string(None)

            if flex:
                ww.compact_array(parts, part_entry).tags()
            else:
                ww.array(parts, part_entry)

        if flex:
            w.compact_array(results, topic_entry)
            w.i32(self._throttle_ms())  # throttle
            w.tags()
        else:
            w.array(results, topic_entry)
            w.i32(self._throttle_ms())  # throttle (v1+)
        return w.done()

    def _h_fetch(self, r: Reader, v: int) -> bytes:
        r.i32()  # replica_id
        max_wait_ms = r.i32()
        r.i32()  # min_bytes
        r.i32()  # max_bytes (v3+)
        r.i8()  # isolation_level (v4+)
        if v >= 7:
            # incremental fetch sessions (KIP-227): not maintained —
            # responding session_id=0 tells the client "no session",
            # so it keeps sending full fetches (legal, just uncached)
            r.i32()  # session_id
            r.i32()  # session_epoch
        requests: list[tuple[str, list[tuple[int, int, int]]]] = []
        for _ in range(r.i32()):
            topic = r.string()
            parts = []
            for _p in range(r.i32()):
                part = r.i32()
                if v >= 9:
                    r.i32()  # current_leader_epoch
                fetch_offset = r.i64()
                if v >= 5:
                    r.i64()  # log_start_offset
                pmax = r.i32()
                parts.append((part, fetch_offset, pmax))
            requests.append((topic, parts))
        if v >= 7:
            for _ in range(max(r.i32(), 0)):  # forgotten_topics_data
                r.string()
                r.array(r.i32)
        if v >= 11:
            r.nullable_string()  # rack_id
        rejecting = self._rejecting()
        # long-poll: when every requested partition is empty, block on
        # the log's condition (single-partition fetch, the common
        # consumer shape) or poll coarsely. Partitions are re-resolved
        # each round: a fetch may race the topic's auto-creation, and
        # returning early would make the client spin. Under pool
        # pressure (frames queueing behind busy workers) the wait is
        # skipped entirely: parking a worker on an empty partition is
        # exactly the wrong move when workers are the scarce resource —
        # the empty response carries the throttle hint instead.
        wait_s = max(max_wait_ms, 0) / 1000.0
        if rejecting or self._throttle_ms() > 0:
            wait_s = 0.0
        deadline = time.monotonic() + wait_s
        wanted = [
            (topic, part, off)
            for topic, parts in requests
            for part, off, _m in parts
        ]
        while not rejecting:
            live = [
                (plog, off)
                for topic, part, off in wanted
                if (plog := self._log_for(topic, part)) is not None
            ]
            if any(plog.next_offset > off for plog, off in live):
                break
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            if len(live) == len(wanted) == 1:
                live[0][0].wait_for(live[0][1], timeout=remaining)
            else:
                time.sleep(min(0.05, remaining))
        # Response assembly: manual field walk (no Writer.array
        # callbacks) so a sealed-segment spool hit can CUT the byte
        # stream and splice in a FileExtent — the zero-copy span the
        # frame server ships via sn_send_file, bit-identically on the
        # Python fallback.
        resp = Parts(api="fetch")
        w = Writer()

        def cut(extent) -> None:
            nonlocal w
            resp.append(w.done())
            resp.append(extent)
            w = Writer()

        w.i32(self._throttle_ms())  # throttle
        if v >= 7:
            w.i16(kp.NONE)  # top-level error
            w.i32(0)  # session_id (0 = no fetch session)
        w.i32(len(requests))
        for name, parts in requests:
            w.string(name)
            w.i32(len(parts))
            for part, off, pmax in parts:
                if rejecting:
                    w.i32(part).i16(kp.REQUEST_TIMED_OUT)
                    w.i64(-1).i64(-1)
                    if v >= 5:
                        w.i64(-1)
                    w.i32(0)  # aborted_transactions (empty)
                    if v >= 11:
                        w.i32(-1)  # preferred_read_replica
                    w.i32(-1)  # null records
                    continue
                plog = self._log_for(name, part)
                if plog is None:
                    w.i32(part).i16(kp.UNKNOWN_TOPIC_OR_PARTITION)
                    w.i64(-1).i64(-1)
                    if v >= 5:
                        w.i64(-1)
                    w.i32(0)
                    if v >= 11:
                        w.i32(-1)
                    w.i32(-1)
                    continue
                hw = plog.next_offset
                if off > hw or (off < plog.earliest_offset):
                    w.i32(part).i16(kp.OFFSET_OUT_OF_RANGE)
                    w.i64(hw).i64(hw)
                    if v >= 5:
                        w.i64(plog.earliest_offset)
                    w.i32(0)
                    if v >= 11:
                        w.i32(-1)
                    w.i32(-1)
                    continue
                spooled = self.spool.extent_for(name, part, plog, off)
                if spooled is not None:
                    # whole sealed segment as ONE on-disk batch; it may
                    # start before `off` (protocol-legal — the client
                    # skips below its requested offset) and it ships
                    # regardless of pmax (the oversized-first-batch
                    # rule: it is the first batch)
                    extent, _base, _next_off = spooled
                    w.i32(part).i16(kp.NONE)
                    w.i64(hw).i64(hw)  # high_watermark, last_stable
                    if v >= 5:
                        w.i64(plog.earliest_offset)
                    w.i32(0)  # aborted_transactions
                    if v >= 11:
                        w.i32(-1)  # preferred_read_replica
                    w.i32(extent.length)  # records blob length
                    cut(extent)
                    continue
                recs = plog.read_from(off, max_records=1024)
                batch = b""
                if recs:
                    if pmax > 0:
                        # honor partition max_bytes, but always ship at
                        # least one record so the consumer makes
                        # progress (Kafka's oversized-first-batch rule)
                        kept, size = [], 64  # batch header overhead
                        for rec in recs:
                            size += 16 + len(rec[2]) + len(rec[3])
                            if kept and size > pmax:
                                break
                            kept.append(rec)
                        recs = kept
                    batch = encode_batch(
                        [
                            Record(
                                key=_unpack_null(k),
                                value=_unpack_null(val),
                                timestamp_ms=ts // 1_000_000,
                                offset=o,
                            )
                            for o, ts, k, val in recs
                        ],
                        base_offset=recs[0][0],
                    )
                w.i32(part).i16(kp.NONE)
                w.i64(hw).i64(hw)  # high_watermark, last_stable
                if v >= 5:
                    w.i64(plog.earliest_offset)
                w.i32(0)  # aborted_transactions
                if v >= 11:
                    w.i32(-1)  # preferred_read_replica
                if batch:
                    w.i32(len(batch)).raw(batch)
                else:
                    w.i32(-1)  # null records
        resp.append(w.done())
        faults.fire("mq.fetch.before_send", bytes=resp.total())
        return resp

    def _h_list_offsets(self, r: Reader, v: int) -> bytes:
        r.i32()  # replica_id
        if v >= 2:
            r.i8()  # isolation
        req = []
        for _ in range(r.i32()):
            topic = r.string()
            parts = []
            for _p in range(r.i32()):
                part = r.i32()
                if v >= 4:
                    r.i32()  # current_leader_epoch
                ts = r.i64()
                if v == 0:
                    r.i32()  # max_num_offsets
                parts.append((part, ts))
            req.append((topic, parts))
        w = Writer()
        if v >= 2:
            w.i32(self._throttle_ms())  # throttle

        def topic_entry(ww: Writer, tp):
            name, parts = tp
            ww.string(name)

            def part_entry(w3: Writer, pt):
                part, ts = pt
                plog = self._log_for(name, part)
                found_ts = -1  # special queries report -1 (spec: only
                #                timestamp lookups name a record's ts)
                if plog is None:
                    err, off = kp.UNKNOWN_TOPIC_OR_PARTITION, -1
                elif ts == -1:  # latest
                    err, off = kp.NONE, plog.next_offset
                elif ts == -2:  # earliest
                    err, off = kp.NONE, plog.earliest_offset
                else:
                    err = kp.NONE
                    off, found_ts = _offset_for_time(plog, ts)
                w3.i32(part).i16(err)
                if v == 0:
                    w3.array(
                        [off] if off >= 0 else [],
                        lambda w4, o: w4.i64(o),
                    )
                else:
                    w3.i64(found_ts).i64(off)
                    if v >= 4:
                        w3.i32(-1)  # leader_epoch

            ww.array(parts, part_entry)

        w.array(req, topic_entry)
        return w.done()

    def _h_create_topics(self, r: Reader, v: int) -> bytes:
        topics = []
        for _ in range(r.i32()):
            name = r.string()
            num_partitions = r.i32()
            r.i16()  # replication_factor
            for _a in range(max(r.i32(), 0)):  # manual assignments
                r.i32()
                r.array(r.i32)
            for _c in range(max(r.i32(), 0)):  # configs
                r.string()
                r.nullable_string()
            topics.append((name, num_partitions))
        r.i32()  # timeout
        validate_only = v >= 1 and r.i8() != 0
        existing = {
            name
            for ns, name, _c in self.broker.list_topics()
            if ns == NAMESPACE
        }
        w = Writer()
        if v >= 2:
            w.i32(self._throttle_ms())  # throttle

        def entry(ww: Writer, tp):
            name, count = tp
            if not _valid_topic(name):
                err = kp.INVALID_TOPIC_EXCEPTION
            elif name in existing:
                err = kp.TOPIC_ALREADY_EXISTS
            else:
                err = kp.NONE
                if not validate_only:
                    self.broker.configure_topic(
                        NAMESPACE, name, max(count, 1)
                    )
            ww.string(name).i16(err)
            if v >= 1:
                ww.nullable_string(None)  # error_message

        w.array(topics, entry)
        return w.done()

    def _h_delete_topics(self, r: Reader, v: int) -> bytes:
        names = r.array(r.string)
        r.i32()  # timeout
        existing = {
            name
            for ns, name, _c in self.broker.list_topics()
            if ns == NAMESPACE
        }
        w = Writer()
        if v >= 1:
            w.i32(self._throttle_ms())

        def entry(ww: Writer, name: str):
            if name in existing:
                self.broker.delete_topic(NAMESPACE, name)
                ww.string(name).i16(kp.NONE)
            else:
                ww.string(name).i16(kp.UNKNOWN_TOPIC_OR_PARTITION)

        w.array(names, entry)
        return w.done()

    def _h_find_coordinator(self, r: Reader, v: int) -> bytes:
        r.string()  # key (group id)
        if v >= 1:
            r.i8()  # key_type
        w = Writer()
        if v >= 1:
            w.i32(self._throttle_ms())  # throttle
        w.i16(kp.NONE)
        if v >= 1:
            w.nullable_string(None)  # error_message
        w.i32(NODE_ID).string(self.advertised_host).i32(self.port)
        return w.done()

    # ------------------------------------------------- group offset apis

    def _h_offset_commit(self, r: Reader, v: int) -> bytes:
        group = r.string()
        if v >= 1:
            r.i32()  # generation
            r.string()  # member
        if 2 <= v <= 4:
            r.i64()  # retention_time (removed in v5)
        if v >= 7:
            r.nullable_string()  # group_instance_id
        results = []
        for _ in range(r.i32()):
            topic = r.string()
            parts = []
            for _p in range(r.i32()):
                part = r.i32()
                offset = r.i64()
                if v >= 6:
                    r.i32()  # committed_leader_epoch
                if v == 1:
                    r.i64()  # commit timestamp
                metadata = r.nullable_string() or ""
                known = 0 <= part < max(self._partitions(topic), 0)
                if known:
                    self.broker.commit_offset(
                        NAMESPACE, topic, part, group, offset,
                        metadata=metadata,
                    )
                    parts.append((part, kp.NONE))
                else:
                    parts.append((part, kp.UNKNOWN_TOPIC_OR_PARTITION))
            results.append((topic, parts))
        w = Writer()
        if v >= 3:
            w.i32(self._throttle_ms())
        w.array(
            results,
            lambda ww, tp: ww.string(tp[0]).array(
                tp[1], lambda w3, pe: w3.i32(pe[0]).i16(pe[1])
            ),
        )
        return w.done()

    def _h_offset_fetch(self, r: Reader, v: int) -> bytes:
        group = r.string()
        req = []
        n = r.i32()
        if n >= 0:
            for _ in range(n):
                topic = r.string()
                parts = r.array(r.i32)
                req.append((topic, parts))
        else:  # null = all topics with commits; serve configured topics
            for ns, name, count in self.broker.list_topics():
                if ns == NAMESPACE:
                    req.append((name, list(range(count))))
        w = Writer()
        if v >= 3:
            w.i32(self._throttle_ms())

        def topic_entry(ww: Writer, tp):
            name, parts = tp
            ww.string(name)

            def part_entry(w3: Writer, part: int):
                off, meta = self.broker.fetch_offset_meta(
                    NAMESPACE, name, part, group
                )
                w3.i32(part).i64(off)
                if v >= 5:
                    w3.i32(-1)  # committed_leader_epoch
                # committed metadata round-trips (null when none)
                w3.nullable_string(meta or None).i16(kp.NONE)

            ww.array(parts, part_entry)

        w.array(req, topic_entry)
        if v >= 2:
            w.i16(kp.NONE)  # top-level error
        return w.done()

    # --------------------------------------------------- group membership

    def _h_join_group(self, r: Reader, v: int) -> bytes:
        group_id = r.string()
        session_timeout = r.i32() / 1000.0
        rebalance_timeout = session_timeout
        if v >= 1:
            rebalance_timeout = r.i32() / 1000.0
        member_id = r.string()
        if v >= 5:
            r.nullable_string()  # group_instance_id
        protocol_type = r.string()
        protocols = [
            (p_name, p_meta)
            for p_name, p_meta in (
                (r.string(), r.bytes_()) for _ in range(r.i32())
            )
        ]
        g = self.coordinator.group(group_id)
        resp = g.join(
            member_id,
            client_id=getattr(self._tl, "client_id", ""),
            protocol_type=protocol_type,
            protocols=protocols,
            session_timeout=max(session_timeout, 1.0),
            rebalance_timeout=max(rebalance_timeout, 1.0),
        )
        w = Writer()
        if v >= 2:
            w.i32(self._throttle_ms())  # throttle
        if resp["error"] != kp.NONE:
            w.i16(resp["error"]).i32(-1).string("").string("").string("")
            w.array([], lambda *_: None)
            return w.done()
        w.i16(kp.NONE).i32(resp["generation"]).string(resp["protocol"])
        w.string(resp["leader"]).string(resp["member_id"])

        def member_entry(ww: Writer, m):
            ww.string(m[0])
            if v >= 5:
                ww.nullable_string(None)  # group_instance_id
            ww.bytes_(m[1])

        w.array(resp["members"], member_entry)
        return w.done()

    def _h_sync_group(self, r: Reader, v: int) -> bytes:
        group_id = r.string()
        generation = r.i32()
        member_id = r.string()
        if v >= 3:
            r.nullable_string()  # group_instance_id
        assignments = [
            (mid, blob)
            for mid, blob in (
                (r.string(), r.bytes_()) for _ in range(r.i32())
            )
        ]
        g = self.coordinator.lookup(group_id)
        if g is None:
            err, blob = kp.UNKNOWN_MEMBER_ID, b""
        else:
            err, blob = g.sync(member_id, generation, assignments)
        w = Writer()
        if v >= 1:
            w.i32(self._throttle_ms())
        w.i16(err).bytes_(blob)
        return w.done()

    def _h_heartbeat(self, r: Reader, v: int) -> bytes:
        group_id = r.string()
        generation = r.i32()
        member_id = r.string()
        if v >= 3:
            r.nullable_string()  # group_instance_id
        g = self.coordinator.lookup(group_id)
        err = (
            kp.UNKNOWN_MEMBER_ID
            if g is None
            else g.heartbeat(member_id, generation)
        )
        w = Writer()
        if v >= 1:
            w.i32(self._throttle_ms())
        w.i16(err)
        return w.done()

    def _h_leave_group(self, r: Reader, v: int) -> bytes:
        group_id = r.string()
        if v >= 3:
            # batch leave (KIP-345): members array replaces member_id
            members = [
                (r.string(), r.nullable_string()) for _ in range(r.i32())
            ]
        else:
            members = [(r.string(), None)]
        g = self.coordinator.lookup(group_id)
        results = [
            (
                mid,
                gid,
                kp.UNKNOWN_MEMBER_ID if g is None else g.leave(mid),
            )
            for mid, gid in members
        ]
        top_err = next(
            (err for _, _, err in results if err != kp.NONE), kp.NONE
        )
        w = Writer()
        if v >= 1:
            w.i32(self._throttle_ms())
        w.i16(top_err if v < 3 else kp.NONE)
        if v >= 3:
            w.array(
                results,
                lambda ww, m: ww.string(m[0])
                .nullable_string(m[1])
                .i16(m[2]),
            )
        return w.done()

    def _h_list_groups(self, r: Reader, v: int) -> bytes:
        w = Writer()
        if v >= 1:
            w.i32(self._throttle_ms())
        w.i16(kp.NONE)
        w.array(
            self.coordinator.list_groups(),
            lambda ww, g: ww.string(g[0]).string(g[1]),
        )
        return w.done()

    def _h_describe_groups(self, r: Reader, v: int) -> bytes:
        names = r.array(r.string)
        if v >= 3:
            r.i8()  # include_authorized_operations
        w = Writer()
        if v >= 1:
            w.i32(self._throttle_ms())

        def entry(ww: Writer, name: str):
            g = self.coordinator.lookup(name)
            if g is None:
                ww.i16(kp.NONE).string(name).string("Dead")
                ww.string("").string("")
                ww.array([], lambda *_: None)
                if v >= 3:
                    ww.i32(-2147483648)  # authorized_operations (unset)
                return
            with g.lock:
                ww.i16(kp.NONE).string(name).string(g.state)
                ww.string(g.protocol_type).string(g.protocol_name)

                def member_entry(w3: Writer, m):
                    w3.string(m.member_id)
                    if v >= 4:
                        w3.nullable_string(None)  # group_instance_id
                    w3.string(m.client_id)
                    w3.string("/127.0.0.1")
                    w3.bytes_(g._metadata_for(m)).bytes_(m.assignment)

                ww.array(list(g.members.values()), member_entry)
            if v >= 3:
                ww.i32(-2147483648)

        w.array(names, entry)
        return w.done()


def _pack_null(b: bytes | None) -> bytes:
    """Kafka keys/values are nullable (a null value IS a compaction
    tombstone) but the partition log stores plain bytes — a one-byte
    flag preserves null vs empty. Only topics in the kafka namespace
    use this framing."""
    return b"\x00" if b is None else b"\x01" + b


def _unpack_null(b: bytes) -> bytes | None:
    if not b or b[0] == 0:
        return None
    return b[1:]


def _valid_topic(name: str) -> bool:
    return (
        0 < len(name) <= 249
        and name not in (".", "..")
        and all(c.isalnum() or c in "._-" for c in name)
    )


def _offset_for_time(plog, ts_ms: int, scan_limit: int = 10_000) -> tuple[int, int]:
    """(first offset whose timestamp >= ts_ms, that record's
    timestamp ms) via bounded scan; (-1, -1) when nothing qualifies —
    the pair the ListOffsets v1+ response reports."""
    ts_ns = ts_ms * 1_000_000
    off = plog.earliest_offset
    scanned = 0
    while scanned < scan_limit:
        recs = plog.read_from(off, max_records=1024)
        if not recs:
            return -1, -1
        for o, rts, _k, _v in recs:
            if rts >= ts_ns:
                return o, rts // 1_000_000
        scanned += len(recs)
        off = recs[-1][0] + 1
    return -1, -1
