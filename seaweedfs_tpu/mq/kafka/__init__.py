"""Kafka wire-protocol gateway over the MQ broker.

Reference: weed/mq/kafka (39k LoC) — protocol codec under protocol/,
gateway server under gateway/, group coordination under consumer/.
This package implements the wire subset real clients need: ApiVersions,
Metadata, Produce/Fetch (record batches v2), ListOffsets, CreateTopics/
DeleteTopics, FindCoordinator and the classic consumer-group protocol
(JoinGroup/SyncGroup/Heartbeat/LeaveGroup/OffsetCommit/OffsetFetch),
mapped onto the MqBroker partition logs.
"""

from .gateway import KafkaGateway  # noqa: F401
