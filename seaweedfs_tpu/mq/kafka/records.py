"""Kafka record batch v2 (magic 2) codec.

Reference: weed/mq/kafka/protocol (record batch handling per the Kafka
protocol spec). Batch layout (big-endian):

  baseOffset           i64
  batchLength          i32   (bytes after this field)
  partitionLeaderEpoch i32
  magic                i8    (= 2)
  crc                  u32   (CRC32C of everything after this field)
  attributes           i16   (bits 0-2 compression: 0 none, 1 gzip)
  lastOffsetDelta      i32
  baseTimestamp        i64
  maxTimestamp         i64
  producerId           i64
  producerEpoch        i16
  baseSequence         i32
  recordCount          i32
  records…                   (possibly compressed as a unit)

Each record: length(varint) attributes(i8) timestampDelta(varlong)
offsetDelta(varint) keyLen(varint) key valueLen(varint) value
headerCount(varint) [headerKeyLen(varint) key valLen(varint) val]…
All varints are zigzag.
"""

from __future__ import annotations

import gzip
import struct
import time
from dataclasses import dataclass, field

from ...utils.crc import crc32c
from .protocol import Reader, write_varint

MAGIC_V2 = 2
_HEADER = struct.Struct(">qiib")  # baseOffset, batchLength, leaderEpoch, magic
_POST_CRC = struct.Struct(">hiqqqhii")

COMPRESSION_NONE = 0
COMPRESSION_GZIP = 1
COMPRESSION_SNAPPY = 2
COMPRESSION_LZ4 = 3
COMPRESSION_ZSTD = 4


@dataclass
class Record:
    key: bytes | None
    value: bytes | None
    timestamp_ms: int = 0
    offset: int = 0  # absolute, filled on decode / assigned on append
    headers: list[tuple[str, bytes | None]] = field(default_factory=list)


class UnsupportedCompression(ValueError):
    pass


def _encode_record(
    r: Record, offset_delta: int, ts_delta: int
) -> bytes:
    body = bytearray()
    body += b"\x00"  # attributes (unused)
    body += write_varint(ts_delta)
    body += write_varint(offset_delta)
    if r.key is None:
        body += write_varint(-1)
    else:
        body += write_varint(len(r.key)) + r.key
    if r.value is None:
        body += write_varint(-1)
    else:
        body += write_varint(len(r.value)) + r.value
    body += write_varint(len(r.headers))
    for hk, hv in r.headers:
        kb = hk.encode()
        body += write_varint(len(kb)) + kb
        if hv is None:
            body += write_varint(-1)
        else:
            body += write_varint(len(hv)) + hv
    return write_varint(len(body)) + bytes(body)


def encode_batch(
    records: list[Record], base_offset: int = 0, compression: int = 0
) -> bytes:
    """Record batch v2; `compression` is the attributes codec id
    (0 none, 1 gzip, 2 snappy, 3 lz4, 4 zstd)."""
    if not records:
        return b""
    base_ts = records[0].timestamp_ms or int(time.time() * 1000)
    max_ts = max(r.timestamp_ms or base_ts for r in records)
    recs = b"".join(
        _encode_record(
            r,
            offset_delta=(r.offset - base_offset),
            ts_delta=(r.timestamp_ms or base_ts) - base_ts,
        )
        for r in records
    )
    if compression != COMPRESSION_NONE:
        from . import codecs as _codecs

        recs = {
            COMPRESSION_GZIP: gzip.compress,
            COMPRESSION_SNAPPY: _codecs.snappy_compress,
            COMPRESSION_LZ4: _codecs.lz4_compress,
            COMPRESSION_ZSTD: _codecs.zstd_compress,
        }[compression](recs)
    last_delta = records[-1].offset - base_offset
    post_crc = (
        _POST_CRC.pack(
            compression,  # attributes bits 0-2
            last_delta,
            base_ts,
            max_ts,
            -1,  # producerId
            -1,  # producerEpoch
            -1,  # baseSequence
            len(records),
        )
        + recs
    )
    crc = crc32c(post_crc)
    batch_len = 4 + 1 + 4 + len(post_crc)  # leaderEpoch+magic+crc+rest
    return (
        _HEADER.pack(base_offset, batch_len, -1, MAGIC_V2)
        + struct.pack(">I", crc)
        + post_crc
    )


def decode_batches(raw: bytes) -> list[Record]:
    """All records from a (possibly multi-batch) records blob; absolute
    offsets and timestamps reconstructed. Raises UnsupportedCompression
    for unknown codec ids (none/gzip/snappy/lz4/zstd supported),
    ValueError on CRC mismatch or corrupt compressed payloads."""
    out: list[Record] = []
    pos = 0
    while pos + _HEADER.size <= len(raw):
        base_offset, batch_len, _epoch, magic = _HEADER.unpack_from(raw, pos)
        end = pos + 12 + batch_len  # baseOffset+batchLength prefix = 12
        if end > len(raw):
            break  # partial trailing batch (Kafka permits truncation)
        if magic != MAGIC_V2:
            raise ValueError(f"unsupported magic {magic} (only v2)")
        crc_stored = struct.unpack_from(">I", raw, pos + _HEADER.size)[0]
        post = raw[pos + _HEADER.size + 4 : end]
        if crc32c(post) != crc_stored:
            raise ValueError("record batch CRC mismatch")
        (
            attributes,
            _last_delta,
            base_ts,
            _max_ts,
            _pid,
            _pepoch,
            _bseq,
            count,
        ) = _POST_CRC.unpack_from(post, 0)
        payload = post[_POST_CRC.size :]
        codec = attributes & 0x07
        if codec != COMPRESSION_NONE:
            from . import codecs as _codecs

            try:
                decompress = {
                    COMPRESSION_GZIP: gzip.decompress,
                    COMPRESSION_SNAPPY: _codecs.snappy_decompress,
                    COMPRESSION_LZ4: _codecs.lz4_decompress,
                    COMPRESSION_ZSTD: _codecs.zstd_decompress,
                }[codec]
            except KeyError:
                raise UnsupportedCompression(
                    f"compression codec {codec}"
                ) from None
            try:
                payload = decompress(payload)
            except Exception as e:  # noqa: BLE001 — normalize decoder
                # errors (IndexError/ZstdError/...) to the ValueError
                # contract so one corrupt batch fails one partition,
                # not the connection
                raise ValueError(
                    f"batch decompression failed (codec {codec}): {e!r}"
                ) from None
        r = Reader(payload)
        for _ in range(count):
            _len = r.varint()
            rec_end = r.pos + _len
            r.i8()  # attributes
            ts_delta = r.varlong()
            off_delta = r.varint()
            klen = r.varint()
            key = bytes(r._take(klen)) if klen >= 0 else None
            vlen = r.varint()
            value = bytes(r._take(vlen)) if vlen >= 0 else None
            headers: list[tuple[str, bytes | None]] = []
            for _h in range(r.varint()):
                hklen = r.varint()
                hk = r._take(hklen).decode()
                hvlen = r.varint()
                hv = bytes(r._take(hvlen)) if hvlen >= 0 else None
                headers.append((hk, hv))
            r.pos = rec_end  # tolerate unknown trailing record fields
            out.append(
                Record(
                    key=key,
                    value=value,
                    timestamp_ms=base_ts + ts_delta,
                    offset=base_offset + off_delta,
                    headers=headers,
                )
            )
        pos = end
    return out
