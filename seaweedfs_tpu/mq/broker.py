"""MQ broker: topics -> partitions -> append logs, pub/sub over gRPC.

Reference: weed/mq/broker (broker_grpc_pub.go/_sub.go) with filer-backed
segment storage (weed/mq/logstore) and consumer-group offsets
(weed/mq/offset). Partitioning: key-hash over a fixed partition count
(ring-slicing arrives with multi-broker balancing).
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from concurrent import futures

import grpc
import requests

from ..pb import mq_pb2 as mq
from ..pb import rpc
from ..utils.glog import logger
from ..utils.urls import service_url
from . import balancer as balancer_mod
from .log_buffer import PartitionLog, decode_records

mlog = logger("mq")

TOPICS_ROOT = "/topics"


class _TopicState:
    def __init__(self, partition_count: int, durable_parity: bool = False):
        self.partition_count = partition_count
        self.durable_parity = durable_parity
        self.logs: dict[int, PartitionLog] = {}
        # partition -> durable-parity stream (mq/stream_parity.py);
        # populated only when durable_parity is on and the broker has a
        # parity_dir
        self.parity: dict[int, "object"] = {}


class MqBroker:
    """Single-broker core; the service facade lives in MqService."""

    def __init__(
        self,
        filer: str = "",
        segment_records: int = 4096,
        parity_dir: str = "",
        durable_parity_default: bool | None = None,
    ):
        """filer: host:port of a filer for durable segments/offsets;
        empty = memory-only broker (bounded tails, no recovery — unless
        `parity_dir` gives it a durable-parity stream to replay from).

        parity_dir: local directory for streaming-EC log parity
        (ec/stream_encode.py). Topics configured with durable parity
        get per-partition EC streams whose parity trails the append
        head by a bounded lag; on restart the unsealed tail (records
        the filer segments never saw) is replayed from the stream.
        `durable_parity_default` is what `configure_topic` uses when
        the caller doesn't say (default: on iff parity_dir is set)."""
        self.filer = filer
        self.segment_records = segment_records
        self.parity_dir = parity_dir
        self.durable_parity_default = (
            bool(parity_dir)
            if durable_parity_default is None
            else durable_parity_default
        )
        self._parity_flusher = None
        self._mq_committer = None
        self._topics: dict[tuple[str, str], _TopicState] = {}
        self._offsets: dict[tuple, int] = {}  # (ns, topic, part, group)
        self._offset_meta: dict[tuple, str] = {}  # committed metadata
        self._schemas: dict[tuple[str, str], str] = {}  # (ns, topic)
        self._lock = threading.RLock()
        self._http = requests.Session()
        if filer:
            # startup-ordering tolerance: the filer may still be coming up
            last_err = None
            for attempt in range(10):
                try:
                    self._recover()
                    break
                except requests.RequestException as e:
                    last_err = e
                    time.sleep(min(0.5 * (attempt + 1), 3.0))
            else:
                raise RuntimeError(
                    f"mq broker: filer {filer} unreachable during recovery: {last_err}"
                )
        elif parity_dir:
            # memory-only broker with a parity dir: the EC streams are
            # the ONLY durability — topics and their unsealed tails are
            # recovered from parity_dir alone
            self._recover_parity_only()

    # ------------------------------------------------------------ filer io

    def _url(self, path: str) -> str:
        return service_url(self.filer, path)

    def _seg_path(self, ns: str, name: str, part: int, seg: int) -> str:
        return f"{TOPICS_ROOT}/{ns}/{name}/{part:04d}/seg-{seg:08d}.log"

    def topics_root(self) -> str:
        return TOPICS_ROOT

    def _delete_file(self, path: str) -> None:
        r = self._http.delete(self._url(path), timeout=60)
        if r.status_code not in (200, 204, 404):
            r.raise_for_status()

    def _put_file(self, path: str, data: bytes) -> None:
        r = self._http.post(
            self._url(path),
            data=data,
            headers={"Content-Type": "application/octet-stream"},
            timeout=60,
        )
        r.raise_for_status()

    def _get_file(self, path: str):
        """File bytes, or None ONLY for not-found; a transient filer
        error must raise — treating it as absence would recover a too-low
        next_offset and overwrite records."""
        r = self._http.get(self._url(path), timeout=60)
        if r.status_code == 404:
            return None
        r.raise_for_status()
        if r.headers.get("X-Filer-Listing") == "true":
            return None  # a directory, not a file
        return r.content

    def _list_dir(self, path: str) -> list[dict]:
        """Full listing, following pagination (the filer caps pages)."""
        from ..client.filer_client import list_dir

        return list(list_dir(self.filer, path, session=self._http))

    # ------------------------------------------------------------ recovery

    def _recover(self) -> None:
        for ns_e in self._list_dir(TOPICS_ROOT):
            if not ns_e["IsDirectory"]:
                continue
            ns = ns_e["FullPath"].rsplit("/", 1)[-1]
            if ns.startswith("."):
                continue
            for t_e in self._list_dir(f"{TOPICS_ROOT}/{ns}"):
                if not t_e["IsDirectory"]:
                    continue
                name = t_e["FullPath"].rsplit("/", 1)[-1]
                conf = self._get_file(f"{TOPICS_ROOT}/{ns}/{name}/topic.conf")
                if conf is None:
                    continue
                cfg = json.loads(conf)
                st = _TopicState(
                    int(cfg["partitionCount"]),
                    durable_parity=bool(cfg.get("durableParity"))
                    and bool(self.parity_dir),
                )
                self._topics[(ns, name)] = st
                for p in range(st.partition_count):
                    st.logs[p] = self._make_log(ns, name, p, recover=True)
                    if st.durable_parity:
                        self._attach_parity(ns, name, st, p, recover=True)
                off = self._get_file(f"{TOPICS_ROOT}/{ns}/{name}/offsets.json")
                if off:
                    for k, v in json.loads(off).items():
                        part_s, group = k.split("|", 1)
                        key = (ns, name, int(part_s), group)
                        if isinstance(v, list):  # [offset, metadata]
                            self._offsets[key] = v[0]
                            self._offset_meta[key] = v[1]
                        else:
                            self._offsets[key] = v

    def _make_log(self, ns: str, name: str, part: int, recover: bool = False) -> PartitionLog:
        spill = None
        load = None
        if self.filer:
            def spill(seg: int, raw: bytes, _ns=ns, _name=name, _p=part):
                path = self._seg_path(_ns, _name, _p, seg)
                self._put_file(path, raw)
                # a re-sealed partial segment supersedes any archived
                # stats sidecar: stale bounds would let pushdown prune
                # LIVE rows
                self._delete_file(path[: -len(".log")] + ".stats.json")

            def load(seg: int, _ns=ns, _name=name, _p=part):
                path = self._seg_path(_ns, _name, _p, seg)
                raw = self._get_file(path)
                if raw is not None:
                    return raw
                # sealed segment may have been ARCHIVED to parquet
                # (mq/logstore.py); re-materialize the record stream
                data = self._get_file(path[: -len(".log")] + ".parquet")
                if data is None:
                    return None
                from .logstore import parquet_to_segment

                return parquet_to_segment(data)

        next_offset = earliest = 0
        if recover and self.filer:
            # dedupe per segment NUMBER, preferring .log: a stale
            # .parquet coexisting with a fuller re-sealed .log must
            # never shadow it (lexicographic sort alone would pick
            # ".parquet" as last and recover a too-low next_offset)
            by_stem: dict[str, str] = {}
            for e in self._list_dir(f"{TOPICS_ROOT}/{ns}/{name}/{part:04d}"):
                p_full = e["FullPath"]
                for ext in (".log", ".parquet"):
                    if p_full.endswith(ext):
                        stem = p_full[: -len(ext)]
                        if ext == ".log" or stem not in by_stem:
                            by_stem[stem] = p_full
            segs = [by_stem[s] for s in sorted(by_stem)]

            def _read_seg(path: str) -> bytes | None:
                data = self._get_file(path)
                if data is None or not path.endswith(".parquet"):
                    return data
                from .logstore import parquet_to_segment

                return parquet_to_segment(data)

            if segs:
                first = _read_seg(segs[0])
                last = _read_seg(segs[-1])
                if first is not None:
                    for off, *_ in decode_records(first):
                        earliest = off
                        break
                if last is not None:
                    for off, *_ in decode_records(last):
                        next_offset = off + 1
        return PartitionLog(
            segment_records=self.segment_records,
            spill=spill,
            load=load,
            next_offset=next_offset,
            earliest_offset=earliest,
        )

    # ----------------------------------------------------- durable parity

    def _attach_parity(
        self, ns: str, name: str, st: _TopicState, p: int,
        recover: bool = False,
    ) -> None:
        """Give partition `p` its streaming-EC parity: recover+replay
        the unsealed tail first (records the durable segments never
        saw), then hook the log's append observer so every new record
        enters the live stream."""
        from .stream_parity import PartitionParity

        parity = PartitionParity(self.parity_dir, ns, name, p)
        plog = st.logs[p]
        if recover:
            replayed = 0
            for off, ts, key, value in parity.recover():
                if off < plog.next_offset:
                    continue  # already durable in a sealed segment
                if off > plog.next_offset:
                    # hole vs the durable cut. On a virgin log this is
                    # just the retention window starting past 0 (the
                    # bounded tail dropped earlier records by design):
                    # fast-forward and replay from there. Otherwise
                    # stop — dense numbering must never skip.
                    if not plog.fast_forward(off):
                        break
                plog.append_at(off, ts, key, value)
                replayed += 1
            if replayed:
                mlog.info(
                    "mq parity: replayed %d unsealed records for "
                    "%s/%s[%d]", replayed, ns, name, p,
                )
        st.parity[p] = parity
        plog.on_append = parity.append_record
        self._ensure_parity_flusher()

    def _parity_topic_conf(self, ns: str, name: str) -> str:
        return os.path.join(self.parity_dir, ns, name, "topic.json")

    def _recover_parity_only(self) -> None:
        """Memory-only broker + parity_dir: rebuild topics (and their
        recoverable tails) from the parity directory alone."""
        import glob as _glob

        for conf in sorted(
            _glob.glob(os.path.join(self.parity_dir, "*", "*", "topic.json"))
        ):
            name = os.path.basename(os.path.dirname(conf))
            ns = os.path.basename(os.path.dirname(os.path.dirname(conf)))
            try:
                with open(conf) as f:
                    cfg = json.load(f)
            except (OSError, ValueError) as e:
                # loud: an unreadable topic.json strands intact stream
                # generations — never skip one silently
                mlog.warning(
                    "mq parity: unreadable %s (%s); topic %s/%s NOT "
                    "recovered, stream generations left on disk",
                    conf, e, ns, name,
                )
                continue
            st = _TopicState(
                int(cfg.get("partitionCount", 1)), durable_parity=True
            )
            self._topics[(ns, name)] = st
            for p in range(st.partition_count):
                st.logs[p] = self._make_log(ns, name, p)
                self._attach_parity(ns, name, st, p, recover=True)

    def _ensure_parity_flusher(self) -> None:
        if self._parity_flusher is None:
            from .stream_parity import ParityFlusher

            self._parity_flusher = ParityFlusher(self)
            self._parity_flusher.start()

    def parity_sweep(self) -> None:
        """One flusher pass: bound every partition's parity lag, then
        prune stream generations below the durability floor (sealed
        into filer segments, or — memory-only — fallen out of the
        bounded tail)."""
        with self._lock:
            items = [
                (st, dict(st.parity)) for st in self._topics.values()
            ]
        for st, parts in items:
            for p, parity in parts.items():
                if parity.needs_flush():
                    parity.flush()
                plog = st.logs.get(p)
                if plog is None:
                    continue
                with plog._lock:
                    floor = (
                        plog._tail_base if self.filer
                        else plog.earliest_offset
                    )
                parity.prune(floor)

    def parity_status(self) -> dict:
        """Per-topic durable-parity roll-up (shell/status surfaces)."""
        out = {}
        with self._lock:
            items = list(self._topics.items())
        for (ns, name), st in items:
            if not st.parity:
                continue
            out[f"{ns}/{name}"] = {
                p: {
                    "pending_bytes": parity.pending_bytes(),
                    "parity_lag_ms": round(
                        parity.parity_lag_s() * 1000.0, 3
                    ),
                }
                for p, parity in sorted(st.parity.items())
            }
        return out

    def load_score(self) -> float:
        """Parity-backlog component of the gravity load signal: pending
        parity bytes across every partition, in units of the flush
        threshold (1.0 ≈ one full flush window behind)."""
        from .stream_parity import flush_bytes_default

        pending = 0
        with self._lock:
            items = [dict(st.parity) for st in self._topics.values()]
        for parts in items:
            for parity in parts.values():
                try:
                    pending += parity.pending_bytes()
                except Exception:  # noqa: BLE001 — telemetry only
                    pass
        return pending / float(max(1, flush_bytes_default()))

    def group_committer(self):
        """The broker group committer covering durable-parity produce
        acks, or None when SEAWEED_MQ_GROUP_COMMIT_MS is 0. The knob is
        read live per call and the committer swapped when it changes
        (mirrors Volume._group_committer)."""
        from .group_commit import MqGroupCommitter, group_commit_window_s

        w = group_commit_window_s()
        c = self._mq_committer
        if c is not None and c.window_s == w:
            return c
        with self._lock:
            c = self._mq_committer
            if w <= 0:
                if c is not None:
                    self._mq_committer = None
                    c.stop()
                return None
            if c is None or c.window_s != w:
                if c is not None:
                    c.stop()
                c = MqGroupCommitter(w)
                self._mq_committer = c
            return c

    def close(self) -> None:
        """Stop the parity flusher and close every stream (flushes
        first: a clean shutdown leaves nothing to replay)."""
        if self._mq_committer is not None:
            self._mq_committer.stop()
            self._mq_committer = None
        if self._parity_flusher is not None:
            self._parity_flusher.stop()
            self._parity_flusher = None
        self.flush()
        with self._lock:
            for st in self._topics.values():
                for parity in st.parity.values():
                    parity.close()

    # ------------------------------------------------------------- topics

    def configure_topic(
        self,
        ns: str,
        name: str,
        partitions: int,
        durable_parity: bool | None = None,
    ) -> None:
        """`durable_parity` (None = the broker default: on when it has
        a parity_dir) gives every partition a streaming-EC parity
        stream — parity trails the append head by a bounded lag instead
        of waiting for segment seal."""
        with self._lock:
            if (ns, name) in self._topics:
                return
            want_parity = bool(self.parity_dir) and (
                self.durable_parity_default
                if durable_parity is None
                else durable_parity
            )
            st = _TopicState(max(partitions, 1), durable_parity=want_parity)
            for p in range(st.partition_count):
                st.logs[p] = self._make_log(ns, name, p)
                if want_parity:
                    self._attach_parity(ns, name, st, p)
            self._topics[(ns, name)] = st
            if want_parity:
                # atomic + fsynced: on a memory-only broker this file
                # is the only way a restart learns the topic exists —
                # a torn write would orphan every intact stream gen
                from ..utils.fs import atomic_write

                conf = self._parity_topic_conf(ns, name)
                os.makedirs(os.path.dirname(conf), exist_ok=True)
                atomic_write(
                    conf,
                    json.dumps(
                        {"partitionCount": st.partition_count}
                    ).encode(),
                )
            if self.filer:
                self._put_file(
                    f"{TOPICS_ROOT}/{ns}/{name}/topic.conf",
                    json.dumps(
                        {
                            "partitionCount": st.partition_count,
                            "durableParity": want_parity,
                        }
                    ).encode(),
                )

    def delete_topic(self, ns: str, name: str) -> None:
        """Drop a topic: in-memory state AND its filer subtree
        (topic.conf, offsets.json, segments) — otherwise a restart
        resurrects the topic, and a re-created topic's offsets would
        collide with stale segments."""
        with self._lock:
            st = self._topics.pop((ns, name), None)
            if st is not None:
                for parity in st.parity.values():
                    parity.delete()
                if st.parity and self.parity_dir:
                    # the per-partition deletes leave the topic dir +
                    # topic.json; a restart must not resurrect the topic
                    import shutil as _shutil

                    _shutil.rmtree(
                        os.path.join(self.parity_dir, ns, name),
                        ignore_errors=True,
                    )
            self._offsets = {
                k: v
                for k, v in self._offsets.items()
                if (k[0], k[1]) != (ns, name)
            }
            self._offset_meta = {
                k: v
                for k, v in self._offset_meta.items()
                if (k[0], k[1]) != (ns, name)
            }
        if self.filer:
            r = self._http.delete(
                self._url(f"{TOPICS_ROOT}/{ns}/{name}?recursive=true"),
                timeout=60,
            )
            if r.status_code not in (204, 404):
                r.raise_for_status()

    def topic(self, ns: str, name: str) -> _TopicState:
        st = self._topics.get((ns, name))
        if st is None:
            raise KeyError(f"topic {ns}/{name} not configured")
        return st

    def scan_records(
        self,
        ns: str,
        name: str,
        part: int,
        off_lo: int = 0,
        ts_lo_ns: int | None = None,
        ts_hi_ns: int | None = None,
        counters: dict | None = None,
    ):
        """Yield (offset, ts_ns, key, value) for one partition with
        PREDICATE PUSHDOWN over archived segments: a `.stats.json`
        sidecar (written at parquet-archive time) whose offset/ts
        ranges exclude the query's bounds skips the segment WITHOUT
        fetching its bytes. `counters` (if given) tallies
        segments_scanned / segments_skipped / rows_scanned — the
        auditable proof pruning happened."""
        st = self.topic(ns, name)
        plog = st.logs.get(part)
        if plog is None:
            return
        if counters is None:
            counters = {}
        counters.setdefault("segments_scanned", 0)
        counters.setdefault("segments_skipped", 0)
        counters.setdefault("rows_scanned", 0)
        off = max(plog.earliest_offset, off_lo)
        with plog._lock:
            tail_base = plog._tail_base
        sr = self.segment_records
        if self.filer:
            seg = off // sr
            # segments wholly below the offset bound are pruned without
            # even a stats fetch; count them so the audit adds up
            counters["segments_skipped"] += max(
                seg - plog.earliest_offset // sr, 0
            )
            while seg * sr < tail_base:
                lo_in_seg = max(off, seg * sr)
                # stats can only prune when a ts bound is set or the
                # scan starts mid-segment; an unbounded full scan must
                # not pay a sidecar round-trip per segment
                can_prune = (
                    ts_lo_ns is not None
                    or ts_hi_ns is not None
                    or lo_in_seg > seg * sr
                )
                stats = (
                    self._seg_stats(ns, name, part, seg) if can_prune else None
                )
                if stats is not None and (
                    (
                        ts_lo_ns is not None
                        and stats.get("ts_ns_max") is not None
                        and stats["ts_ns_max"] < ts_lo_ns
                    )
                    or (
                        ts_hi_ns is not None
                        and stats.get("ts_ns_min") is not None
                        and stats["ts_ns_min"] > ts_hi_ns
                    )
                    or (
                        stats.get("offset_max") is not None
                        and stats["offset_max"] < lo_in_seg
                    )
                ):
                    counters["segments_skipped"] += 1
                    seg += 1
                    continue
                raw = None
                path = self._seg_path(ns, name, part, seg)
                raw = self._get_file(path)
                if raw is None:
                    data = self._get_file(path[: -len(".log")] + ".parquet")
                    if data is not None:
                        from .logstore import parquet_to_segment

                        raw = parquet_to_segment(data)
                if raw is not None:
                    counters["segments_scanned"] += 1
                    for rec in decode_records(raw):
                        # upper bound at the tail_base snapshot: a seal
                        # racing this scan can merge tail records into
                        # the segment, and the tail read below would
                        # yield them AGAIN
                        if lo_in_seg <= rec[0] < tail_base:
                            counters["rows_scanned"] += 1
                            yield rec
                seg += 1
            off = max(off, tail_base)
        while True:
            recs = plog.read_from(off, max_records=2048)
            if not recs:
                return
            for rec in recs:
                counters["rows_scanned"] += 1
                yield rec
            off = recs[-1][0] + 1

    def _seg_stats(self, ns: str, name: str, part: int, seg: int) -> dict | None:
        path = self._seg_path(ns, name, part, seg)[: -len(".log")] + ".stats.json"
        try:
            raw = self._get_file(path)
        except requests.RequestException:
            return None
        if raw is None:
            return None
        try:
            return json.loads(raw)
        except ValueError:
            return None

    def compact_topic(self, ns: str, name: str) -> int:
        """Archive this topic's sealed raw segments to parquet NOW
        (mq.topic.compact; the periodic archiver does the same on a
        timer). Returns segments archived."""
        from .logstore import SegmentArchiver

        st = self.topic(ns, name)  # KeyError surfaces to the caller
        if not self.filer:
            return 0
        for log_ in st.logs.values():
            log_.flush()  # seal the tails so they are archivable
        # min_age_segments=0: an OPERATOR-initiated compact must cover
        # every sealed segment (the background archiver's 1-segment
        # grace exists only to keep tail reads on the raw format)
        arch = SegmentArchiver(self, min_age_segments=0)
        return sum(
            arch._archive_partition(ns, name, p)
            for p in range(st.partition_count)
        )

    def truncate_topic(
        self, ns: str, name: str, partition: int = -1, before_offset: int = -1
    ) -> int:
        """Drop records below before_offset (-1 = all current records)
        for one or every partition (mq.topic.truncate). In-memory
        truncation is record-granular; durable segment files are
        deleted only when ENTIRELY below the boundary, so a restart may
        re-expose the partial segment's older records (documented
        segment-granular durability)."""
        st = self.topic(ns, name)
        parts = (
            range(st.partition_count) if partition < 0 else [partition]
        )
        done = 0
        for p in parts:
            log_ = st.logs.get(p)
            if log_ is None:
                continue
            boundary = log_.truncate_before(before_offset)
            if self.filer:
                full_below = boundary // self.segment_records
                for seg in range(full_below):
                    self._delete_file(self._seg_path(ns, name, p, seg))
                    pq = self._seg_path(ns, name, p, seg)[: -len(".log")]
                    self._delete_file(pq + ".parquet")
                    self._delete_file(pq + ".stats.json")
            done += 1
        return done

    def pick_partition(self, st: _TopicState, key: bytes, requested: int) -> int:
        if requested >= 0:
            return requested % st.partition_count
        if not key:
            return int(time.time_ns()) % st.partition_count
        return int.from_bytes(
            hashlib.md5(key).digest()[:4], "big"
        ) % st.partition_count

    # ------------------------------------------------------------- offsets

    def list_topics(self) -> list[tuple[str, str, int]]:
        with self._lock:
            return sorted(
                (ns, name, st.partition_count)
                for (ns, name), st in self._topics.items()
            )

    # ------------------------------------------------------------ schemas

    def set_schema(self, ns: str, name: str, schema_json: str) -> None:
        """Register (or with "" delete) a topic's schema: a JSON doc
        {"fields": [{"name": ..., "type": int|float|string|bool}, ...],
        "enforce": bool} (reference weed/mq/schema, simplified from
        protobuf descriptors to a JSON field list)."""
        self.topic(ns, name)  # must exist
        if schema_json:
            doc = json.loads(schema_json)
            if not isinstance(doc.get("fields"), list):
                raise ValueError("schema needs a 'fields' list")
            for f in doc["fields"]:
                if "name" not in f:
                    raise ValueError(f"schema field without name: {f}")
        with self._lock:
            if schema_json:
                self._schemas[(ns, name)] = schema_json
            else:
                self._schemas.pop((ns, name), None)
        if self.filer:
            path = f"{TOPICS_ROOT}/{ns}/{name}/schema.json"
            if schema_json:
                self._put_file(path, schema_json.encode())
            else:
                self._delete_file(path)

    def get_schema(self, ns: str, name: str) -> str:
        """'' = no schema. Negative lookups are CACHED — Publish calls
        this on the hot path, and a schema-less topic must not pay a
        filer round-trip (or fail on a filer hiccup) per message."""
        with self._lock:
            s = self._schemas.get((ns, name))
        if s is not None:
            return s
        s = ""
        if self.filer:
            try:
                raw = self._get_file(f"{TOPICS_ROOT}/{ns}/{name}/schema.json")
            except requests.RequestException:
                return ""  # transient filer error: fail open, don't cache
            if raw:
                s = raw.decode()
        with self._lock:
            self._schemas[(ns, name)] = s
        return s

    def validate_against_schema(self, ns: str, name: str, value: bytes) -> str:
        """'' when acceptable; an error string when the topic enforces
        a schema and the payload violates it."""
        s = self.get_schema(ns, name)
        if not s:
            return ""
        try:
            doc = json.loads(s)
        except json.JSONDecodeError:
            return ""
        if not doc.get("enforce"):
            return ""
        try:
            payload = json.loads(value)
        except (ValueError, UnicodeDecodeError):
            return "payload is not JSON but the topic enforces a schema"
        if not isinstance(payload, dict):
            return "payload must be a JSON object"
        types = {
            "int": int,
            "float": (int, float),
            "string": str,
            "bool": bool,
            "bytes": str,
        }
        for f in doc.get("fields", []):
            fname = f.get("name")
            if fname not in payload:
                if f.get("required"):
                    return f"missing required field {fname!r}"
                continue
            ftype = f.get("type", "string")
            want = types.get(ftype)
            have = payload[fname]
            # bool is a subclass of int in Python: a JSON true must not
            # satisfy an int/float field
            if ftype in ("int", "float") and isinstance(have, bool):
                return f"field {fname!r} is not a {ftype}"
            if want and not isinstance(have, want):
                return f"field {fname!r} is not a {ftype}"
        return ""

    def commit_offset(self, ns, name, part, group, offset, metadata: str = "") -> None:
        # snapshot under the lock, persist outside it: one slow filer
        # write must not stall every other MQ RPC
        with self._lock:
            self._offsets[(ns, name, part, group)] = offset
            if metadata:
                self._offset_meta[(ns, name, part, group)] = metadata
            else:
                self._offset_meta.pop((ns, name, part, group), None)
            grouped = {
                f"{p}|{g}": (
                    [o, m]
                    if (m := self._offset_meta.get((n2, t2, p, g), ""))
                    else o
                )
                for (n2, t2, p, g), o in self._offsets.items()
                if (n2, t2) == (ns, name)
            }
        if self.filer:
            self._put_file(
                f"{TOPICS_ROOT}/{ns}/{name}/offsets.json",
                json.dumps(grouped).encode(),
            )

    def fetch_offset(self, ns, name, part, group) -> int:
        with self._lock:
            return self._offsets.get((ns, name, part, group), -1)

    def fetch_offset_meta(self, ns, name, part, group) -> tuple[int, str]:
        """(offset, committed metadata) — Kafka's OffsetFetch returns
        the metadata string the committer attached."""
        with self._lock:
            return (
                self._offsets.get((ns, name, part, group), -1),
                self._offset_meta.get((ns, name, part, group), ""),
            )

    def flush(self) -> None:
        with self._lock:
            for st in self._topics.values():
                for log in st.logs.values():
                    log.flush()
                for parity in st.parity.values():
                    parity.flush()


class MqService:
    """gRPC servicer (method table in pb/rpc.py MQ_SERVICE)."""

    def __init__(self, broker: MqBroker, balancer=None, load_fn=None):
        self.broker = broker
        self.balancer = balancer
        self.load_fn = load_fn  # gravity telemetry source (server-level)

    # ------------------------------------------------------ multi-broker

    def BrokerStatus(self, request, context):
        bal = self.balancer
        fn = self.load_fn or self.broker.load_score
        try:
            load = float(fn())
        except Exception:  # noqa: BLE001 — telemetry must not fail pings
            load = 0.0
        return mq.BrokerStatusResponse(
            address=bal.self_addr if bal else "",
            peers=bal.peers if bal else [],
            uptime_seconds=int(time.time() - bal.started_at) if bal else 0,
            load_score=load,
        )

    def LookupTopicBrokers(self, request, context):
        t = request.topic
        ns = t.namespace or "default"
        try:
            st = self.broker.topic(ns, t.name)
        except KeyError as e:
            return mq.LookupTopicBrokersResponse(error=str(e))
        bal = self.balancer
        if bal is None:
            return mq.LookupTopicBrokersResponse(
                assignments=[
                    mq.BrokerPartitionAssignment(partition=p, leader="")
                    for p in range(st.partition_count)
                ]
            )
        return mq.LookupTopicBrokersResponse(
            assignments=[
                mq.BrokerPartitionAssignment(
                    partition=p, leader=leader, follower=follower
                )
                for p, leader, follower in bal.assignments(
                    ns, t.name, st.partition_count
                )
            ]
        )

    def FollowAppend(self, request, context):
        """Leader → follower synchronous replication (reference
        broker_grpc_pub_follow.go)."""
        t = request.topic
        ns = t.namespace or "default"
        try:
            st = self.broker.topic(ns, t.name)
        except KeyError:
            # follower that missed the configure broadcast lazily
            # materializes the topic at the leader's partition count
            self.broker.configure_topic(
                ns, t.name, request.partition_count or 1
            )
            st = self.broker.topic(ns, t.name)
        part = request.partition
        plog = st.logs.get(part)
        if plog is None:
            return mq.FollowAppendResponse(error=f"partition {part} absent")
        expected = plog.append_at(
            request.offset,
            request.message.ts_ns or time.time_ns(),
            request.message.key,
            request.message.value,
        )
        if expected <= request.offset:
            # gap: this replica is missing [expected, offset); tell the
            # leader so it backfills before re-sending
            return mq.FollowAppendResponse(error=f"gap:{expected}")
        return mq.FollowAppendResponse()

    def DeleteTopic(self, request, context):
        try:
            self.broker.delete_topic(request.ns or "default", request.name)
        except KeyError as e:
            return mq.DeleteTopicResponse(error=str(e))
        return mq.DeleteTopicResponse()

    def CompactTopic(self, request, context):
        try:
            n = self.broker.compact_topic(
                request.ns or "default", request.name
            )
        except KeyError as e:
            return mq.CompactTopicResponse(error=str(e))
        return mq.CompactTopicResponse(archived_segments=n)

    def TruncateTopic(self, request, context):
        try:
            n = self.broker.truncate_topic(
                request.ns or "default",
                request.name,
                partition=request.partition,
                before_offset=request.before_offset,
            )
        except KeyError as e:
            return mq.TruncateTopicResponse(error=str(e))
        return mq.TruncateTopicResponse(truncated_partitions=n)

    def ConfigureTopic(self, request, context):
        t = request.topic
        # durable_parity rides the wire as a tri-state int32 (proto3
        # scalar presence is unknowable): 0 = broker default, 1 = on,
        # 2 = off — the gRPC twin of the Python API's None/True/False.
        dp = {1: True, 2: False}.get(int(request.durable_parity))
        self.broker.configure_topic(
            t.namespace or "default", t.name, request.partition_count,
            durable_parity=dp,
        )
        # broadcast: every broker needs the topic state (any of them
        # may lead or follow any partition)
        bal = self.balancer
        if bal is not None and not balancer_mod.is_forwarded(context):
            for peer in bal.peers:
                if peer == bal.self_addr:
                    continue
                try:
                    bal.stub(peer).ConfigureTopic(
                        request,
                        metadata=balancer_mod.FWD_METADATA,
                        timeout=5,
                    )
                except grpc.RpcError:
                    pass  # down peers re-learn via FollowAppend/recovery
        return mq.ConfigureTopicResponse()

    def ListTopics(self, request, context):
        return mq.ListTopicsResponse(
            topics=[
                mq.TopicInfo(
                    topic=mq.Topic(namespace=ns, name=name),
                    partition_count=count,
                )
                for ns, name, count in self.broker.list_topics()
            ]
        )

    def Publish(self, request, context):
        t = request.topic
        ns = t.namespace or "default"
        try:
            st = self.broker.topic(ns, t.name)
        except KeyError as e:
            return mq.PublishResponse(error=str(e))
        err = self.broker.validate_against_schema(
            ns, t.name, bytes(request.message.value)
        )
        if err:
            return mq.PublishResponse(error=f"schema violation: {err}")
        part = self.broker.pick_partition(
            st, request.message.key, request.partition
        )
        bal = self.balancer
        # the Kafka gateway owns its namespace on its own broker (Kafka
        # clients see a single-broker cluster); only native topics ride
        # the balancer
        balanced = (
            bal is not None and not bal.single and ns != "kafka"
        )
        leader = follower = ""
        if balanced:
            leader, follower = bal.assignment(ns, t.name, part)
        if (
            balanced
            and leader != bal.self_addr
            and not balancer_mod.is_forwarded(context)
        ):
            # transparent forward: any broker accepts any publish
            # (reference pub_balancer routing)
            fwd = mq.PublishRequest(topic=request.topic, partition=part)
            fwd.message.CopyFrom(request.message)
            try:
                return bal.stub(leader).Publish(
                    fwd, metadata=balancer_mod.FWD_METADATA, timeout=10
                )
            except grpc.RpcError as e:
                return mq.PublishResponse(
                    error=f"forward to {leader}: {e.code()}"
                )
        ts = request.message.ts_ns or time.time_ns()
        off = st.logs[part].append(ts, request.message.key, request.message.value)
        if balanced and follower and follower != bal.self_addr:
            self._replicate(request.topic, ns, st, part, off, ts,
                            request.message, follower)
        return mq.PublishResponse(offset=off, partition=part)

    def _replicate(
        self, topic, ns: str, st, part: int, off: int, ts: int,
        message, follower: str,
    ) -> None:
        """Sync-replicate one record; on a reported gap, backfill the
        follower from this leader's log first (a rejoining follower
        must never hold silent holes — they become lost acked records
        at promotion)."""
        def send(o: int, ts_ns: int, key: bytes, value: bytes) -> str:
            fa = mq.FollowAppendRequest(
                topic=topic,
                partition=part,
                offset=o,
                partition_count=st.partition_count,
                message=mq.DataMessage(key=key, value=value, ts_ns=ts_ns),
            )
            return bal_stub.FollowAppend(fa, timeout=10).error

        bal_stub = self.balancer.stub(follower)
        try:
            err = send(off, ts, message.key, message.value)
            if err.startswith("gap:"):
                start = int(err[4:])
                for o, rts, k, v in st.logs[part].read_from(
                    start, max_records=off - start + 1
                ):
                    if o > off:
                        break
                    err = send(o, rts, k, v)
                    if err and not err.startswith("gap:"):
                        break
            if err and not err.startswith("gap:"):
                # a non-gap refusal (partition absent, ...) is a replica
                # hole no protocol will repair — it must be visible
                mlog.warning(
                    "follow append %s/%s[%d]@%d -> %s refused: %s",
                    ns, topic.name, part, off, follower, err,
                )
        except (grpc.RpcError, ValueError) as e:
            # availability over strictness: acked on the leader; the
            # gap protocol repairs the replica on the next publish
            mlog.warning(
                "follow append %s/%s[%d]@%d -> %s failed: %s",
                ns, topic.name, part, off, follower, e,
            )

    def Subscribe(self, request, context):
        t = request.topic
        ns = t.namespace or "default"
        try:
            st = self.broker.topic(ns, t.name)
        except KeyError:
            context.abort(grpc.StatusCode.NOT_FOUND, "topic not configured")
        part = request.partition % st.partition_count
        bal = self.balancer
        resumed_at = -1
        if (
            bal is not None
            and not bal.single
            and ns != "kafka"
            and not balancer_mod.is_forwarded(context)
        ):
            leader, follower = bal.assignment(ns, t.name, part)
            if leader != bal.self_addr:
                # proxy the stream from the partition's leader; on a
                # mid-stream leader death, resume PAST what was already
                # yielded (never re-deliver), and only from a broker
                # actually holding a replica
                last = -1
                try:
                    for rec in bal.stub(leader).Subscribe(
                        request, metadata=balancer_mod.FWD_METADATA
                    ):
                        if not rec.end_of_stream:
                            last = rec.offset
                        yield rec
                        if rec.end_of_stream:
                            return
                    return
                except grpc.RpcError:
                    if bal.self_addr not in (follower,):
                        context.abort(
                            grpc.StatusCode.UNAVAILABLE,
                            f"leader {leader} unreachable and this "
                            "broker holds no replica",
                        )
                    if last >= 0:
                        resumed_at = last + 1
                    # else: nothing was delivered — fall through to the
                    # normal offset resolution (start_offset/committed),
                    # never to an unconditional 0
        log = st.logs[part]
        if resumed_at >= 0:
            offset = resumed_at
        elif request.start_offset >= 0:
            offset = request.start_offset
        elif request.consumer_group and (
            committed := self.broker.fetch_offset(
                t.namespace or "default", t.name, part, request.consumer_group
            )
        ) >= 0:
            offset = committed
        else:
            offset = log.next_offset  # tail
        while context.is_active():
            batch = log.read_from(offset)
            for off, ts, key, value in batch:
                yield mq.SubscribeRecord(
                    message=mq.DataMessage(key=key, value=value, ts_ns=ts),
                    offset=off,
                    partition=part,
                )
                offset = off + 1
            if not batch:
                if not request.follow:
                    yield mq.SubscribeRecord(end_of_stream=True, partition=part)
                    return
                log.wait_for(offset, timeout=1.0)

    def _route_to_leader(self, ns: str, name: str, part: int, context):
        """The partition leader to forward an offset op to, or None to
        serve locally (single broker / kafka ns / already forwarded /
        we ARE the leader)."""
        bal = self.balancer
        if (
            bal is None
            or bal.single
            or ns == "kafka"
            or balancer_mod.is_forwarded(context)
        ):
            return None
        leader, _f = bal.assignment(ns, name, part)
        return None if leader == bal.self_addr else leader

    def CommitOffset(self, request, context):
        t = request.topic
        ns = t.namespace or "default"
        # group offsets live with the partition leader (the broker
        # Subscribe proxies to) — otherwise commits fragment per broker
        leader = self._route_to_leader(ns, t.name, request.partition, context)
        if leader is not None:
            try:
                return self.balancer.stub(leader).CommitOffset(
                    request, metadata=balancer_mod.FWD_METADATA, timeout=10
                )
            except grpc.RpcError:
                # surface the failure: a silent local commit would be
                # invisible to every future FetchOffset (which routes
                # to the leader) — let the client retry instead
                context.abort(
                    grpc.StatusCode.UNAVAILABLE,
                    f"offset leader {leader} unreachable",
                )
        self.broker.commit_offset(
            ns, t.name, request.partition, request.consumer_group,
            request.offset,
        )
        return mq.CommitOffsetResponse()

    def FetchOffset(self, request, context):
        t = request.topic
        ns = t.namespace or "default"
        leader = self._route_to_leader(ns, t.name, request.partition, context)
        if leader is not None:
            try:
                return self.balancer.stub(leader).FetchOffset(
                    request, metadata=balancer_mod.FWD_METADATA, timeout=10
                )
            except grpc.RpcError:
                pass
        return mq.FetchOffsetResponse(
            offset=self.broker.fetch_offset(
                ns, t.name, request.partition, request.consumer_group
            )
        )

    def RegisterSchema(self, request, context):
        t = request.topic
        try:
            self.broker.set_schema(
                t.namespace or "default", t.name, request.schema_json
            )
        except (KeyError, ValueError, json.JSONDecodeError) as e:
            return mq.RegisterSchemaResponse(error=str(e))
        return mq.RegisterSchemaResponse()

    def GetSchema(self, request, context):
        t = request.topic
        return mq.GetSchemaResponse(
            schema_json=self.broker.get_schema(
                t.namespace or "default", t.name
            )
        )

    def PartitionInfo(self, request, context):
        t = request.topic
        try:
            st = self.broker.topic(t.namespace or "default", t.name)
        except KeyError:
            context.abort(grpc.StatusCode.NOT_FOUND, "topic not configured")
        return mq.PartitionInfoResponse(
            partitions=[
                mq.PartitionInfo(
                    partition=p,
                    earliest_offset=log.earliest_offset,
                    next_offset=log.next_offset,
                )
                for p, log in sorted(st.logs.items())
            ]
        )


class MqBrokerServer:
    def __init__(
        self,
        ip: str = "localhost",
        grpc_port: int = 17777,
        filer: str = "",
        segment_records: int = 4096,
        kafka_port: int = -1,
        pg_port: int = -1,
        pg_users: dict[str, str] | None = None,
        peers: list[str] | None = None,
        archive_interval: float = 300.0,
        parity_dir: str = "",
        durable_parity_default: bool | None = None,
        status_port: int = -1,
    ):
        """kafka_port >= 0 also serves the Kafka wire protocol on that
        port; pg_port >= 0 serves PostgreSQL clients a SQL view over
        the topics (0 = ephemeral; see .kafka.port / .pg.port).
        peers: every broker's grpc host:port for multi-broker partition
        balancing + follower replication. parity_dir: local dir for
        streaming-EC durable-parity log streams (see MqBroker).
        status_port >= 0 serves /status (JSON roll-up incl. the Kafka
        gateway pool) and /metrics (sw_mq_*) over HTTP (0 =
        ephemeral; see .status_port after start)."""
        self.ip = ip
        self.grpc_port = grpc_port
        self.broker = MqBroker(
            filer=filer, segment_records=segment_records,
            parity_dir=parity_dir,
            durable_parity_default=durable_parity_default,
        )
        self.balancer = balancer_mod.BrokerBalancer(
            f"{ip}:{grpc_port}", list(peers or [])
        )
        self.balancer.load_fn = self.load_score
        self.service = MqService(
            self.broker, balancer=self.balancer, load_fn=self.load_score
        )
        self._grpc = grpc.server(futures.ThreadPoolExecutor(max_workers=32))
        rpc.add_service(self._grpc, rpc.MQ_SERVICE, self.service)
        self._grpc.add_insecure_port(f"{ip}:{grpc_port}")
        self.kafka = None
        if kafka_port >= 0:
            from .kafka.gateway import KafkaGateway

            self.kafka = KafkaGateway(self.broker, ip=ip, port=kafka_port)
        self.pg = None
        if pg_port >= 0:
            from ..query.engine import QueryEngine
            from ..query.pg_server import PgServer

            self.pg = PgServer(
                QueryEngine(self.broker), ip=ip, port=pg_port, users=pg_users
            )
        # parquet archival of sealed segments (reference weed/mq/logstore)
        self.archiver = None
        self._archive_stop = threading.Event()
        self._archive_thread = None
        if filer and archive_interval > 0:
            from .logstore import SegmentArchiver

            self.archiver = SegmentArchiver(self.broker)
            self._archive_thread = threading.Thread(
                target=self._archive_loop,
                args=(archive_interval,),
                daemon=True,
            )
        # operator HTTP plane: /status + /metrics (mirrors the volume
        # server's listener; advisory sections never fail the endpoint)
        self._status_httpd = None
        self.status_port = status_port
        if status_port >= 0:
            self._status_httpd = self._build_status_httpd(ip, status_port)
            self.status_port = self._status_httpd.server_address[1]

    def _archive_loop(self, interval: float) -> None:
        while not self._archive_stop.wait(interval):
            try:
                self.archiver.run_once()
            except Exception as e:  # noqa: BLE001 — never kill the broker
                log.warning(f"segment archival cycle failed: {e!r}")

    def load_score(self) -> float:
        """Gravity telemetry shipped on BrokerStatus pings: parity
        backlog (flush-threshold units) + Kafka gateway pool pressure
        (ready backlog per worker + connection-slot occupancy). 0 when
        idle; ~1 per saturated dimension."""
        score = self.broker.load_score()
        if self.kafka is not None:
            try:
                ps = self.kafka.pool_status()
                workers = max(1, int(ps.get("workers") or 1))
                score += float(ps.get("ready_backlog", 0)) / workers
                slots = max(1, int(ps.get("max_connections") or 1))
                score += float(ps.get("open_connections", 0)) / slots
            except Exception:  # noqa: BLE001 — telemetry only
                pass
        return score

    def status(self) -> dict:
        """Operator JSON roll-up served at /status."""
        st = {
            "address": self.balancer.self_addr,
            "peers": self.balancer.peers,
            "live_brokers": self.balancer.live(),
            "broker_loads": self.balancer.loads(),
            "load_score": self.load_score(),
            "topics": [
                {"namespace": ns, "name": name, "partitions": count}
                for ns, name, count in self.broker.list_topics()
            ],
        }
        try:
            st["parity"] = self.broker.parity_status()
        except Exception:  # noqa: BLE001 — advisory
            pass
        if self.kafka is not None:
            try:
                st["kafka_pool"] = self.kafka.pool_status()
            except Exception:  # noqa: BLE001 — advisory
                pass
        return st

    def _build_status_httpd(self, ip: str, port: int):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet
                pass

            def _send(self, code, body, ctype):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path.split("?", 1)[0] == "/metrics":
                    from ..utils.metrics import REGISTRY

                    self._send(
                        200, REGISTRY.render(),
                        "text/plain; version=0.0.4",
                    )
                    return
                if self.path.split("?", 1)[0] == "/status":
                    body = json.dumps(server.status()).encode()
                    self._send(200, body, "application/json")
                    return
                self._send(404, b"not found", "text/plain")

        httpd = ThreadingHTTPServer((ip, port), Handler)
        httpd.daemon_threads = True
        return httpd

    def start(self) -> None:
        self._grpc.start()
        self.balancer.start()
        if self.kafka is not None:
            self.kafka.start()
        if self.pg is not None:
            self.pg.start()
        if self._archive_thread is not None:
            self._archive_thread.start()
        if self._status_httpd is not None:
            threading.Thread(
                target=self._status_httpd.serve_forever, daemon=True
            ).start()

    def stop(self) -> None:
        self._archive_stop.set()
        if self._status_httpd is not None:
            self._status_httpd.shutdown()
            self._status_httpd.server_close()
        self.balancer.stop()
        if self.kafka is not None:
            self.kafka.stop()
        if self.pg is not None:
            self.pg.stop()
        self.broker.close()  # parity flusher + streams, then flush
        self._grpc.stop(grace=0.5)
