"""Message queue (layer 7): broker, partition logs, pub/sub client."""

from .broker import MqBroker, MqBrokerServer, MqService
from .client import MqClient
from .log_buffer import PartitionLog, decode_records, encode_record
