"""Multi-broker partition balancing + liveness.

Reference: weed/mq/pub_balancer — brokers share partition ownership;
clients look up per-partition leaders. Here ownership is computed by
rendezvous (HRW) hashing over the LIVE broker set: every broker ranks
(broker, topic, partition) and the top-ranked live broker leads, the
runner-up follows. HRW gives the failover property for free: when a
leader dies, the new top-ranked broker IS the old follower, which holds
the replica fed by FollowAppend — so promotion loses nothing.

Gravity (ISSUE 20): BrokerStatus pings now carry each peer's live
load_score (parity backlog + Kafka gateway pool pressure). Assignment
keeps the HRW ranking for stability but demotes the top-ranked broker
to follower when it is hotter than the runner-up by more than
SEAWEED_MQ_GRAVITY_HYSTERESIS — load noise inside the margin cannot
flap leadership, and brokers with divergent load views are absorbed by
the is_forwarded single-hop rule.
"""

from __future__ import annotations

import hashlib
import os
import threading
import time

import grpc

from ..pb import mq_pb2 as mq
from ..pb import rpc
from ..utils.glog import logger

log = logger("mq-balancer")

FORWARDED_KEY = "sw-forwarded"


def _score(broker: str, ns: str, name: str, part: int) -> bytes:
    return hashlib.md5(f"{broker}|{ns}|{name}|{part}".encode()).digest()


def gravity_hysteresis() -> float:
    """SEAWEED_MQ_GRAVITY_HYSTERESIS: how much hotter (in load-score
    units) the HRW leader must be than the runner-up before assignment
    swaps them. Read live per call."""
    try:
        return float(os.environ.get("SEAWEED_MQ_GRAVITY_HYSTERESIS", "1.5"))
    except ValueError:
        return 1.5


def is_forwarded(context) -> bool:
    """True when a peer broker already routed this request to us — a
    second hop must serve locally (divergent live-set views must not
    forward in a loop)."""
    if context is None:
        return False
    try:
        return any(
            k == FORWARDED_KEY for k, _v in context.invocation_metadata()
        )
    except AttributeError:
        return False


FWD_METADATA = ((FORWARDED_KEY, "1"),)


class BrokerBalancer:
    def __init__(
        self,
        self_addr: str,
        peers: list[str],
        ping_interval: float = 1.0,
        ping_timeout: float = 0.75,
    ):
        """peers: every broker's grpc host:port, including (or not)
        this one."""
        self.self_addr = self_addr
        self.peers = sorted(set(peers) | {self_addr})
        self.ping_interval = ping_interval
        self.ping_timeout = ping_timeout
        self._live = set(self.peers)  # optimistic until pings say otherwise
        self._loads: dict[str, float] = {}  # addr -> last load_score
        self.load_fn = None  # server-injected: this broker's own load
        self._lock = threading.Lock()
        self._channels: dict[str, grpc.Channel] = {}
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._ping_loop, daemon=True)
        self.started_at = time.time()

    @property
    def single(self) -> bool:
        return len(self.peers) == 1

    def start(self) -> None:
        if not self.single:
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        # join the ping loop FIRST: an in-flight iteration would
        # recreate (and leak) channels after the clear below
        if self._thread.is_alive():
            self._thread.join(timeout=2 * self.ping_timeout + 1)
        with self._lock:
            for ch in self._channels.values():
                ch.close()
            self._channels.clear()

    # --------------------------------------------------------- liveness

    def stub(self, addr: str) -> rpc.Stub:
        with self._lock:
            ch = self._channels.get(addr)
            if ch is None:
                ch = grpc.insecure_channel(addr)
                self._channels[addr] = ch
        return rpc.mq_stub(ch)

    def live(self) -> list[str]:
        with self._lock:
            return sorted(self._live)

    def _ping_loop(self) -> None:
        while not self._stop.wait(self.ping_interval):
            live = {self.self_addr}
            loads: dict[str, float] = {}
            if self.load_fn is not None:
                try:
                    loads[self.self_addr] = float(self.load_fn())
                except Exception:  # noqa: BLE001 — telemetry only
                    pass
            for peer in self.peers:
                if peer == self.self_addr:
                    continue
                try:
                    resp = self.stub(peer).BrokerStatus(
                        mq.BrokerStatusRequest(), timeout=self.ping_timeout
                    )
                    live.add(peer)
                    loads[peer] = float(getattr(resp, "load_score", 0.0))
                except grpc.RpcError:
                    pass
            with self._lock:
                if live != self._live:
                    log.info(
                        "live broker set: %s -> %s",
                        sorted(self._live),
                        sorted(live),
                    )
                self._live = live
                self._loads = loads

    def loads(self) -> dict[str, float]:
        """Last observed load_score per broker (missing = no telemetry
        yet — gravity then leaves the HRW ranking alone)."""
        with self._lock:
            return dict(self._loads)

    # ------------------------------------------------------- assignment

    def assignment(
        self, ns: str, name: str, part: int
    ) -> tuple[str, str]:
        """(leader, follower) for one partition over the live set.

        Gravity: when both the HRW leader and runner-up have load
        telemetry and the leader is hotter by more than the hysteresis
        margin, the pair swaps — the partition lands on the cooler
        broker while the HRW winner keeps the replica, so failover
        still loses nothing."""
        live = self.live()
        if not live:
            return self.self_addr, ""
        ranked = sorted(
            live, key=lambda b: _score(b, ns, name, part), reverse=True
        )
        leader = ranked[0]
        follower = ranked[1] if len(ranked) > 1 else ""
        if follower:
            loads = self.loads()
            hot, cool = loads.get(leader), loads.get(follower)
            if (
                hot is not None
                and cool is not None
                and hot > cool + gravity_hysteresis()
            ):
                leader, follower = follower, leader
        return leader, follower

    def assignments(
        self, ns: str, name: str, count: int
    ) -> list[tuple[int, str, str]]:
        return [
            (p, *self.assignment(ns, name, p)) for p in range(count)
        ]
