"""MQ client: publisher + subscriber sessions (reference weed/mq/client
and the agent's session brokering, simplified to direct broker calls)."""

from __future__ import annotations

import time
from typing import Iterator, Optional

import grpc

from ..pb import mq_pb2 as mq
from ..pb import rpc


class MqClient:
    def __init__(self, broker: str):
        self._channel = grpc.insecure_channel(broker)
        self.stub = rpc.mq_stub(self._channel)

    def configure_topic(
        self,
        name: str,
        partitions: int = 4,
        namespace: str = "default",
        durable_parity: bool | None = None,
    ) -> None:
        """`durable_parity` mirrors the broker's Python API over the
        wire (tri-state int32 field 3: 0 = broker default, 1 = on,
        2 = off): a REMOTE client can now opt a topic's partitions in
        or out of the streaming-EC parity stream."""
        self.stub.ConfigureTopic(
            mq.ConfigureTopicRequest(
                topic=mq.Topic(namespace=namespace, name=name),
                partition_count=partitions,
                durable_parity=(
                    0 if durable_parity is None
                    else (1 if durable_parity else 2)
                ),
            ),
            timeout=30,
        )

    def topics(self) -> list[tuple[str, str, int]]:
        resp = self.stub.ListTopics(mq.ListTopicsRequest(), timeout=30)
        return [
            (t.topic.namespace, t.topic.name, t.partition_count)
            for t in resp.topics
        ]

    def publish(
        self,
        name: str,
        value: bytes,
        key: bytes = b"",
        namespace: str = "default",
        partition: int = -1,
    ) -> tuple[int, int]:
        """-> (partition, offset)."""
        resp = self.stub.Publish(
            mq.PublishRequest(
                topic=mq.Topic(namespace=namespace, name=name),
                partition=partition,
                message=mq.DataMessage(key=key, value=value, ts_ns=time.time_ns()),
            ),
            timeout=30,
        )
        if resp.error:
            raise RuntimeError(resp.error)
        return resp.partition, resp.offset

    def subscribe(
        self,
        name: str,
        partition: int,
        start_offset: int = -1,  # -1: committed group offset, else tail
        namespace: str = "default",
        consumer_group: str = "",
        follow: bool = False,
        timeout: Optional[float] = None,
    ) -> Iterator[mq.SubscribeRecord]:
        stream = self.stub.Subscribe(
            mq.SubscribeRequest(
                topic=mq.Topic(namespace=namespace, name=name),
                partition=partition,
                start_offset=start_offset,
                consumer_group=consumer_group,
                follow=follow,
            ),
            timeout=timeout,
        )
        for rec in stream:
            if rec.end_of_stream:
                return
            yield rec

    def commit(self, name: str, partition: int, group: str, offset: int, namespace: str = "default") -> None:
        self.stub.CommitOffset(
            mq.CommitOffsetRequest(
                topic=mq.Topic(namespace=namespace, name=name),
                partition=partition,
                consumer_group=group,
                offset=offset,
            ),
            timeout=30,
        )

    def committed(self, name: str, partition: int, group: str, namespace: str = "default") -> int:
        return self.stub.FetchOffset(
            mq.FetchOffsetRequest(
                topic=mq.Topic(namespace=namespace, name=name),
                partition=partition,
                consumer_group=group,
            ),
            timeout=30,
        ).offset

    def partition_info(self, name: str, namespace: str = "default"):
        return self.stub.PartitionInfo(
            mq.PartitionInfoRequest(topic=mq.Topic(namespace=namespace, name=name)),
            timeout=30,
        ).partitions

    def close(self) -> None:
        self._channel.close()
