"""Remote-storage provider registry (reference weed/remote_storage:
pluggable s3/gcs/azure/aliyun/... clients behind one interface).

The S3 client is native (own SigV4 signer, remote/s3_client.py) and
also fronts every S3-compatible store (MinIO, Ceph RGW, Wasabi, B2's
S3 endpoint, GCS's XML interop endpoint with HMAC keys). GCS-native
and Azure-Blob-native protocols need their SDKs, which this image does
not ship — those providers are GATED with explicit errors instead of
silently missing, and the SPI is the seam a deployment with the SDKs
installed plugs into.
"""

from __future__ import annotations

from typing import Callable

from .s3_client import RemoteS3Client

_REGISTRY: dict[str, Callable] = {}


def register(kind: str, factory: Callable) -> None:
    _REGISTRY[kind] = factory


def make_remote_client(
    kind: str,
    endpoint: str = "",
    access_key: str = "",
    secret_key: str = "",
    region: str = "us-east-1",
    **kw,
):
    """kind: s3 | gcs-s3 | gcs | azure | <registered>. Returns a client
    with the RemoteS3Client surface (list/get/put/delete objects)."""
    if kind in _REGISTRY:
        return _REGISTRY[kind](
            endpoint=endpoint,
            access_key=access_key,
            secret_key=secret_key,
            region=region,
            **kw,
        )
    if kind == "s3":
        return RemoteS3Client(
            endpoint=endpoint,
            access_key=access_key,
            secret_key=secret_key,
            region=region,
            **kw,
        )
    if kind == "gcs-s3":
        # GCS XML interoperability endpoint speaks S3 with HMAC keys
        return RemoteS3Client(
            endpoint=endpoint or "https://storage.googleapis.com",
            access_key=access_key,
            secret_key=secret_key,
            region=region,
            **kw,
        )
    if kind == "gcs":
        try:
            import google.cloud.storage  # noqa: F401
        except ImportError as e:
            raise RuntimeError(
                "native GCS requires google-cloud-storage (not installed in "
                "this build); use kind='gcs-s3' (the XML interop endpoint "
                "with HMAC keys) or register() a provider"
            ) from e
        raise NotImplementedError("gcs: SDK present but unwired")
    if kind == "azure":
        try:
            import azure.storage.blob  # noqa: F401
        except ImportError as e:
            raise RuntimeError(
                "Azure Blob requires azure-storage-blob (not installed in "
                "this build); use an S3-compatible gateway or register() a "
                "provider"
            ) from e
        raise NotImplementedError("azure: SDK present but unwired")
    raise ValueError(f"unknown remote storage kind {kind!r}")
