"""Lazy remote mounts: a filer directory backed by a cloud bucket.

Reference: weed/filer/read_remote.go + filer_lazy_remote*.go and the
shell's remote.configure/mount/cache/uncache/unmount commands —
metadata is materialized at mount time (names, sizes, etags; no data),
reads stream through from the remote on demand, and `cache` pins a
file's bytes into local chunks (uncache drops them again).

Storage conventions (all inside the filer itself, like the reference's
filer-conf):
  KV  remote.conf:<name>    -> JSON client config
  KV  remote.mount:<dir>    -> JSON {remote, bucket, prefix}
  entry.extended["sw-remote"] -> JSON {remote, bucket, key, size, etag}
"""

from __future__ import annotations

import json

from ..filer.entry import Entry, new_entry, normalize_path
from ..filer.filer import Filer, FilerError
from .s3_client import RemoteS3Client

REMOTE_ATTR = "sw-remote"


def configure(filer: Filer, name: str, conf: dict) -> None:
    """conf: {endpoint, access_key, secret_key, region}."""
    filer.store.kv_put(f"remote.conf:{name}".encode(), json.dumps(conf).encode())


def get_client(filer: Filer, name: str) -> RemoteS3Client:
    raw = filer.store.kv_get(f"remote.conf:{name}".encode())
    if raw is None:
        raise FilerError(f"remote storage {name!r} not configured")
    conf = json.loads(raw)
    return RemoteS3Client(
        endpoint=conf["endpoint"],
        access_key=conf.get("access_key", ""),
        secret_key=conf.get("secret_key", ""),
        region=conf.get("region", "us-east-1"),
    )


def list_mounts(filer: Filer) -> dict[str, dict]:
    out = {}
    raw = filer.store.kv_get(b"remote.mounts")
    if raw:
        out = json.loads(raw)
    return out


def _save_mounts(filer: Filer, mounts: dict) -> None:
    filer.store.kv_put(b"remote.mounts", json.dumps(mounts).encode())


def mount(
    filer: Filer, directory: str, remote_name: str, bucket: str, prefix: str = ""
) -> int:
    """Materialize the remote listing as entries under `directory`;
    returns how many objects were mapped."""
    directory = normalize_path(directory)
    client = get_client(filer, remote_name)
    mounts = list_mounts(filer)
    if directory in mounts:
        raise FilerError(f"{directory} is already a remote mount")
    objs = client.list_objects(bucket, prefix)
    n = 0
    for obj in objs:
        rel = obj.key[len(prefix) :].lstrip("/")
        if not rel or rel.endswith("/"):
            continue
        path = f"{directory}/{rel}"
        entry = new_entry(path, mode=0o644)
        entry.attr.file_size = obj.size
        entry.extended[REMOTE_ATTR] = json.dumps(
            {
                "remote": remote_name,
                "bucket": bucket,
                "key": obj.key,
                "size": obj.size,
                "etag": obj.etag,
            }
        ).encode()
        filer.create_entry(entry)
        n += 1
    mounts[directory] = {
        "remote": remote_name,
        "bucket": bucket,
        "prefix": prefix,
    }
    _save_mounts(filer, mounts)
    return n


def mount_buckets(
    filer: Filer, directory: str, remote_name: str, prefix_filter: str = ""
) -> dict[str, int]:
    """Mount EVERY bucket of a configured remote under
    directory/<bucket> (reference remote.mount.buckets); returns
    {bucket: objects_mapped}. Already-mounted buckets are skipped."""
    directory = normalize_path(directory)
    client = get_client(filer, remote_name)
    mounts = list_mounts(filer)
    out: dict[str, int] = {}
    for bucket in client.list_buckets():
        if prefix_filter and not bucket.startswith(prefix_filter):
            continue
        target = f"{directory}/{bucket}"
        if target in mounts:
            continue
        out[bucket] = mount(filer, target, remote_name, bucket)
        mounts = list_mounts(filer)  # mount() persisted a new entry
    return out


def meta_sync(filer: Filer, directory: str) -> tuple[int, int, int]:
    """Refresh a mount's metadata from the remote listing (reference
    remote.meta.sync): new objects appear, changed sizes/etags update,
    objects gone remotely drop their local entries. Returns
    (added, updated, removed)."""
    directory = normalize_path(directory)
    mounts = list_mounts(filer)
    conf = mounts.get(directory)
    if conf is None:
        raise FilerError(f"{directory} is not a remote mount")
    client = get_client(filer, conf["remote"])
    prefix = conf.get("prefix", "")
    remote_objs = {
        obj.key[len(prefix):].lstrip("/"): obj
        for obj in client.list_objects(conf["bucket"], prefix)
        if obj.key[len(prefix):].lstrip("/") and not obj.key.endswith("/")
    }
    local: dict[str, Entry] = {}

    def walk(d: str, rel: str = ""):
        for e in filer.list_entries(d, limit=1_000_000):
            if e.is_directory:
                walk(e.full_path, f"{rel}{e.name}/")
            elif REMOTE_ATTR in e.extended:
                local[f"{rel}{e.name}"] = e

    walk(directory)
    added = updated = removed = 0
    for rel, obj in remote_objs.items():
        meta = {
            "remote": conf["remote"],
            "bucket": conf["bucket"],
            "key": obj.key,
            "size": obj.size,
            "etag": obj.etag,
        }
        have = local.get(rel)
        if have is None:
            entry = new_entry(f"{directory}/{rel}", mode=0o644)
            entry.attr.file_size = obj.size
            entry.extended[REMOTE_ATTR] = json.dumps(meta).encode()
            filer.create_entry(entry)
            added += 1
            continue
        old_meta = json.loads(have.extended[REMOTE_ATTR])
        if (old_meta.get("etag"), old_meta.get("size")) != (obj.etag, obj.size):
            old_chunks: list = []

            def mutate(e, _m=meta, _o=obj, _oc=old_chunks):
                _oc.extend(e.chunks)
                e.attr.file_size = _o.size
                e.chunks = []  # cached bytes are stale: drop them
                e.extended[REMOTE_ATTR] = json.dumps(_m).encode()

            filer.mutate_entry(have.full_path, mutate)
            if old_chunks:
                # the dropped cache chunks must be reclaimed (same
                # discipline as uncache), or every sync cycle over a
                # cached mount leaks volume space
                filer.gc_chunks(old_chunks)
            updated += 1
    for rel, e in local.items():
        if rel not in remote_objs:
            filer.delete_entry(e.full_path, gc_chunks=True)
            removed += 1
    return added, updated, removed


def unmount(filer: Filer, directory: str) -> None:
    directory = normalize_path(directory)
    mounts = list_mounts(filer)
    if directory not in mounts:
        raise FilerError(f"{directory} is not a remote mount")
    # local-cache chunks under the mount ARE reclaimed; remote data is
    # untouched (the mount is a view)
    filer.delete_entry(directory, recursive=True)
    del mounts[directory]
    _save_mounts(filer, mounts)


def read_remote(
    filer: Filer, entry: Entry, offset: int = 0, size: int = -1
) -> bytes:
    """Read-through for an uncached remote entry."""
    meta = json.loads(entry.extended[REMOTE_ATTR])
    client = get_client(filer, meta["remote"])
    return client.get_object(
        meta["bucket"], meta["key"], offset=offset, size=size
    )


def cache(filer: Filer, path: str) -> Entry:
    """Pin a remote file's bytes into local chunks (remote.cache)."""
    entry = filer.find_entry(path)
    raw = entry.extended.get(REMOTE_ATTR)
    if raw is None:
        raise FilerError(f"{path} is not remote-mounted")
    if entry.chunks or entry.content:
        return entry  # already cached
    data = read_remote(filer, entry)
    cached = filer.write_file(
        path, data, mime=entry.attr.mime, extended={REMOTE_ATTR: raw}
    )
    return cached


def uncache(filer: Filer, path: str) -> Entry:
    """Drop the local copy, keep the remote mapping (remote.uncache)."""
    entry = filer.find_entry(path)
    raw = entry.extended.get(REMOTE_ATTR)
    if raw is None:
        raise FilerError(f"{path} is not remote-mounted")
    old_chunks = list(entry.chunks)

    def strip(e: Entry) -> None:
        e.chunks = []
        e.content = b""
        e.attr.file_size = json.loads(raw)["size"]

    out = filer.mutate_entry(path, strip)
    if old_chunks:
        filer.gc_chunks(old_chunks)
    return out
