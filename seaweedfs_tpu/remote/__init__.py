"""Remote (cloud) storage: client SPI, lazy remote mounts, sync sinks.

Reference: weed/remote_storage (s3/gcs/azure client SPI + tracked sync
offsets), weed/filer/read_remote.go + filer_lazy_remote*.go (cloud-
backed directories with read-through caching), weed/replication/sink/
s3sink. One concrete client here — S3-compatible with SigV4 — which
covers the framework's own S3 gateway (cluster→cluster) and any
S3-style endpoint.
"""

from .s3_client import RemoteS3Client, RemoteStorageError  # noqa: F401
