"""SigV4-signing S3 client (the remote_storage SPI's one concrete
implementation).

Reference: weed/remote_storage/s3 — list/read/write/delete objects on
an S3-compatible endpoint. Signing is AWS Signature V4 (header form),
the mirror image of the gateway's verify_v4.
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import urllib.parse
import xml.etree.ElementTree as ET
from dataclasses import dataclass

import requests

from ..utils.retry import RetryError, RetryPolicy, retry_call


class RemoteStorageError(Exception):
    pass


class TransientRemoteError(RemoteStorageError):
    """Retryable remote failure: connection reset, timeout, HTTP 5xx or
    429. Permanent rejections (4xx) stay RemoteStorageError and are
    never retried."""


# Unified policy (utils/retry.py): 3 quick signed attempts. Each
# attempt re-signs (fresh x-amz-date), so a retry is never rejected for
# clock skew accumulated while backing off.
DEFAULT_S3_RETRY_POLICY = RetryPolicy(
    max_attempts=3,
    base_delay=0.2,
    max_delay=2.0,
    retry_on=(
        TransientRemoteError,
        requests.ConnectionError,
        requests.Timeout,
    ),
)


@dataclass
class RemoteObject:
    key: str
    size: int
    etag: str = ""
    mtime: str = ""


def _sign(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


class RemoteS3Client:
    def __init__(
        self,
        endpoint: str,
        access_key: str = "",
        secret_key: str = "",
        region: str = "us-east-1",
        retry_policy: RetryPolicy | None = DEFAULT_S3_RETRY_POLICY,
    ):
        """endpoint: http(s)://host:port (path-style addressing).
        `retry_policy` governs transient-failure retries per request
        (None disables)."""
        self.endpoint = endpoint.rstrip("/")
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region
        self.retry_policy = retry_policy
        self._http = requests.Session()

    # ------------------------------------------------------------ sigv4

    def _headers(
        self, method: str, path: str, query: str, payload: bytes
    ) -> dict:
        host = urllib.parse.urlparse(self.endpoint).netloc
        now = datetime.datetime.now(datetime.timezone.utc)
        amz_date = now.strftime("%Y%m%dT%H%M%SZ")
        datestamp = now.strftime("%Y%m%d")
        phash = hashlib.sha256(payload).hexdigest()
        headers = {
            "Host": host,
            "x-amz-date": amz_date,
            "x-amz-content-sha256": phash,
        }
        if not self.access_key:
            return headers  # anonymous (open-mode gateways)
        canonical_q = "&".join(
            sorted(
                f"{urllib.parse.quote(k, safe='')}="
                f"{urllib.parse.quote(v, safe='')}"
                for k, v in urllib.parse.parse_qsl(
                    query, keep_blank_values=True
                )
            )
        )
        signed = "host;x-amz-content-sha256;x-amz-date"
        canonical = "\n".join(
            [
                method,
                urllib.parse.quote(path),
                canonical_q,
                f"host:{host}\n"
                f"x-amz-content-sha256:{phash}\n"
                f"x-amz-date:{amz_date}\n",
                signed,
                phash,
            ]
        )
        scope = f"{datestamp}/{self.region}/s3/aws4_request"
        to_sign = "\n".join(
            [
                "AWS4-HMAC-SHA256",
                amz_date,
                scope,
                hashlib.sha256(canonical.encode()).hexdigest(),
            ]
        )
        k = _sign(
            _sign(
                _sign(
                    _sign(
                        ("AWS4" + self.secret_key).encode(), datestamp
                    ),
                    self.region,
                ),
                "s3",
            ),
            "aws4_request",
        )
        sig = hmac.new(k, to_sign.encode(), hashlib.sha256).hexdigest()
        headers["Authorization"] = (
            f"AWS4-HMAC-SHA256 Credential={self.access_key}/{scope}, "
            f"SignedHeaders={signed}, Signature={sig}"
        )
        return headers

    def _request(
        self,
        method: str,
        path: str,
        query: str = "",
        payload: bytes = b"",
        extra_headers: dict | None = None,
        ok=(200,),
    ) -> requests.Response:
        url = self.endpoint + urllib.parse.quote(path)
        if query:
            url += "?" + query

        def attempt() -> requests.Response:
            headers = self._headers(method, path, query, payload)
            if extra_headers:
                headers.update(extra_headers)
            r = self._http.request(
                method, url, headers=headers, data=payload or None, timeout=60
            )
            if r.status_code not in ok:
                err = (
                    TransientRemoteError
                    if r.status_code >= 500 or r.status_code == 429
                    else RemoteStorageError
                )
                raise err(
                    f"{method} {path}: HTTP {r.status_code} {r.text[:200]}"
                )
            return r

        if self.retry_policy is None:
            return attempt()
        try:
            return retry_call(
                attempt, self.retry_policy, describe=f"s3 {method} {path}"
            )
        except RetryError as e:
            # callers classify on RemoteStorageError — surface the last
            # underlying failure in that taxonomy, not the retry wrapper
            cause = e.__cause__
            if isinstance(cause, RemoteStorageError):
                raise cause from e
            raise RemoteStorageError(str(e)) from e

    # ------------------------------------------------------- operations

    def list_objects(
        self, bucket: str, prefix: str = "", max_keys: int = 100_000
    ) -> list[RemoteObject]:
        """Full listing via ListObjectsV2 continuation."""
        out: list[RemoteObject] = []
        token = ""
        while len(out) < max_keys:
            q = "list-type=2&max-keys=1000"
            if prefix:
                q += "&prefix=" + urllib.parse.quote(prefix, safe="")
            if token:
                q += "&continuation-token=" + urllib.parse.quote(
                    token, safe=""
                )
            r = self._request("GET", f"/{bucket}", q)
            root = ET.fromstring(r.content)
            ns = ""
            if root.tag.startswith("{"):
                ns = root.tag[: root.tag.index("}") + 1]
            for c in root.findall(f"{ns}Contents"):
                out.append(
                    RemoteObject(
                        key=c.findtext(f"{ns}Key", ""),
                        size=int(c.findtext(f"{ns}Size", "0")),
                        etag=c.findtext(f"{ns}ETag", "").strip('"'),
                        mtime=c.findtext(f"{ns}LastModified", ""),
                    )
                )
            token = root.findtext(f"{ns}NextContinuationToken", "")
            if root.findtext(f"{ns}IsTruncated", "false") != "true" or not token:
                break
        return out

    def get_object(
        self, bucket: str, key: str, offset: int = 0, size: int = -1
    ) -> bytes:
        headers = {}
        if offset or size >= 0:
            end = "" if size < 0 else str(offset + size - 1)
            headers["Range"] = f"bytes={offset}-{end}"
        r = self._request(
            "GET",
            f"/{bucket}/{key}",
            extra_headers=headers,
            ok=(200, 206),
        )
        data = r.content
        if r.status_code == 200 and (offset or size >= 0):
            data = data[offset : offset + size if size >= 0 else None]
        return data

    def put_object(self, bucket: str, key: str, data: bytes) -> None:
        self._request("PUT", f"/{bucket}/{key}", payload=data, ok=(200, 201))

    def delete_object(self, bucket: str, key: str) -> None:
        self._request(
            "DELETE", f"/{bucket}/{key}", ok=(200, 202, 204, 404)
        )

    def head_object(self, bucket: str, key: str) -> RemoteObject | None:
        try:
            r = self._request("HEAD", f"/{bucket}/{key}")
        except RemoteStorageError:
            return None
        return RemoteObject(
            key=key,
            size=int(r.headers.get("Content-Length", "0")),
            etag=r.headers.get("ETag", "").strip('"'),
        )

    def ensure_bucket(self, bucket: str) -> None:
        self._request("PUT", f"/{bucket}", ok=(200, 201, 409))

    def list_buckets(self) -> list[str]:
        """GET / (ListAllMyBuckets) -> bucket names."""
        r = self._request("GET", "/")
        root = ET.fromstring(r.content)
        ns = root.tag[: root.tag.index("}") + 1] if root.tag.startswith("{") else ""
        return [
            e.text or ""
            for e in root.findall(f".//{ns}Bucket/{ns}Name")
            if e.text
        ]
