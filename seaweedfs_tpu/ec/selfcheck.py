"""Shared EC self-check: production encoder on a device mesh vs CPU.

Used by BOTH the driver's `dryrun_multichip` and the test suite, so the
two stay one implementation: fabricate a small volume, encode it with
the multi-device JaxBackend through the REAL ec_encode_volume pipeline,
re-encode with the CPU backend, and require bit-identical .ecsum
sidecars and shard bytes.
"""

from __future__ import annotations

import os

import numpy as np

from ..storage.needle import Needle
from ..storage.volume import Volume
from .backend import CpuBackend, JaxBackend
from .bitrot import BitrotProtection
from .context import DEFAULT_EC_CONTEXT
from .encoder import ec_encode_volume


def mesh_encode_selfcheck(
    tmp_dir: str,
    n_devices: int,
    batch_size: int = 96 * 1024 + 13,  # odd: exercises column padding
    payload_size: int = 217_013,
    needles: int = 5,
    seed: int = 0,
) -> None:
    """Raises on any mismatch; returns None when bit-exact."""
    rng = np.random.default_rng(seed)
    vol = Volume(tmp_dir, 1, needle_map_kind="memory")
    for nid in range(1, needles + 1):
        data = rng.integers(0, 256, size=payload_size, dtype=np.uint8).tobytes()
        vol.write_needle(Needle(cookie=9, needle_id=nid, data=data))
    vol.flush()
    base = vol.base_file_name(tmp_dir, "", 1)
    vol.close()

    jb = JaxBackend(DEFAULT_EC_CONTEXT, impl="xla", n_devices=n_devices)
    if jb._mesh_rs is None or jb._mesh_rs.n_devices != n_devices:
        raise AssertionError("mesh path did not engage")
    # Pin placement to "mesh": this check exists to prove the COLUMN-
    # SLICED multi-chip path is bit-exact; the auto placement policy
    # would route this small lone encode onto a single chip.
    from .device_queue import QueueScope

    ec_encode_volume(
        base, backend=jb, batch_size=batch_size,
        scheduler=QueueScope(placement="mesh"),
    )
    mesh_prot = BitrotProtection.load(base + ".ecsum")
    shard_bytes = {}
    for i in range(DEFAULT_EC_CONTEXT.total):
        p = base + DEFAULT_EC_CONTEXT.to_ext(i)
        with open(p, "rb") as f:
            shard_bytes[i] = f.read()
        os.unlink(p)
    os.unlink(base + ".ecsum")

    ec_encode_volume(base, backend=CpuBackend(DEFAULT_EC_CONTEXT))
    cpu_prot = BitrotProtection.load(base + ".ecsum")
    if mesh_prot.shard_crcs != cpu_prot.shard_crcs:
        raise AssertionError("mesh .ecsum CRCs differ from CPU")
    if mesh_prot.shard_sizes != cpu_prot.shard_sizes:
        raise AssertionError("mesh shard sizes differ from CPU")
    for i in range(DEFAULT_EC_CONTEXT.total):
        with open(base + DEFAULT_EC_CONTEXT.to_ext(i), "rb") as f:
            if shard_bytes[i] != f.read():
                raise AssertionError(f"shard {i} bytes differ from CPU")
