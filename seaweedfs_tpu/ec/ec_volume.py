"""EcVolume: serve reads from mounted EC shards, with on-the-fly
Reed-Solomon recovery of intervals whose shard is absent.

Reference: weed/storage/erasure_coding/ec_volume.go (sealed .ecx binary
search :501, .ecj-backed deletion set :425-455) and store_ec.go
ReadEcShardNeedle/:656-747 (recover-by-reconstruction read path). Remote
shard fetch arrives with the cluster layer; here recovery uses whatever
shards are on local disk.
"""

from __future__ import annotations

import json
import os
import struct
import threading
from typing import Optional

import numpy as np

from .. import faults
from ..storage.needle import CrcError, Needle
from ..storage.needle_map import SortedFileNeedleMap
from ..storage.types import actual_offset
from ..utils import trace
from ..utils.chunk_cache import ChunkCache
from ..utils.glog import logger
from ..ops import gf256
from .backend import RSBackend, _decode_coeffs, get_backend
from .bitrot import BitrotError, BitrotProtection
from .context import DEFAULT_EC_CONTEXT, QUARANTINE_SUFFIX, ECContext, ECError
from .decoder import record_actual_size
from .locate import locate_data
from .pipeline import run_staged_apply
from .volume_info import VolumeInfo

log = logger("ec.volume")

# Column-batch width for staged on-the-fly reconstruction: extents at
# least two batches wide go through the backend's staged apply
# (H2D/compute/D2H overlapped per batch); smaller extents take the
# single-shot reconstruct — the latency-sensitive needle-read shape,
# where pipeline thread spawn would cost more than it hides.
STAGED_RECOVERY_BATCH = 4 << 20

# Default byte budget for the reconstructed-interval cache: hot needles
# on a lost shard pay Reed-Solomon + sidecar verification once, not per
# read. Small on purpose — it only ever holds VERIFIED reconstruction
# output for degraded extents. Entries are generation-keyed per shard:
# a shard remount/unmount drops only that shard's extents; content
# changes (tombstones) still drop wholesale.
DEFAULT_INTERVAL_CACHE_BYTES = 16 << 20


class EcNotFoundError(ECError):
    pass


class EcCookieMismatch(ECError):
    pass


class EcVolume:
    def __init__(
        self,
        directory: str,
        volume_id: int,
        collection: str = "",
        backend_name: str = "auto",
        remote_reader=None,
        interval_cache_bytes: int = DEFAULT_INTERVAL_CACHE_BYTES,
        interval_cache: ChunkCache | None = None,
        scheduler=None,
    ):
        """remote_reader(shard_id, offset, size, generation) -> bytes|None
        lets the cluster layer serve shards held by peer servers
        (reference store_ec.go:599 streaming VolumeEcShardRead; the
        generation is the EncodeTsNs fence so a stale peer never answers);
        recovery by local reconstruction remains the fallback.

        `interval_cache_bytes` bounds the LRU of verified reconstructed
        extents (0 disables): repeated reads of needles on a missing
        shard reuse one reconstruction instead of re-running RS + CRC
        per read. Entries are keyed by (shard generation, shard id):
        remount/rebuild/unmount of a shard invalidates only that
        shard's extents; deletes invalidate wholesale.

        `interval_cache` (Store wiring) hands in a SHARED ChunkCache:
        one byte budget across every EC volume on the server, so a
        degraded hot volume can use the whole allowance instead of
        being boxed into a per-volume slice while cold volumes' slices
        sit empty. Keys are volume-namespaced; invalidation and close()
        drop only this volume's extents.

        `scheduler` (Store wiring) is the QueueScope whose placement/
        admission config wide degraded reconstructions run under (None
        = the process-wide default scope)."""
        from ..storage.volume import Volume

        self.volume_id = volume_id
        self.collection = collection
        self.base = Volume.base_file_name(directory, collection, volume_id)
        self._lock = threading.RLock()

        vi = VolumeInfo.maybe_load(self.base + ".vif") or VolumeInfo()
        self.version = vi.version
        self.ctx: ECContext = vi.ec_ctx or DEFAULT_EC_CONTEXT
        self.encode_ts_ns = vi.encode_ts_ns  # generation fence

        self._ecx = SortedFileNeedleMap(self.base + ".ecx")
        self._deleted: set[int] = set()
        self._ecj = open(self.base + ".ecj", "ab+")
        self._ecj.seek(0)
        while True:
            b = self._ecj.read(8)
            if len(b) < 8:
                break
            self._deleted.add(struct.unpack(">Q", b)[0])

        # Crash recovery BEFORE serving: a pending <shard>.repair
        # journal means a leaf repair was interrupted mid-protocol —
        # replay (or roll back) it now so no fd ever opens over a
        # half-applied patch (ec/repair_journal.py window table).
        try:
            from .repair_journal import recover_volume_journals

            recover_volume_journals(self.base, self.ctx)
        except Exception as e:  # recovery must never block a mount
            log.error("repair-journal recovery for %s failed: %s", self.base, e)

        self.shard_fds: dict[int, int] = {}
        self._shard_size = 0
        for i in range(self.ctx.total):
            p = self.base + self.ctx.to_ext(i)
            if os.path.exists(p):
                self.shard_fds[i] = os.open(p, os.O_RDONLY)
                self._shard_size = os.path.getsize(p)

        # Authoritative layout from the encode-time .dat size; fallback
        # for .vif-less volumes mirrors the reference's shard-size-1
        # disambiguation (ec_volume.go LocateEcShardNeedleInterval).
        if vi.dat_file_size > 0:
            self._locate_shard_size = vi.dat_file_size // self.ctx.data_shards
        else:
            self._locate_shard_size = max(self._shard_size - 1, 0)

        self.backend: RSBackend = get_backend(
            backend_name, self.ctx.data_shards, self.ctx.parity_shards
        )
        self.remote_reader = remote_reader
        self.scheduler = scheduler
        # Bitrot sidecar, loaded lazily for degraded-read verification.
        # False = not loaded yet (absence is re-probed per degraded
        # read; only a successful load is cached).
        self._prot: BitrotProtection | bool = False
        self._prot_warned = False
        # Verified-reconstruction LRU (degraded-read hot path); None =
        # disabled. Keys are VOLUME-NAMESPACED, GENERATION-QUALIFIED
        # shard-aligned extents ("<ns><sid>:<gen>:<lo>:<hi>"), values
        # are bytes that already passed sidecar verification. Each shard
        # id carries its own generation counter, bumped on remount/
        # unmount of THAT shard — an unrelated shard event no longer
        # drops the whole cache, and an in-flight reconstruction racing
        # an invalidation parks its result under the stale generation
        # where no new read looks. The namespace (collection_vid, like
        # the base file name) lets a Store-level shared cache hold many
        # volumes under one byte budget.
        self._cache_ns = (
            f"{collection}_{volume_id}:" if collection else f"{volume_id}:"
        )
        self._shared_cache = interval_cache is not None
        if interval_cache is not None:
            self.interval_cache: ChunkCache | None = interval_cache
        else:
            self.interval_cache = (
                ChunkCache(interval_cache_bytes, tier="ec_interval")
                if interval_cache_bytes > 0
                else None
            )
        self._shard_gen: dict[int, int] = {}
        # Decode-coefficient rows are tiny but their GF inversion isn't
        # free on a hot read path; memoize per (target, source-set).
        self._coeff_cache: dict[tuple, np.ndarray] = {}
        # Observability: total bytes pread/fetched to serve reads
        # (sibling reads during recovery dominate under degraded
        # serving — the bench derives read amplification from this).
        self.bytes_read = 0
        # Bytes of shard content produced by RS reconstruction (the
        # degraded-read work). Rides the heartbeat telemetry blob as
        # per-volume HEAT: the rebalance scanner (ec/rebalance.py)
        # weighs reconstruction double when ranking hot volumes —
        # moving a reconstructing volume toward chips is exactly what
        # data gravity exists for.
        self.bytes_reconstructed = 0
        # Heat counters survive a clean restart: without the sidecar a
        # restart resets them to zero, the master's per-sweep delta
        # logic sees a counter regression, and the first post-restart
        # window is clamped to zero (worker/control.py) — a whole
        # gravity sweep of real heat lost per restart. The sidecar is
        # generation-fenced on encode_ts_ns so counters from a volume
        # that was re-encoded (same id, new data) are never resurrected.
        self._heat_path = self.base + ".heat"
        try:
            with open(self._heat_path, encoding="utf-8") as f:
                blob = json.load(f)
            if blob.get("gen") == self.encode_ts_ns:
                self.bytes_read = int(blob.get("read_bytes", 0))
                self.bytes_reconstructed = int(
                    blob.get("reconstructed_bytes", 0)
                )
        except (OSError, ValueError):  # absent/corrupt: start cold
            pass

    # ------------------------------------------------------------- lookup

    def find_needle(self, needle_id: int):
        nv = self._ecx.get(needle_id)
        if nv is None:
            return None
        if needle_id in self._deleted:
            return None
        return nv

    def has_needle(self, needle_id: int) -> bool:
        nv = self.find_needle(needle_id)
        return nv is not None and not nv.is_deleted

    # --------------------------------------------------------------- read

    def read_needle(self, needle_id: int, cookie: Optional[int] = None) -> Needle:
        with self._lock:
            nv = self.find_needle(needle_id)
        if nv is None or nv.is_deleted:
            raise EcNotFoundError(f"needle {needle_id:x} not found")
        # Interval reads run OUTSIDE the volume lock: os.pread is
        # thread-safe and a slow remote shard fetch must not serialize
        # every other read of this volume.
        off = actual_offset(nv.offset)
        rec_size = record_actual_size(nv.size, self.version)
        try:
            return self._parse(self._read_extent(off, rec_size), cookie, needle_id)
        except CrcError:
            # Local bytes are rotten (bitrot / torn shard). Self-heal on
            # read: re-derive every interval by sidecar-verified
            # reconstruction, bypassing the local shard copies. Either
            # the record comes back bit-exact or this raises — a corrupt
            # needle is never served.
            log.warning(
                "needle %x failed CRC from local shards; retrying via "
                "verified reconstruction", needle_id,
            )
            return self._parse(
                self._read_extent(off, rec_size, prefer_recovery=True),
                cookie, needle_id,
            )

    def _parse(self, raw: bytes, cookie: Optional[int], needle_id: int) -> Needle:
        n = Needle.from_bytes(raw, self.version)
        if cookie is not None and n.cookie != cookie:
            raise EcCookieMismatch(f"needle {needle_id:x} cookie mismatch")
        return n

    def _read_extent(
        self, offset: int, size: int, prefer_recovery: bool = False
    ) -> bytes:
        parts = []
        for iv in locate_data(
            offset, size, self._locate_shard_size, self.ctx.data_shards
        ):
            shard_id, shard_off = iv.to_shard_and_offset(self.ctx.data_shards)
            if prefer_recovery:
                parts.append(self._recover_interval(shard_id, shard_off, iv.size))
            else:
                parts.append(self._read_shard_interval(shard_id, shard_off, iv.size))
        return b"".join(parts)

    def _read_shard_interval(self, shard_id: int, offset: int, size: int) -> bytes:
        fd = self.shard_fds.get(shard_id)
        if fd is not None:
            try:
                faults.fire(
                    "ec.volume.shard_read",
                    shard=shard_id, offset=offset, size=size,
                )
                got = os.pread(fd, size, offset)
            except OSError:  # racing unmount closed the fd (or injected)
                got = b""
            got = faults.mutate(
                "ec.volume.shard_read", got,
                shard=shard_id, offset=offset, size=size,
            )
            if len(got) == size:
                self.bytes_read += size
                return got
            # short read = truncated shard; fall through to recovery
        if self.remote_reader is not None:
            got = self.remote_reader(shard_id, offset, size, self.encode_ts_ns)
            if got is not None and len(got) == size:
                self.bytes_read += size
                return got
        return self._recover_interval(shard_id, offset, size)

    # ---------------------------------------------------------- recovery

    def _bitrot(self) -> Optional[BitrotProtection]:
        """Lazy-load the .ecsum sidecar for reconstruction verification.
        Absent or unreadable -> None for THIS read only: a successful
        load is cached, but absence is re-probed every time — a sidecar
        that lands late (crash window between shard publish and sidecar
        write, shards copied before the sidecar) must re-arm
        verification, not be disabled for the life of the mount."""
        if self._prot is False:
            try:
                self._prot = BitrotProtection.load(self.base + ".ecsum")
            except (FileNotFoundError, BitrotError, OSError) as e:
                if not self._prot_warned:
                    self._prot_warned = True
                    log.warning(
                        "%s.ecsum unavailable (%s); degraded reads are "
                        "UNVERIFIED until it appears", self.base, e,
                    )
                return None
        return self._prot

    def _recover_interval(self, shard_id: int, offset: int, size: int) -> bytes:
        """Reconstruct [offset, offset+size) of one shard and — when the
        .ecsum sidecar is available — verify the containing bitrot
        granules before returning a byte (the reconstruction itself ran
        over unverified sibling reads, so its output cannot be trusted
        unchecked). Fail-closed: a mismatch raises rather than serving.

        Granularity follows the sidecar: a v2 sidecar's 64 KiB leaves
        mean a needle read reconstructs and verifies only the leaves
        covering its extent, instead of whole 16 MiB blocks (up to 256x
        less sibling I/O per verified degraded read). Verified output
        lands in the interval cache so a hot needle on a lost shard
        pays reconstruction once.
        """
        # Flight-recorder root per degraded-read op (a child when a
        # server RPC/scrub span is active in this thread).
        sp = trace.start(
            "ec.degraded_read",
            name=f"v{self.volume_id}.{shard_id:02d}",
            volume=self.volume_id, shard=shard_id,
            offset=offset, size=size,
        )
        try:
            with trace.activate(sp):
                return self._recover_interval_traced(
                    shard_id, offset, size, sp
                )
        finally:
            trace.finish(sp)

    def _recover_interval_traced(
        self, shard_id: int, offset: int, size: int, sp
    ) -> bytes:
        prot = self._bitrot()
        if prot is None or not (0 <= shard_id < len(prot.shard_crcs)):
            return self._reconstruct_range(shard_id, offset, size)
        # Finest level the sidecar records; identical granularity across
        # shards (equal sizes, one layout), so one granule size serves
        # both the sibling pre-checks and the output check.
        bs, _ = prot.verify_granularity(shard_id)
        ssize = prot.shard_sizes[shard_id]
        if offset + size > ssize:
            # extent beyond the sidecar's recorded shard: no ground
            # truth for the tail — serve unverified rather than refuse
            # (matches pre-sidecar volumes)
            return self._reconstruct_range(shard_id, offset, size)
        lo = (offset // bs) * bs
        hi = min(-(-(offset + size) // bs) * bs, ssize)

        cache = self.interval_cache
        key = (
            f"{self._cache_ns}{shard_id}:"
            f"{self._shard_gen.get(shard_id, 0)}:{lo}:{hi}"
        )

        def range_ok(sid: int, data: bytes) -> bool:
            """Verify a shard's [lo, hi) bytes against its own granule
            CRCs (granules align across shards: equal sizes, one
            layout)."""
            with trace.stage(sp, "crc_verify"):
                return prot.verify_range(sid, lo, data)

        def build() -> bytes:
            # Sources are sidecar-verified BEFORE being fed to
            # Reed-Solomon: a silently-rotten sibling is excluded
            # instead of poisoning the reconstruction (which would
            # force a refusal even though k clean shards exist).
            data = self._reconstruct_range(
                shard_id, lo, hi - lo, source_ok=range_ok
            )
            if not range_ok(shard_id, data):
                raise ECError(
                    f"reconstructed shard {shard_id} [{lo}:{hi}) fails "
                    f".ecsum verification; refusing to serve"
                )
            return data

        if cache is None:
            return build()[offset - lo : offset - lo + size]
        # Read-through with singleflight collapse: N concurrent misses
        # on one degraded extent run build() ONCE — everyone gets the
        # leader's verified bytes (the leader's refusal propagates to
        # every waiter too; nobody retries a reconstruction that just
        # failed verification). Only VERIFIED output is ever cached, so
        # a hit is as trustworthy as the read that populated it.
        # Invalidation is race-free both ways it happens: remount/
        # rebuild bump the shard GENERATION (a stale in-flight build
        # parks its bytes under the old key where no new reader looks),
        # and a leaf patch's ranged drop_matching FENCES matching
        # in-flight builds (returned to their callers, never admitted).
        data, src = cache.get_or_load(key, build)
        if src == "hit":
            trace.event(sp, "cache_hit", lo=lo, hi=hi)
        elif src == "wait":
            trace.event(sp, "singleflight_wait", lo=lo, hi=hi)
        return data[offset - lo : offset - lo + size]

    def _reconstruct_range(
        self, shard_id: int, offset: int, size: int, source_ok=None
    ) -> bytes:
        """On-the-fly RS decode of one interval from >=k sibling shards
        (reference store_ec.go:656-747; like the reference, sibling
        reads fan out in parallel — remote fetches dominate latency)."""
        k = self.ctx.data_shards
        sp = trace.current()  # the ec.degraded_read root, when armed
        sources: dict[int, np.ndarray] = {}
        # Local sibling reads ride the native zero-copy plane when it's
        # up (and no fault registry is armed — the chaos seams want
        # bytes): each sibling's extent lands in a numpy buffer via one
        # positioned native read instead of an os.pread bytes copy. The
        # downstream stack/verify path takes either representation.
        from . import native_io

        use_native = native_io.enabled() and not faults.active()
        local = [(i, fd) for i, fd in self.shard_fds.items() if i != shard_id]
        for i, fd in local:
            try:
                with trace.stage(sp, "sibling_read"):
                    if use_native:
                        arr = np.empty(size, dtype=np.uint8)
                        native_io.read_exact_into(fd, arr, offset)
                        got = arr
                    else:
                        got = os.pread(fd, size, offset)
            except OSError:
                continue
            self.bytes_read += len(got)
            if len(got) == size and (
                source_ok is None or source_ok(i, got)
            ):
                sources[i] = (
                    got if use_native else np.frombuffer(got, dtype=np.uint8)
                )
                if len(sources) == k:
                    break
        if len(sources) < k and self.remote_reader is not None:
            import contextvars
            from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait

            missing = [
                i
                for i in range(self.ctx.total)
                if i != shard_id and i not in sources
            ]

            def fetch(i):
                return i, self.remote_reader(i, offset, size, self.encode_ts_ns)

            def submit(ex, i):
                # Per-task contextvar copy: the fetch thread sees the
                # caller's request id + active span, so the peer
                # shard-read RPC hop carries both in its metadata.
                return ex.submit(contextvars.copy_context().run, fetch, i)

            # stop as soon as k sources exist: one hung peer must not
            # stall the read for the full RPC timeout
            ex = ThreadPoolExecutor(max_workers=min(len(missing), 8))
            try:
                # "sibling_read" covers only the blocked wait on peer
                # fetches; the source_ok callbacks below run range_ok,
                # which tags its own time "crc_verify" — wrapping them
                # here too would double-count verify seconds into the
                # wire stage.
                with trace.stage(sp, "sibling_read"):
                    futures = {submit(ex, i) for i in missing}
                while futures and len(sources) < k:
                    with trace.stage(sp, "sibling_read"):
                        done, futures = wait(
                            futures, return_when=FIRST_COMPLETED
                        )
                    for f in done:
                        i, got = f.result()
                        if got is not None:
                            self.bytes_read += len(got)
                        if (
                            got is not None
                            and len(got) == size
                            and (source_ok is None or source_ok(i, got))
                        ):
                            sources[i] = np.frombuffer(got, dtype=np.uint8)
            finally:
                ex.shutdown(wait=False, cancel_futures=True)
        if len(sources) < k:
            raise ECError(
                f"shard {shard_id} unavailable and only {len(sources)} "
                f"sibling shards readable (need {k})"
            )
        if len(sources) > k:
            sources = {i: sources[i] for i in sorted(sources)[:k]}
        if size >= 2 * STAGED_RECOVERY_BATCH:
            # Wide extent (multi-leaf verified reconstruction, v1 16 MiB
            # blocks, scrub-driven repair reads): batch the GF(256)
            # apply through the backend's staged hooks so H2D upload,
            # device compute, and D2H drain overlap across column
            # batches — the same shape rebuild uses, one code path
            # (ec/pipeline.py run_staged_apply).
            src_ids = tuple(sorted(sources))
            coeffs = self._coeff_cache.get((shard_id, src_ids))
            if coeffs is None:
                # the backend already built this matrix (Protocol doesn't
                # promise the attribute, so fall back to constructing)
                matrix = getattr(self.backend, "matrix", None)
                if matrix is None:
                    matrix = gf256.ReedSolomon(k, self.ctx.parity_shards).matrix
                coeffs = _decode_coeffs(matrix, k, (shard_id,), src_ids)
                if len(self._coeff_cache) >= 64:  # flapping remote sources
                    self._coeff_cache.clear()
                self._coeff_cache[(shard_id, src_ids)] = coeffs
            # Stacked PER BATCH, not whole-extent: a (k, size) upfront
            # stack would transiently double the sibling-byte footprint
            # for exactly the wide extents this path targets; one
            # (k, batch) copy at a time is the to_device copy anyway.
            srcs = [sources[i] for i in src_ids]
            out = np.empty(size, dtype=np.uint8)

            def produce():
                for off in range(0, size, STAGED_RECOVERY_BATCH):
                    yield off, np.stack(
                        [s[off : off + STAGED_RECOVERY_BATCH] for s in srcs]
                    )

            def consume(off, rec):
                out[off : off + rec.shape[1]] = rec[0]

            run_staged_apply(
                self.backend, coeffs, produce, consume,
                describe="ec degraded reconstruction",
                # Degraded reads ARE serving traffic: they preempt any
                # colocated recovery/scrub stream at batch granularity
                # on the shared device queue. On a multi-chip backend
                # the stream lands whole on the least-loaded chip; a
                # 1-row reconstruction's admission cost is ~1/m of a
                # parity encode at equal width (cost model).
                priority="foreground",
                scheduler=self.scheduler,
                cost_hint=size,
                span=sp,
                read_stage="stage_batch",
                write_stage="write_sink",
            )
            self.bytes_reconstructed += size
            return out.tobytes()
        # Single-shot path (the latency-sensitive needle-read shape):
        # still a CLIENT of the shared per-chip scheduler — serving
        # traffic takes a FOREGROUND window slot with a cost hint, so a
        # gateway read preempts colocated recovery/scrub admission
        # instead of racing it unscheduled (ISSUE 11). The wait lands on
        # the span as "admission_wait", like the staged path's.
        from .device_queue import batch_cost, resolve_scope

        queue = resolve_scope(self.scheduler).for_backend(self.backend)
        if queue is not None:
            with queue.admission(
                "foreground", batch_cost(1, size), span=sp
            ):
                with trace.stage(sp, "reconstruct"):
                    rec = self.backend.reconstruct(sources, want=[shard_id])
        else:
            with trace.stage(sp, "reconstruct"):
                rec = self.backend.reconstruct(sources, want=[shard_id])
        self.bytes_reconstructed += size
        return np.asarray(rec[shard_id], dtype=np.uint8).tobytes()

    # ------------------------------------------------------------- delete

    def delete_needle(self, needle_id: int) -> int:
        """Journal an EC tombstone (reference ec_volume_delete.go)."""
        with self._lock:
            nv = self._ecx.get(needle_id)
            if nv is None or nv.is_deleted or needle_id in self._deleted:
                return 0
            self._ecj.write(struct.pack(">Q", needle_id))
            self._ecj.flush()
            os.fsync(self._ecj.fileno())
            self._deleted.add(needle_id)
            self._drop_interval_cache()  # cached extents may cover it
            return nv.size

    # -------------------------------------------------------------- state

    def _drop_interval_cache(self, shard_ids: list[int] | None = None) -> None:
        """Invalidate cached reconstructed extents. With `shard_ids`,
        only THOSE shards' entries drop (and their generation counters
        bump, so an in-flight reconstruction cannot repopulate under the
        old key): a remount of one shard no longer costs every other
        shard's cached reconstructions. None = wholesale for THIS volume
        (content changes — a tombstone may land inside any cached
        extent); a shared Store-level cache keeps other volumes'
        extents either way."""
        if shard_ids is None:
            for sid in range(self.ctx.total):
                self._shard_gen[sid] = self._shard_gen.get(sid, 0) + 1
            if self.interval_cache is not None:
                self.interval_cache.drop_prefix(self._cache_ns)
            return
        for sid in shard_ids:
            self._shard_gen[sid] = self._shard_gen.get(sid, 0) + 1
            if self.interval_cache is not None:
                self.interval_cache.drop_prefix(f"{self._cache_ns}{sid}:")

    def invalidate_shard_ranges(
        self, shard_id: int, ranges: list[tuple[int, int]]
    ) -> None:
        """Drop cached reconstructed extents overlapping the given byte
        ranges of one shard (a leaf repair just patched those bytes in
        place — same inode, so no fd swap, but any cached extent built
        over the old bytes is stale). Finer than a whole-shard
        generation bump: the shard's other cached extents stay hot."""
        if self.interval_cache is None or not ranges:
            return
        prefix = (
            f"{self._cache_ns}{shard_id}:{self._shard_gen.get(shard_id, 0)}:"
        )

        def overlaps(key: str) -> bool:
            try:
                lo, hi = key[len(prefix):].split(":")
                lo, hi = int(lo), int(hi)
            except ValueError:
                return True  # unparseable = assume stale
            return any(lo < rhi and rlo < hi for rlo, rhi in ranges)

        with self._lock:
            self.interval_cache.drop_matching(prefix, overlaps)

    @property
    def shard_ids(self) -> list[int]:
        return sorted(self.shard_fds)

    def quarantined_shards(self) -> list[int]:
        """Shards whose scrub-quarantine file (<shard>.bad) is on disk."""
        return [
            i
            for i in range(self.ctx.total)
            if os.path.exists(self.base + self.ctx.to_ext(i) + QUARANTINE_SUFFIX)
        ]

    def legitimate_shards(self) -> list[int]:
        """Shards this server legitimately owns: currently served PLUS
        quarantined ones (a shard pulled from service for corruption is
        still this server's to repair — it must not drop off the repair
        list just because it was unmounted)."""
        with self._lock:
            held = set(self.shard_fds)
        return sorted(held | set(self.quarantined_shards()))

    def shard_size(self) -> int:
        return self._shard_size

    def refresh_shards(self) -> list[int]:
        """Pick up shard files that appeared on disk since mount (e.g.
        just copied from a peer); returns the current shard ids."""
        with self._lock:
            return self.reopen_shards(
                [i for i in range(self.ctx.total) if i not in self.shard_fds]
            )

    def reopen_shards(self, shard_ids: Optional[list[int]] = None) -> list[int]:
        """Re-open shard fds from the current directory entries. After a
        rebuild atomically replaces a shard file, an fd opened before
        the rename still reads the OLD inode (the quarantined bytes);
        serving must swap to the regenerated file. Returns mounted ids."""
        with self._lock:
            ids = list(self.shard_fds) if shard_ids is None else shard_ids
            self._drop_interval_cache(ids)
            for sid in ids:
                p = self.base + self.ctx.to_ext(sid)
                old = self.shard_fds.pop(sid, None)
                if old is not None:
                    os.close(old)
                if os.path.exists(p):
                    self.shard_fds[sid] = os.open(p, os.O_RDONLY)
                    self._shard_size = max(self._shard_size, os.path.getsize(p))
            return sorted(self.shard_fds)

    def unmount_shards(self, shard_ids: list[int]) -> int:
        """Stop serving specific local shards (reference Unmount per
        shard set); returns how many shards remain mounted."""
        with self._lock:
            self._drop_interval_cache(shard_ids)
            for sid in shard_ids:
                fd = self.shard_fds.pop(sid, None)
                if fd is not None:
                    os.close(fd)
            return len(self.shard_fds)

    def _save_heat(self) -> None:
        """Persist the heat counters beside the volume (atomic tmp +
        rename, best-effort): a clean unmount/restart then resumes the
        monotonic counter stream instead of resetting to zero and
        blanking the master's first post-restart gravity window."""
        try:
            tmp = self._heat_path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(
                    {
                        "gen": self.encode_ts_ns,
                        "read_bytes": int(self.bytes_read),
                        "reconstructed_bytes": int(self.bytes_reconstructed),
                    },
                    f,
                )
            os.replace(tmp, self._heat_path)
        except OSError:  # advisory; never fail a close over heat
            pass

    def close(self) -> None:
        with self._lock:
            self._save_heat()
            for fd in self.shard_fds.values():
                os.close(fd)
            self.shard_fds.clear()
            self._ecj.close()
            self._ecx.close()
            if self._shared_cache and self.interval_cache is not None:
                # an unmounted volume must not keep squatting on the
                # store-wide reconstruction budget
                self.interval_cache.drop_prefix(self._cache_ns)
