"""Map a byte interval of the original .dat onto EC shard intervals.

The .dat is striped row-major over the k data shards: first in rows of
k x 1GB "large blocks", then the remainder in rows of k x 1MB "small
blocks". Shard file i is the column: its large blocks, then its small
blocks (reference weed/storage/erasure_coding/ec_locate.go:16-98).

Unlike the reference (which hardcodes DataShardsCount in the row math),
everything here is parametrized by the context's data-shard count.
"""

from __future__ import annotations

from dataclasses import dataclass

from .context import LARGE_BLOCK_SIZE, SMALL_BLOCK_SIZE


@dataclass(frozen=True)
class Interval:
    """One contiguous run inside a single (large or small) block."""

    block_index: int  # index within the large-block area OR the small-block area
    inner_offset: int
    size: int
    is_large_block: bool
    large_block_rows: int  # number of large-block rows in the volume

    def to_shard_and_offset(
        self,
        data_shards: int,
        large_block_size: int = LARGE_BLOCK_SIZE,
        small_block_size: int = SMALL_BLOCK_SIZE,
    ) -> tuple[int, int]:
        """-> (shard_id, byte offset inside that shard's file)."""
        row = self.block_index // data_shards
        off = self.inner_offset
        if self.is_large_block:
            off += row * large_block_size
        else:
            off += self.large_block_rows * large_block_size + row * small_block_size
        return self.block_index % data_shards, off


def locate_data(
    offset: int,
    size: int,
    shard_size: int,
    data_shards: int,
    large_block_size: int = LARGE_BLOCK_SIZE,
    small_block_size: int = SMALL_BLOCK_SIZE,
) -> list[Interval]:
    """Intervals covering dat[offset : offset+size].

    `shard_size` decides where large blocks end: the authoritative value
    is dat_file_size // data_shards (reference ec_volume.go
    LocateEcShardNeedleInterval uses the .vif datFileSize).
    """
    large_rows = shard_size // large_block_size
    large_area = large_rows * large_block_size * data_shards

    if offset < large_area:
        is_large = True
        block_index, inner = divmod(offset, large_block_size)
    else:
        is_large = False
        block_index, inner = divmod(offset - large_area, small_block_size)

    intervals: list[Interval] = []
    while size > 0:
        block_len = large_block_size if is_large else small_block_size
        remaining = block_len - inner
        if remaining <= 0:
            block_index, is_large = _next_block(
                block_index, is_large, large_rows, data_shards
            )
            inner = 0
            continue
        take = min(size, remaining)
        intervals.append(Interval(block_index, inner, take, is_large, large_rows))
        size -= take
        block_index, is_large = _next_block(
            block_index, is_large, large_rows, data_shards
        )
        inner = 0
    return intervals


def _next_block(
    block_index: int, is_large: bool, large_rows: int, data_shards: int
) -> tuple[int, bool]:
    nxt = block_index + 1
    if is_large and nxt == large_rows * data_shards:
        return 0, False
    return nxt, is_large
