"""Rack-aware EC shard placement planning.

Reference: weed/shell/command_ec_common.go:60-120 (the EcBalance
algorithm description) and weed/storage/erasure_coding/ecbalancer/ —
per collection: deduplicate shard copies, spread each volume's shards
across racks (bounded by the per-rack average), then even them across
servers within each rack, and finally flatten total per-server counts
inside every rack.

Pure planning: callers snapshot the cluster into NodeViews, get back an
ordered list of Move/Drop operations, and execute them with their own
RPC machinery (the shell's ec.balance does copy+mount / unmount+delete
per move). Keeping the planner pure makes it testable against synthetic
topologies the way the reference tests shell commands against fixture
topology dumps.
"""

from __future__ import annotations

import os
import time
from collections import defaultdict
from dataclasses import dataclass, field

# Load normalizer for the scalar gravity score: one typical encode
# batch's cost units (4 parity rows x 16 MiB = batch_cost(4, 16 MiB)),
# so `ec_load / GRAVITY_LOAD_NORM` reads as "batches outstanding".
GRAVITY_LOAD_NORM = float(4 << 24)


def telemetry_stale_after() -> float:
    """SEAWEED_EC_TELEMETRY_STALE_S: heartbeat telemetry older than
    this stops steering placement/gravity (default 30 s ~ 15 missed
    2 s heartbeats). A dead node's last-reported idle chips must not
    keep attracting bytes."""
    try:
        return float(os.environ.get("SEAWEED_EC_TELEMETRY_STALE_S", "30"))
    except ValueError:
        return 30.0


@dataclass
class NodeView:
    """One volume server as the planner sees it."""

    id: str
    rack: str = ""
    data_center: str = ""
    free_slots: int = 100
    # Disk headroom in bytes; -1 = unknown (callers without byte-level
    # topology keep slot-only planning). Known headroom both GATES a
    # destination (a shard must physically fit) and breaks scoring ties
    # toward the roomiest node, so sustained holder loss doesn't pile
    # regenerated shards onto a nearly-full survivor.
    free_bytes: int = -1
    # LIVE compute-load signal, heartbeat-learned (DataNode.ec_telemetry
    # -> per-chip DeviceQueue.load() cost units summed across the
    # node's chips). -1 = unknown (no telemetry: scored as idle, the
    # pre-live behavior). Used as a scoring tiebreak so a long-lived EC
    # stream lands on the host with compute headroom, not just disk
    # headroom.
    ec_load: float = -1.0
    # open fallback breakers on the node (its chips are failing over to
    # CPU): such a node loses any remotely close placement call.
    ec_breakers_open: int = 0
    # per-op/stage EWMA seconds from the node's flight recorder —
    # recorded for span-event evidence; device-stage pressure breaks
    # final ties.
    ec_stage_ewma_s: float = -1.0
    # heartbeat-learned chip count (len of the telemetry chips map):
    # the hardware half of the gravity score. 0 = unknown/none —
    # non-reporting nodes neither attract nor repel on chips alone.
    ec_chips: int = 0
    # seconds since the master last absorbed this node's telemetry
    # blob; -1 = never reported. Signals past `telemetry_stale_after()`
    # are aged out in node_view_for, but the age itself survives for
    # status surfaces.
    telemetry_age_s: float = -1.0
    # vid -> set of shard ids held
    shards: dict[int, set[int]] = field(default_factory=dict)

    def shard_count(self) -> int:
        return sum(len(s) for s in self.shards.values())

    def rack_key(self) -> tuple[str, str]:
        return (self.data_center, self.rack)

    def gravity_score(self) -> float:
        """Scalar data-gravity attractiveness — chips discounted by
        live load, open breakers, and device-stage pressure. Higher =
        more compute headroom where the bytes would land; 0 for a
        non-reporting (or chip-less, or stale-telemetry) node. Used by
        the hot-volume rebalance planner (ec/rebalance.py) to rank
        holder chip-deficit, and by the status surfaces; destination
        SCORING inside `_pick_dest_node` uses the equivalent tuple so
        ordering stays exact, not float-rounded."""
        if self.ec_chips <= 0:
            return 0.0
        score = self.ec_chips / (
            1.0 + max(self.ec_load, 0.0) / GRAVITY_LOAD_NORM
        )
        if self.ec_breakers_open > 0:
            score /= 1.0 + self.ec_breakers_open
        if self.ec_stage_ewma_s > 0:
            score /= 1.0 + self.ec_stage_ewma_s
        return score


@dataclass(frozen=True)
class Move:
    vid: int
    shard_id: int
    src: str
    dst: str
    reason: str


@dataclass(frozen=True)
class Drop:
    """Delete a duplicate shard copy (dedupe)."""

    vid: int
    shard_id: int
    node: str


def node_view_for(
    node_id: str,
    rack: str,
    data_center: str,
    max_volume_count: int,
    num_volumes: int,
    ec_entries,
    collection: str = "",
    used_bytes: int = -1,
    capacity_bytes: int = -1,
    ec_telemetry: dict | None = None,
    now: float | None = None,
    stale_after: float | None = None,
) -> NodeView:
    """The ONE topology->NodeView mapping (shard-bit expansion and the
    slots*10 capacity formula) shared by the shell executor and the
    master's auto-scanner — a private copy in either would let the
    detector and the executor disagree about what needs balancing.

    ec_entries: EcShardInfoMsg-shaped objects (.id/.shard_bits/
    .collection). Every collection counts against capacity; only the
    selected one (if any) is planned.

    `used_bytes`/`capacity_bytes` (both >= 0) derive the node's disk
    headroom (`NodeView.free_bytes`); either unknown keeps headroom
    unknown (-1, slot-only planning).

    `ec_telemetry` is the node's heartbeat-learned device-telemetry
    blob (`DataNode.ec_telemetry` / the volume server's
    `_ec_telemetry_json`): per-chip queue loads sum into the LIVE
    `ec_load` scoring signal, the chip map's size into `ec_chips` (the
    gravity hardware signal), open breakers into `ec_breakers_open`,
    and the device-stage EWMAs into `ec_stage_ewma_s`. None/{} keeps
    the signals unknown — planning degrades to the static scoring.

    Stale-telemetry aging: a blob whose `received_at` (stamped by the
    master at absorb time; falls back to the sender's `ts`) is older
    than `stale_after` seconds (default `telemetry_stale_after()`)
    contributes NO steering signals — a dead node's last-reported idle
    chips must not keep attracting bytes — but `telemetry_age_s`
    still carries the age for status surfaces."""
    shards: dict[int, set[int]] = {}
    all_shards = 0
    for e in ec_entries:
        all_shards += bin(e.shard_bits).count("1")
        if collection and e.collection != collection:
            continue
        shards[e.id] = {i for i in range(32) if e.shard_bits & (1 << i)}
    ec_load = -1.0
    breakers = 0
    stage_ewma = -1.0
    n_chips = 0
    age_s = -1.0
    if ec_telemetry:
        try:
            stamped = float(
                ec_telemetry.get("received_at")
                or ec_telemetry.get("ts")
                or 0.0
            )
        except (TypeError, ValueError):
            stamped = 0.0
        if stamped > 0:
            age_s = max((now if now is not None else time.time()) - stamped, 0.0)
        if stale_after is None:
            stale_after = telemetry_stale_after()
        if age_s >= 0 and age_s > stale_after:
            # aged out: keep only the age; every signal reads unknown
            ec_telemetry = None
    if ec_telemetry:
        chips = ec_telemetry.get("chips")
        if isinstance(chips, dict):
            n_chips = len(chips)
            try:
                ec_load = float(
                    sum(c.get("load", 0) for c in chips.values())
                )
            except (TypeError, AttributeError):
                ec_load = -1.0
        try:
            breakers = int(ec_telemetry.get("breakers_open", 0))
        except (TypeError, ValueError):
            breakers = 0
        ewmas = ec_telemetry.get("stage_ewma_s")
        if isinstance(ewmas, dict):
            try:
                stage_ewma = float(
                    sum(
                        v
                        for k2, v in ewmas.items()
                        if k2.endswith(("h2d_dispatch", "device_drain"))
                    )
                )
            except (TypeError, ValueError):
                stage_ewma = -1.0
    return NodeView(
        id=node_id,
        rack=rack,
        data_center=data_center,
        free_slots=max(
            (int(max_volume_count or 8) - num_volumes) * 10 - all_shards,
            0,
        ),
        free_bytes=(
            max(capacity_bytes - used_bytes, 0)
            if capacity_bytes >= 0 and used_bytes >= 0
            else -1
        ),
        ec_load=ec_load,
        ec_breakers_open=breakers,
        ec_stage_ewma_s=stage_ewma,
        ec_chips=n_chips,
        telemetry_age_s=age_s,
        shards=shards,
    )


def plan_ec_balance(
    nodes: list[NodeView], max_moves: int = 10_000,
    data_gravity: bool = False, max_gravity_moves: int = 4,
) -> tuple[list[Drop], list[Move]]:
    """Full balance pass: dedupe -> across racks -> within racks ->
    per-rack total flattening. Mutates the NodeViews to reflect planned
    operations so later stages see earlier decisions.

    `data_gravity=True` (the `ec.balance -dataGravity` flag) appends a
    final stage that drifts shards from chip-poor/loaded nodes toward
    chip-rich low-load nodes — bounded by `max_gravity_moves`, and
    strictly BEHIND the spread invariants: a gravity move never makes
    per-volume spread worse on any node or rack, never exceeds the
    slot gate, and shuns destinations with no known byte headroom
    (like every balance stage, per-shard byte sizes are not in the
    topology snapshot the balancer plans over — the byte-exact fit
    gate lives in `plan_shard_placement(shard_bytes=)` and the
    rebalance planner, which do know shard sizes)."""
    by_id = {n.id: n for n in nodes}
    drops = _plan_dedupe(nodes)
    moves: list[Move] = []
    moves += _plan_across_racks(nodes, by_id)
    moves += _plan_within_racks(nodes, by_id)
    moves += _plan_rack_totals(nodes, by_id)
    if data_gravity:
        moves += _plan_gravity(nodes, by_id, max_gravity_moves)
    return drops, moves[:max_moves]


def plan_shard_placement(
    nodes: list[NodeView], vid: int, shard_ids: list[int],
    shard_bytes: int = 0,
) -> dict[int, str]:
    """Pick a destination server for each regenerated shard of `vid`
    (peer-fetch rebuild's distribute step): the same scoring the
    balancer uses for a move destination — fewest shards of THIS volume
    (spread the loss domain), then fewest total shards, then most free
    slots, then most disk headroom. Mutates the views as it assigns
    (slots AND headroom) so successive shards spread instead of
    stacking on one idle node. `shard_bytes` (when > 0) additionally
    gates destinations on known headroom: a shard is never planned onto
    a node it cannot physically fit. Shards no node can take are absent
    from the result (the caller keeps them local)."""
    plan: dict[int, str] = {}
    for sid in sorted(shard_ids):
        dest = _pick_dest_node(nodes, vid, shard_bytes=shard_bytes)
        if dest is None:
            continue
        dest.shards.setdefault(vid, set()).add(sid)
        dest.free_slots -= 1
        if dest.free_bytes >= 0:
            dest.free_bytes = max(dest.free_bytes - shard_bytes, 0)
        plan[sid] = dest.id
    return plan


# ------------------------------------------------------------------ stages


def _plan_dedupe(nodes: list[NodeView]) -> list[Drop]:
    """A shard held by several servers keeps the copy on the
    least-loaded holder; the rest are dropped
    (doDeduplicateEcShards)."""
    holders: dict[tuple[int, int], list[NodeView]] = defaultdict(list)
    for n in nodes:
        for vid, sids in n.shards.items():
            for sid in sids:
                holders[(vid, sid)].append(n)
    drops: list[Drop] = []
    for (vid, sid), hs in sorted(holders.items()):
        if len(hs) <= 1:
            continue
        hs.sort(key=lambda n: (n.shard_count(), n.id))
        for extra in hs[1:]:
            drops.append(Drop(vid, sid, extra.id))
            extra.shards[vid].discard(sid)
    return drops


def _racks(nodes: list[NodeView]) -> dict[tuple[str, str], list[NodeView]]:
    racks: dict[tuple[str, str], list[NodeView]] = defaultdict(list)
    for n in nodes:
        racks[n.rack_key()].append(n)
    return racks


def gravity_key(n: NodeView) -> tuple:
    """The GRAVITY half of destination scoring: no open chip breakers
    before open ones (a node whose chips are failing over to CPU loses
    any close call), then MORE heartbeat-learned chips before fewer
    (bytes drift toward hardware), then lower live `NodeView.ec_load`
    (summed per-chip DeviceQueue.load()) before higher. Tuple-exact so
    ordering never depends on float rounding; `gravity_score()` is the
    scalar rendering of the same signals for ranking/display."""
    return (
        n.ec_breakers_open > 0,
        -n.ec_chips,
        max(n.ec_load, 0.0),
    )


def _pick_dest_node(
    candidates: list[NodeView], vid: int, shard_bytes: int = 0
) -> NodeView | None:
    """Score a destination server: fewest shards of THIS volume first
    (spread the loss domain), then fewest total shards, then most free
    slots, then the GRAVITY score (`gravity_key`: breakers, chip
    count, live load — heartbeat-learned), then most known disk
    headroom, then lower device-stage EWMA pressure
    (pickEcNodeToBalanceShardsInto, capacity- and compute-aware).
    Gravity ranks BEHIND the rack-spread/slot invariants on purpose: a
    mixed fleet where some nodes don't report telemetry (older builds
    score as 0 chips / idle) must not have gravity override capacity —
    compute headroom only splits capacity ties, it never overrides
    them and never violates spread. A node with known headroom below
    `shard_bytes` is not a candidate at all (the free-bytes GATE)."""
    best = None
    for n in candidates:
        if n.free_slots <= 0:
            continue
        if shard_bytes > 0 and 0 <= n.free_bytes < shard_bytes:
            continue
        key = (
            len(n.shards.get(vid, ())),
            n.shard_count(),
            -n.free_slots,
            *gravity_key(n),
            -max(n.free_bytes, 0),
            max(n.ec_stage_ewma_s, 0.0),
            n.id,
        )
        if best is None or key < best[0]:
            best = (key, n)
    return best[1] if best else None


def _apply_move(m: Move, by_id: dict[str, NodeView]) -> None:
    src, dst = by_id[m.src], by_id[m.dst]
    src.shards[m.vid].discard(m.shard_id)
    if not src.shards[m.vid]:
        del src.shards[m.vid]
    dst.shards.setdefault(m.vid, set()).add(m.shard_id)
    src.free_slots += 1
    dst.free_slots -= 1


def _plan_across_racks(
    nodes: list[NodeView], by_id: dict[str, NodeView]
) -> list[Move]:
    """Per volume: no rack may hold more than
    ceil(total_shards / rack_count) shards (doBalanceEcShardsAcrossRacks)."""
    moves: list[Move] = []
    racks = _racks(nodes)
    if len(racks) < 2:
        return moves
    vids = sorted({vid for n in nodes for vid in n.shards})
    for vid in vids:
        rack_shards: dict[tuple[str, str], list[tuple[str, int]]] = defaultdict(list)
        for n in nodes:
            for sid in sorted(n.shards.get(vid, ())):
                rack_shards[n.rack_key()].append((n.id, sid))
        total = sum(len(v) for v in rack_shards.values())
        if total == 0:
            continue
        avg = -(-total // len(racks))  # ceil
        for rk in sorted(rack_shards, key=lambda k: -len(rack_shards[k])):
            overflow = rack_shards[rk][avg:]
            for node_id, sid in overflow:
                # destination racks scored by fewest shards of this
                # volume then aggregate free slots
                # (pickRackToBalanceShardsInto); fall through to the
                # next-best rack when the favorite has no capacity
                ranked = sorted(
                    (k for k in racks if k != rk),
                    key=lambda k: (
                        sum(len(by_id[n.id].shards.get(vid, ())) for n in racks[k]),
                        -sum(n.free_slots for n in racks[k]),
                        k,
                    ),
                )
                dest = None
                for dest_rk in ranked:
                    dest = _pick_dest_node(racks[dest_rk], vid)
                    if dest is not None:
                        break
                if dest is None:
                    continue
                m = Move(vid, sid, node_id, dest.id, "across-racks")
                _apply_move(m, by_id)
                moves.append(m)
    return moves


def _plan_within_racks(
    nodes: list[NodeView], by_id: dict[str, NodeView]
) -> list[Move]:
    """Per volume, per rack: spread that volume's shards evenly across
    the rack's servers (doBalanceEcShardsWithinOneRack)."""
    moves: list[Move] = []
    for rk, members in sorted(_racks(nodes).items()):
        if len(members) < 2:
            continue
        vids = sorted({vid for n in members for vid in n.shards})
        for vid in vids:
            held = [(n, sorted(n.shards.get(vid, ()))) for n in members]
            total = sum(len(s) for _, s in held)
            if total == 0:
                continue
            avg = -(-total // len(members))  # ceil
            for n, sids in held:
                for sid in sids[avg:]:
                    candidates = [
                        c
                        for c in members
                        if c is not n and len(c.shards.get(vid, ())) < avg
                    ]
                    dest = _pick_dest_node(candidates, vid)
                    if dest is None:
                        continue
                    m = Move(vid, sid, n.id, dest.id, "within-rack")
                    _apply_move(m, by_id)
                    moves.append(m)
    return moves


def _plan_gravity(
    nodes: list[NodeView], by_id: dict[str, NodeView], max_moves: int
) -> list[Move]:
    """Data-gravity drift (ec.balance -dataGravity): move shards off
    the WORST-gravity holders (chip-poor, loaded, breaker-open) onto
    strictly better-gravity nodes — without ever disturbing what the
    spread stages just established. A move is legal only when

    - the destination's gravity is STRICTLY better (`gravity_key`),
    - per-volume per-node spread does not get worse
      (dst_count + 1 <= src_count), and
    - with >= 2 racks, the destination rack stays within the
      ceil(total/racks) across-rack ceiling for that volume,
    - the destination has slot capacity and is not known to be out of
      byte headroom (free_bytes == 0; headroom also breaks destination
      ties — per-shard byte sizes are not in the balance snapshot, so
      the byte-exact fit gate belongs to the callers that have them).

    Bounded by `max_moves` per pass (migration I/O is real); the
    balance scanner converges over successive passes like every other
    stage."""
    moves: list[Move] = []
    racks = _racks(nodes)
    multi_rack = len(racks) >= 2

    def rack_count(rk: tuple[str, str], vid: int) -> int:
        return sum(len(n.shards.get(vid, ())) for n in racks[rk])

    # worst gravity first: their shards want to leave
    for src in sorted(nodes, key=lambda n: gravity_key(n), reverse=True):
        for vid in sorted(src.shards):
            total = sum(len(n.shards.get(vid, ())) for n in nodes)
            ceiling = -(-total // len(racks)) if multi_rack else total
            for sid in sorted(src.shards.get(vid, set())):
                if len(moves) >= max_moves:
                    return moves
                candidates = [
                    d
                    for d in nodes
                    if d is not src
                    and d.free_slots > 0
                    and d.free_bytes != 0
                    and gravity_key(d) < gravity_key(src)
                    and len(d.shards.get(vid, ()))
                    + 1 <= len(src.shards.get(vid, ()))
                    and (
                        not multi_rack
                        or d.rack_key() == src.rack_key()
                        or rack_count(d.rack_key(), vid) + 1 <= ceiling
                    )
                ]
                if not candidates:
                    break  # no better-gravity home for this volume here
                dest = min(
                    candidates,
                    key=lambda d: (
                        *gravity_key(d), -d.free_slots,
                        -max(d.free_bytes, 0), d.id,
                    ),
                )
                m = Move(vid, sid, src.id, dest.id, "gravity")
                _apply_move(m, by_id)
                moves.append(m)
    return moves


def _plan_rack_totals(
    nodes: list[NodeView], by_id: dict[str, NodeView]
) -> list[Move]:
    """Flatten TOTAL per-server shard counts inside each rack without
    disturbing per-volume spread: only move a volume the destination
    doesn't already hold (balanceEcRack)."""
    moves: list[Move] = []
    for rk, members in sorted(_racks(nodes).items()):
        if len(members) < 2:
            continue
        total = sum(n.shard_count() for n in members)
        avg = total / len(members)
        for _ in range(256):
            members_sorted = sorted(
                members, key=lambda n: (n.shard_count(), n.id)
            )
            low, high = members_sorted[0], members_sorted[-1]
            if not (
                high.shard_count() > avg
                and low.shard_count() + 1 <= avg
            ):
                break
            movable = [
                (vid, sid)
                for vid, sids in sorted(high.shards.items())
                for sid in sorted(sids)
                if vid not in low.shards
            ]
            if not movable or low.free_slots <= 0:
                break
            vid, sid = movable[0]
            m = Move(vid, sid, high.id, low.id, "rack-total")
            _apply_move(m, by_id)
            moves.append(m)
    return moves
