"""Rack-aware EC shard placement planning.

Reference: weed/shell/command_ec_common.go:60-120 (the EcBalance
algorithm description) and weed/storage/erasure_coding/ecbalancer/ —
per collection: deduplicate shard copies, spread each volume's shards
across racks (bounded by the per-rack average), then even them across
servers within each rack, and finally flatten total per-server counts
inside every rack.

Pure planning: callers snapshot the cluster into NodeViews, get back an
ordered list of Move/Drop operations, and execute them with their own
RPC machinery (the shell's ec.balance does copy+mount / unmount+delete
per move). Keeping the planner pure makes it testable against synthetic
topologies the way the reference tests shell commands against fixture
topology dumps.
"""

from __future__ import annotations

from collections import defaultdict
from dataclasses import dataclass, field


@dataclass
class NodeView:
    """One volume server as the planner sees it."""

    id: str
    rack: str = ""
    data_center: str = ""
    free_slots: int = 100
    # Disk headroom in bytes; -1 = unknown (callers without byte-level
    # topology keep slot-only planning). Known headroom both GATES a
    # destination (a shard must physically fit) and breaks scoring ties
    # toward the roomiest node, so sustained holder loss doesn't pile
    # regenerated shards onto a nearly-full survivor.
    free_bytes: int = -1
    # LIVE compute-load signal, heartbeat-learned (DataNode.ec_telemetry
    # -> per-chip DeviceQueue.load() cost units summed across the
    # node's chips). -1 = unknown (no telemetry: scored as idle, the
    # pre-live behavior). Used as a scoring tiebreak so a long-lived EC
    # stream lands on the host with compute headroom, not just disk
    # headroom.
    ec_load: float = -1.0
    # open fallback breakers on the node (its chips are failing over to
    # CPU): such a node loses any remotely close placement call.
    ec_breakers_open: int = 0
    # per-op/stage EWMA seconds from the node's flight recorder —
    # recorded for span-event evidence; device-stage pressure breaks
    # final ties.
    ec_stage_ewma_s: float = -1.0
    # vid -> set of shard ids held
    shards: dict[int, set[int]] = field(default_factory=dict)

    def shard_count(self) -> int:
        return sum(len(s) for s in self.shards.values())

    def rack_key(self) -> tuple[str, str]:
        return (self.data_center, self.rack)


@dataclass(frozen=True)
class Move:
    vid: int
    shard_id: int
    src: str
    dst: str
    reason: str


@dataclass(frozen=True)
class Drop:
    """Delete a duplicate shard copy (dedupe)."""

    vid: int
    shard_id: int
    node: str


def node_view_for(
    node_id: str,
    rack: str,
    data_center: str,
    max_volume_count: int,
    num_volumes: int,
    ec_entries,
    collection: str = "",
    used_bytes: int = -1,
    capacity_bytes: int = -1,
    ec_telemetry: dict | None = None,
) -> NodeView:
    """The ONE topology->NodeView mapping (shard-bit expansion and the
    slots*10 capacity formula) shared by the shell executor and the
    master's auto-scanner — a private copy in either would let the
    detector and the executor disagree about what needs balancing.

    ec_entries: EcShardInfoMsg-shaped objects (.id/.shard_bits/
    .collection). Every collection counts against capacity; only the
    selected one (if any) is planned.

    `used_bytes`/`capacity_bytes` (both >= 0) derive the node's disk
    headroom (`NodeView.free_bytes`); either unknown keeps headroom
    unknown (-1, slot-only planning).

    `ec_telemetry` is the node's heartbeat-learned device-telemetry
    blob (`DataNode.ec_telemetry` / the volume server's
    `_ec_telemetry_json`): per-chip queue loads sum into the LIVE
    `ec_load` scoring signal, open breakers into `ec_breakers_open`,
    and the device-stage EWMAs into `ec_stage_ewma_s`. None/{} keeps
    the signals unknown — planning degrades to the static scoring."""
    shards: dict[int, set[int]] = {}
    all_shards = 0
    for e in ec_entries:
        all_shards += bin(e.shard_bits).count("1")
        if collection and e.collection != collection:
            continue
        shards[e.id] = {i for i in range(32) if e.shard_bits & (1 << i)}
    ec_load = -1.0
    breakers = 0
    stage_ewma = -1.0
    if ec_telemetry:
        chips = ec_telemetry.get("chips")
        if isinstance(chips, dict):
            try:
                ec_load = float(
                    sum(c.get("load", 0) for c in chips.values())
                )
            except (TypeError, AttributeError):
                ec_load = -1.0
        try:
            breakers = int(ec_telemetry.get("breakers_open", 0))
        except (TypeError, ValueError):
            breakers = 0
        ewmas = ec_telemetry.get("stage_ewma_s")
        if isinstance(ewmas, dict):
            try:
                stage_ewma = float(
                    sum(
                        v
                        for k2, v in ewmas.items()
                        if k2.endswith(("h2d_dispatch", "device_drain"))
                    )
                )
            except (TypeError, ValueError):
                stage_ewma = -1.0
    return NodeView(
        id=node_id,
        rack=rack,
        data_center=data_center,
        free_slots=max(
            (int(max_volume_count or 8) - num_volumes) * 10 - all_shards,
            0,
        ),
        free_bytes=(
            max(capacity_bytes - used_bytes, 0)
            if capacity_bytes >= 0 and used_bytes >= 0
            else -1
        ),
        ec_load=ec_load,
        ec_breakers_open=breakers,
        ec_stage_ewma_s=stage_ewma,
        shards=shards,
    )


def plan_ec_balance(
    nodes: list[NodeView], max_moves: int = 10_000
) -> tuple[list[Drop], list[Move]]:
    """Full balance pass: dedupe -> across racks -> within racks ->
    per-rack total flattening. Mutates the NodeViews to reflect planned
    operations so later stages see earlier decisions."""
    by_id = {n.id: n for n in nodes}
    drops = _plan_dedupe(nodes)
    moves: list[Move] = []
    moves += _plan_across_racks(nodes, by_id)
    moves += _plan_within_racks(nodes, by_id)
    moves += _plan_rack_totals(nodes, by_id)
    return drops, moves[:max_moves]


def plan_shard_placement(
    nodes: list[NodeView], vid: int, shard_ids: list[int],
    shard_bytes: int = 0,
) -> dict[int, str]:
    """Pick a destination server for each regenerated shard of `vid`
    (peer-fetch rebuild's distribute step): the same scoring the
    balancer uses for a move destination — fewest shards of THIS volume
    (spread the loss domain), then fewest total shards, then most free
    slots, then most disk headroom. Mutates the views as it assigns
    (slots AND headroom) so successive shards spread instead of
    stacking on one idle node. `shard_bytes` (when > 0) additionally
    gates destinations on known headroom: a shard is never planned onto
    a node it cannot physically fit. Shards no node can take are absent
    from the result (the caller keeps them local)."""
    plan: dict[int, str] = {}
    for sid in sorted(shard_ids):
        dest = _pick_dest_node(nodes, vid, shard_bytes=shard_bytes)
        if dest is None:
            continue
        dest.shards.setdefault(vid, set()).add(sid)
        dest.free_slots -= 1
        if dest.free_bytes >= 0:
            dest.free_bytes = max(dest.free_bytes - shard_bytes, 0)
        plan[sid] = dest.id
    return plan


# ------------------------------------------------------------------ stages


def _plan_dedupe(nodes: list[NodeView]) -> list[Drop]:
    """A shard held by several servers keeps the copy on the
    least-loaded holder; the rest are dropped
    (doDeduplicateEcShards)."""
    holders: dict[tuple[int, int], list[NodeView]] = defaultdict(list)
    for n in nodes:
        for vid, sids in n.shards.items():
            for sid in sids:
                holders[(vid, sid)].append(n)
    drops: list[Drop] = []
    for (vid, sid), hs in sorted(holders.items()):
        if len(hs) <= 1:
            continue
        hs.sort(key=lambda n: (n.shard_count(), n.id))
        for extra in hs[1:]:
            drops.append(Drop(vid, sid, extra.id))
            extra.shards[vid].discard(sid)
    return drops


def _racks(nodes: list[NodeView]) -> dict[tuple[str, str], list[NodeView]]:
    racks: dict[tuple[str, str], list[NodeView]] = defaultdict(list)
    for n in nodes:
        racks[n.rack_key()].append(n)
    return racks


def _pick_dest_node(
    candidates: list[NodeView], vid: int, shard_bytes: int = 0
) -> NodeView | None:
    """Score a destination server: fewest shards of THIS volume first
    (spread the loss domain), then fewest total shards, then no open
    chip breakers before open ones (a node whose chips are failing
    over to CPU loses any close call), then most free slots, then —
    the LIVE compute signal, heartbeat-learned — lower
    `NodeView.ec_load` (summed per-chip DeviceQueue.load()) before
    higher, then most known disk headroom, then lower device-stage
    EWMA pressure (pickEcNodeToBalanceShardsInto, capacity- and
    compute-aware). Live load ranks AFTER the slot capacity signal on
    purpose: a mixed fleet where some nodes don't report telemetry
    (older builds score as idle, 0.0) must not funnel every shard onto
    the non-reporting nodes — load only splits capacity ties, it never
    overrides them. A node with known headroom below `shard_bytes` is
    not a candidate at all."""
    best = None
    for n in candidates:
        if n.free_slots <= 0:
            continue
        if shard_bytes > 0 and 0 <= n.free_bytes < shard_bytes:
            continue
        key = (
            len(n.shards.get(vid, ())),
            n.shard_count(),
            n.ec_breakers_open > 0,
            -n.free_slots,
            max(n.ec_load, 0.0),
            -max(n.free_bytes, 0),
            max(n.ec_stage_ewma_s, 0.0),
            n.id,
        )
        if best is None or key < best[0]:
            best = (key, n)
    return best[1] if best else None


def _apply_move(m: Move, by_id: dict[str, NodeView]) -> None:
    src, dst = by_id[m.src], by_id[m.dst]
    src.shards[m.vid].discard(m.shard_id)
    if not src.shards[m.vid]:
        del src.shards[m.vid]
    dst.shards.setdefault(m.vid, set()).add(m.shard_id)
    src.free_slots += 1
    dst.free_slots -= 1


def _plan_across_racks(
    nodes: list[NodeView], by_id: dict[str, NodeView]
) -> list[Move]:
    """Per volume: no rack may hold more than
    ceil(total_shards / rack_count) shards (doBalanceEcShardsAcrossRacks)."""
    moves: list[Move] = []
    racks = _racks(nodes)
    if len(racks) < 2:
        return moves
    vids = sorted({vid for n in nodes for vid in n.shards})
    for vid in vids:
        rack_shards: dict[tuple[str, str], list[tuple[str, int]]] = defaultdict(list)
        for n in nodes:
            for sid in sorted(n.shards.get(vid, ())):
                rack_shards[n.rack_key()].append((n.id, sid))
        total = sum(len(v) for v in rack_shards.values())
        if total == 0:
            continue
        avg = -(-total // len(racks))  # ceil
        for rk in sorted(rack_shards, key=lambda k: -len(rack_shards[k])):
            overflow = rack_shards[rk][avg:]
            for node_id, sid in overflow:
                # destination racks scored by fewest shards of this
                # volume then aggregate free slots
                # (pickRackToBalanceShardsInto); fall through to the
                # next-best rack when the favorite has no capacity
                ranked = sorted(
                    (k for k in racks if k != rk),
                    key=lambda k: (
                        sum(len(by_id[n.id].shards.get(vid, ())) for n in racks[k]),
                        -sum(n.free_slots for n in racks[k]),
                        k,
                    ),
                )
                dest = None
                for dest_rk in ranked:
                    dest = _pick_dest_node(racks[dest_rk], vid)
                    if dest is not None:
                        break
                if dest is None:
                    continue
                m = Move(vid, sid, node_id, dest.id, "across-racks")
                _apply_move(m, by_id)
                moves.append(m)
    return moves


def _plan_within_racks(
    nodes: list[NodeView], by_id: dict[str, NodeView]
) -> list[Move]:
    """Per volume, per rack: spread that volume's shards evenly across
    the rack's servers (doBalanceEcShardsWithinOneRack)."""
    moves: list[Move] = []
    for rk, members in sorted(_racks(nodes).items()):
        if len(members) < 2:
            continue
        vids = sorted({vid for n in members for vid in n.shards})
        for vid in vids:
            held = [(n, sorted(n.shards.get(vid, ()))) for n in members]
            total = sum(len(s) for _, s in held)
            if total == 0:
                continue
            avg = -(-total // len(members))  # ceil
            for n, sids in held:
                for sid in sids[avg:]:
                    candidates = [
                        c
                        for c in members
                        if c is not n and len(c.shards.get(vid, ())) < avg
                    ]
                    dest = _pick_dest_node(candidates, vid)
                    if dest is None:
                        continue
                    m = Move(vid, sid, n.id, dest.id, "within-rack")
                    _apply_move(m, by_id)
                    moves.append(m)
    return moves


def _plan_rack_totals(
    nodes: list[NodeView], by_id: dict[str, NodeView]
) -> list[Move]:
    """Flatten TOTAL per-server shard counts inside each rack without
    disturbing per-volume spread: only move a volume the destination
    doesn't already hold (balanceEcRack)."""
    moves: list[Move] = []
    for rk, members in sorted(_racks(nodes).items()):
        if len(members) < 2:
            continue
        total = sum(n.shard_count() for n in members)
        avg = total / len(members)
        for _ in range(256):
            members_sorted = sorted(
                members, key=lambda n: (n.shard_count(), n.id)
            )
            low, high = members_sorted[0], members_sorted[-1]
            if not (
                high.shard_count() > avg
                and low.shard_count() + 1 <= avg
            ):
                break
            movable = [
                (vid, sid)
                for vid, sids in sorted(high.shards.items())
                for sid in sorted(sids)
                if vid not in low.shards
            ]
            if not movable or low.free_slots <= 0:
                break
            vid, sid = movable[0]
            m = Move(vid, sid, high.id, low.id, "rack-total")
            _apply_move(m, by_id)
            moves.append(m)
    return moves
