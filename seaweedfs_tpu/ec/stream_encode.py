"""Streaming EC: encode-on-write with incremental parity (online RS).

Until now EC only ran as a batch job over SEALED volumes
(`ec/encoder.py:write_ec_files` reads a finished .dat). This module
opens the WRITE path: an :class:`EcStreamEncoder` accepts appends of
unknown total length on a long-lived device stream and keeps parity
trailing the append head by a bounded lag, so redundancy exists while
the object is still being written — EC as a serving-path capability
(the MQ broker's durable-parity log segments, `mq/stream_parity.py`)
instead of a nightly batch.

Why this is cheap math: RS over GF(2^8) is LINEAR. With generator rows
``G = matrix[k:]`` (m x k), parity of a stripe is ``P = G @ D``; when a
row-batch lands in data row ``i`` columns ``[c0,c1)``, the parity of
the zero-extended stripe updates in place::

    P[:, c0:c1] ^= G[:, i:i+1] @ new_bytes      (GF add == XOR)

so a PARTIAL stripe (rows not yet arrived = zeros) always carries valid
parity for its zero-extension — every flush point is a crash-consistent
redundancy point, not just stripe boundaries.

Layout contract (bit-identity with the batch encoder)
-----------------------------------------------------

The stream uses exactly `write_ec_files`'s striping: greedy large
stripes of ``k x block_size`` (row ``i`` of stripe ``s`` lands in shard
``i`` at file offset ``s * block_size``), and — at :meth:`close` with
``finalize=True`` — the ragged tail re-striped with
``small_block_size`` rows, zero-padded, just like the batch path's
small-chunk plan. N appends through the stream therefore produce
byte-identical shard files and sidecar CRCs to ONE `write_ec_files`
over the concatenation with the same block parameters (asserted
cross-backend in tests/test_ec_stream_encode.py and in the
`streaming_encode` bench line).

Durability protocol (the stripe-cursor journal)
-----------------------------------------------

Appends buffer in the open stripe; :meth:`flush` makes them durable:

  1. PROCESS — parity deltas dispatched through the stream's
     DeviceQueue admission (`backend.apply_staged`, PR 5 cost model);
     data rows pwritten at their final offsets; completed stripes seal
     (final parity rows + CRCs).
  2. FSYNC   — every touched shard fd.
  3. JOURNAL — `<base>.stream` cursor (self-checksummed like
     ec/repair_journal.py intents): uuid fence, embedder cookie
     (`meta`, e.g. the MQ partition's base record offset), durable
     byte head, sealed stripe count.

Recovery (:func:`recover_stream`) reads the journal, bounds the head
by on-disk row extents, lets the embedder frame-scan the linear bytes
for the TRUE head (e.g. dense MQ record offsets), then re-derives and
rewrites any parity that disagrees with the data — data is ground
truth; a stripe whose parity disagrees is repaired or rolled back,
never published.

Time-to-durable-parity is the first-class metric:
``sw_ec_stream_parity_lag_seconds`` observes, per append, the wall
time from append() to the flush that made its parity durable;
:meth:`parity_lag_s` exposes the live lag of the oldest un-flushed
append.

Env knobs (`SEAWEED_EC_STREAM_*`, all overridable per call):
``SEAWEED_EC_STREAM_BLOCK_KB`` (large-stripe row block, default 1024),
``SEAWEED_EC_STREAM_SMALL_KB`` (tail re-stripe block, default 64),
``SEAWEED_EC_STREAM_FLUSH_KB`` (broker flush threshold, default 256),
``SEAWEED_EC_STREAM_MAX_LAG_MS`` (broker flush deadline, default 200),
``SEAWEED_EC_STREAM_ROTATE_MB`` (broker stream rotation, default 64),
``SEAWEED_EC_STREAM_BACKEND`` (broker RS backend, default auto).
"""

from __future__ import annotations

import os
import struct
import threading
import time
import uuid as _uuid
import weakref
from dataclasses import dataclass

import numpy as np

from .. import faults
from ..utils import metrics as _M
from ..utils import trace
from ..utils.crc import crc32c
from ..utils.fs import atomic_write, fsync_dir
from ..utils.glog import logger
from .bitrot import BitrotProtection, ShardChecksumBuilder
from .context import (
    BITROT_BLOCK_SIZE,
    BITROT_LEAF_SIZE,
    DEFAULT_EC_CONTEXT,
    ECContext,
    ECError,
)

log = logger("ec.stream")

JOURNAL_SUFFIX = ".stream"

MAGIC = 0x53575354  # "SWST"
FORMAT_VERSION = 1
# magic u32 BE | version u16 | k u8 | m u8 | block u32 | small u32 |
# uuid 16s | meta u64 | durable u64 | sealed u64 | head u64 | crc u32
_JOURNAL = struct.Struct(">I")
_JOURNAL_BODY = struct.Struct("<HBBII16sQQQQ")


def _env_kib(name: str, default_kib: int) -> int:
    try:
        v = int(os.environ.get(name, str(default_kib)))
    except ValueError:
        v = default_kib
    return max(v, 1) << 10


def stream_block_size() -> int:
    """Large-stripe row block (bytes): SEAWEED_EC_STREAM_BLOCK_KB."""
    return _env_kib("SEAWEED_EC_STREAM_BLOCK_KB", 1024)


def stream_small_block_size() -> int:
    """Tail re-stripe block (bytes): SEAWEED_EC_STREAM_SMALL_KB."""
    return _env_kib("SEAWEED_EC_STREAM_SMALL_KB", 64)


_parity_lag = _M.REGISTRY.histogram(
    "sw_ec_stream_parity_lag_seconds",
    "per-append wall time from append() to durable parity "
    "(time-to-durable-parity, the streaming-EC first-class metric)",
    buckets=(
        0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
        10.0, 30.0,
    ),
)
_appended_bytes = _M.REGISTRY.counter(
    "sw_ec_stream_appended_bytes_total",
    "bytes appended to EC stream encoders",
)
_stripes_sealed = _M.REGISTRY.counter(
    "sw_ec_stream_stripes_sealed_total",
    "EC stream stripes sealed (final parity published)",
)
_recovered = _M.REGISTRY.counter(
    "sw_ec_stream_recovered_total",
    "EC stream recovery events by outcome",
    ("outcome",),
)


# Live encoder registry for the open-streams gauge + stream_summary():
# weak, so a dropped encoder never pins device state behind a metric.
_live_streams: "weakref.WeakSet[EcStreamEncoder]" = weakref.WeakSet()


def _open_stream_samples():
    yield {}, float(sum(1 for e in list(_live_streams) if not e.closed))


_M.REGISTRY.gauge(
    "sw_ec_stream_open",
    "EC stream encoders currently open",
    fn=_open_stream_samples,
)


def stream_summary() -> dict:
    """Process-local streaming-EC roll-up for /cluster/status and the
    volume server /status plane (the `/debug/gateway` idiom): open
    streams with their live parity lag, plus the lifetime counters."""
    streams = []
    for enc in list(_live_streams):
        if enc.closed:
            continue
        streams.append(
            {
                "base": os.path.basename(enc.base),
                "head_bytes": enc.head,
                "durable_bytes": enc.durable,
                "sealed_stripes": enc.sealed_stripes,
                "parity_lag_ms": round(enc.parity_lag_s() * 1000.0, 3),
                "chip": enc.chip_label,
            }
        )
    return {
        "open": len(streams),
        "streams": sorted(streams, key=lambda s: s["base"]),
        "appended_bytes": sum(_appended_bytes.snapshot().values()),
        "stripes_sealed": sum(_stripes_sealed.snapshot().values()),
        # label tuples -> plain strings: this dict rides JSON surfaces
        "recovered": {
            (k[0] if k else ""): v
            for k, v in _recovered.snapshot().items()
        },
    }


# --------------------------------------------------------------------------
# Stripe-cursor journal
# --------------------------------------------------------------------------


@dataclass
class StreamJournal:
    """Decoded `<base>.stream` cursor: everything recovery needs to
    trust the on-disk stream prefix."""

    uuid: bytes
    meta: int  # embedder cookie (MQ: base record offset of this stream)
    durable: int  # linear bytes with durable data AND parity
    sealed: int  # stripes whose final parity is published
    head: int  # advisory: bytes appended at journal time (>= durable)
    block_size: int = 0
    small_block_size: int = 0
    data_shards: int = 0
    parity_shards: int = 0

    def to_bytes(self) -> bytes:
        body = _JOURNAL_BODY.pack(
            FORMAT_VERSION,
            self.data_shards,
            self.parity_shards,
            self.block_size,
            self.small_block_size,
            self.uuid,
            self.meta,
            self.durable,
            self.sealed,
            self.head,
        )
        raw = _JOURNAL.pack(MAGIC) + body
        return raw + struct.pack("<I", crc32c(raw))


def load_stream_journal(base: str) -> StreamJournal | None:
    """The stream's cursor, or None when absent/torn — a torn cursor
    means the stream was never durable past its previous cursor (the
    journal is written AFTER the fsync it describes), so recovery
    treats it as empty rather than guessing."""
    path = base + JOURNAL_SUFFIX
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError:
        return None
    want = _JOURNAL.size + _JOURNAL_BODY.size + 4
    if len(raw) != want:
        return None
    if crc32c(raw[:-4]) != struct.unpack("<I", raw[-4:])[0]:
        return None
    if _JOURNAL.unpack_from(raw)[0] != MAGIC:
        return None
    (
        version, k, m, block, small, uid, meta, durable, sealed, head,
    ) = _JOURNAL_BODY.unpack_from(raw, _JOURNAL.size)
    if version != FORMAT_VERSION:
        return None
    return StreamJournal(
        uuid=uid, meta=meta, durable=durable, sealed=sealed, head=head,
        block_size=block, small_block_size=small,
        data_shards=k, parity_shards=m,
    )


# --------------------------------------------------------------------------
# The encoder
# --------------------------------------------------------------------------


class EcStreamEncoder:
    """Online EC encoder for one append stream of unknown length.

    Not thread-safe per method pair by accident: append/flush/close
    serialize on an internal lock, so a broker's append path and its
    background parity flusher may race freely.

    `scheduler` is the QueueScope whose placement/admission config this
    stream runs under (None = process default); the stream is placed
    ONCE at construction via `chip_pool.place_stream` (live-load
    routing) and every parity batch is admitted to the chip's
    DeviceQueue with the PR 5 cost model
    (`batch_cost(m, batch_width)`).

    `meta` is an opaque embedder cookie persisted in the stripe-cursor
    journal (the MQ glue stores the partition's base record offset).
    """

    def __init__(
        self,
        base: str,
        ctx: ECContext = DEFAULT_EC_CONTEXT,
        backend=None,
        block_size: int | None = None,
        small_block_size: int | None = None,
        leaf_size: int = BITROT_LEAF_SIZE,
        scheduler=None,
        meta: int = 0,
        fsync: bool = True,
    ):
        from .backend import get_backend
        from .chip_pool import place_stream
        from .device_queue import batch_cost

        if backend is None:
            backend = get_backend("auto", ctx.data_shards, ctx.parity_shards)
        self.base = base
        self.ctx = ctx
        self.block_size = int(block_size or stream_block_size())
        self.small_block_size = int(
            small_block_size or stream_small_block_size()
        )
        if self.small_block_size > self.block_size:
            raise ECError(
                f"small block {self.small_block_size} exceeds block "
                f"{self.block_size}"
            )
        self.leaf_size = leaf_size
        self.meta = int(meta)
        self.uuid = _uuid.uuid4().bytes
        self._fsync = fsync
        k, m, total = ctx.data_shards, ctx.parity_shards, ctx.total
        self._k, self._m = k, m
        self._stripe_row = self.block_size * k
        # parity generator rows of the shared RS matrix (m x k): the
        # linearity identity needs exactly these coefficients
        self._gen = np.ascontiguousarray(
            np.asarray(backend.matrix, dtype=np.uint8)[k : k + m, :]
        )
        # Two locks so the APPEND path never waits on parity math or
        # fsync: `_buf_lock` guards only the pending buffer + head +
        # lag queue (append takes just this — a buffer copy), while
        # `_lock` serializes process/flush/close (stripe state, fds,
        # journal). Lock order where both are held: _lock outer,
        # _buf_lock inner.
        self._lock = threading.RLock()
        self._buf_lock = threading.Lock()
        self._fds: list[int] = []
        try:
            for i in range(total):
                self._fds.append(
                    os.open(
                        base + ctx.to_ext(i),
                        os.O_RDWR | os.O_CREAT | os.O_TRUNC,
                        0o644,
                    )
                )
        except BaseException:
            for fd in self._fds:
                os.close(fd)
            raise
        self._builders = [
            ShardChecksumBuilder(BITROT_BLOCK_SIZE, leaf_size)
            for _ in range(total)
        ]
        # open-stripe state: data rows + incremental parity, both in
        # memory (k x block + m x block); `filled` is the linear byte
        # count within the stripe
        self._data = np.zeros((k, self.block_size), dtype=np.uint8)
        self._parity = np.zeros((m, self.block_size), dtype=np.uint8)
        self._filled = 0
        self.sealed_stripes = 0
        # appended-but-unprocessed bytes (parity not yet computed)
        self._pending: list[bytes] = []
        self._pending_bytes = 0
        # (linear end offset, append wall time) for lag attribution
        self._lag_q: list[tuple[int, float]] = []
        self.head = 0  # bytes appended
        self._processed = 0  # bytes run through the parity math
        self.durable = 0  # bytes with durable data+parity (journaled)
        self._touched: set[int] = set()
        self.closed = False
        self._finalized = False
        # Flight recorder + placement: one long-lived foreground stream
        self._span = trace.start(
            "ec.stream_encode", name=os.path.basename(base), base=base,
            block_size=self.block_size,
        )
        self._placement = place_stream(
            backend, "foreground",
            scope=scheduler,
            cost_hint=batch_cost(m, self.block_size),
            span=self._span,
        )
        self._backend = self._placement.backend
        self.chip_label = getattr(self._backend, "chip_label", "")
        dq = self._placement.queue
        self._stream = (
            dq.stream("foreground", label="ec stream encode", span=self._span)
            if dq is not None
            else None
        )
        self._write_journal()
        _live_streams.add(self)

    # ------------------------------------------------------------ append

    def append(self, data: bytes) -> int:
        """Buffer `data` at the stream head; returns the linear byte
        offset it starts at. Takes only the buffer lock (one copy) —
        an append never waits behind a concurrent flush's parity math
        or fsync. Parity is computed at the next
        :meth:`process`/:meth:`flush` (the broker's flusher calls flush
        on a bytes/lag policy); durability comes from flush."""
        if not data:
            return self.head
        with self._buf_lock:
            if self.closed:
                raise ECError(f"stream encoder {self.base} is closed")
            off = self.head
            self._pending.append(bytes(data))
            self._pending_bytes += len(data)
            self.head += len(data)
            self._lag_q.append((self.head, time.monotonic()))
            _appended_bytes.inc(len(data))
            return off

    @property
    def pending_bytes(self) -> int:
        with self._buf_lock:
            return self.head - self.durable

    def parity_lag_s(self) -> float:
        """Age of the oldest append whose parity is not yet durable
        (0.0 when fully flushed) — the live lag the flusher bounds."""
        with self._buf_lock:
            if not self._lag_q:
                return 0.0
            return max(time.monotonic() - self._lag_q[0][1], 0.0)

    # ----------------------------------------------------------- process

    def _dispatch_apply(self, coeffs: np.ndarray, batch: np.ndarray):
        """One parity-delta batch through the placed device stream
        (DeviceQueue admission, PR 5 cost model) or directly when the
        scheduler is disabled. Returns the m x width host delta."""
        from .device_queue import batch_cost

        be = self._backend
        if self._stream is None:
            with trace.stage(self._span, "h2d_dispatch", self.chip_label):
                handle = be.apply_staged(coeffs, be.to_device(batch))
            with trace.stage(self._span, "device_drain", self.chip_label):
                return np.ascontiguousarray(be.to_host(handle), np.uint8)
        ticket, handle = self._stream.dispatch(
            lambda: be.apply_staged(coeffs, be.to_device(batch)),
            batch_cost(coeffs.shape[0], batch.shape[-1]),
        )
        try:
            with trace.stage(self._span, "device_drain", self.chip_label):
                return np.ascontiguousarray(be.to_host(handle), np.uint8)
        finally:
            self._stream.release(ticket)

    def _seal_stripe(self) -> None:
        """The open stripe is full: publish its final parity rows, roll
        every shard's CRCs, reset the stripe buffers."""
        faults.fire("ec.stream.seal", base=self.base, stripe=self.sealed_stripes)
        s = self.sealed_stripes
        base_off = s * self.block_size
        k, m = self._k, self._m
        with trace.stage(self._span, "write_sink"):
            for j in range(m):
                os.pwrite(self._fds[k + j], self._parity[j].tobytes(), base_off)
                self._touched.add(k + j)
        for i in range(k):
            self._builders[i].write(self._data[i].tobytes())
        for j in range(m):
            self._builders[k + j].write(self._parity[j].tobytes())
        self._data[:] = 0
        self._parity[:] = 0
        self._filled = 0
        self.sealed_stripes += 1
        _stripes_sealed.inc()

    def process(self) -> None:
        """Drain the append buffer through the parity math: data rows
        pwritten at their final offsets, parity updated in place via
        `apply_staged` (RS linearity), full stripes sealed. Does NOT
        fsync or journal — that is :meth:`flush`'s second half."""
        with self._lock:
            self._process_locked()

    def _process_locked(self) -> None:
        with self._buf_lock:
            if not self._pending:
                return
            buf = b"".join(self._pending)
            self._pending = []
            self._pending_bytes = 0
        self._processed += len(buf)
        block, row_bytes = self.block_size, self._stripe_row
        k = self._k
        pos = 0
        while pos < len(buf):
            in_stripe = self._filled
            row = in_stripe // block
            col = in_stripe % block
            take = min(len(buf) - pos, block - col)
            chunk = np.frombuffer(buf, dtype=np.uint8, count=take, offset=pos)
            # data row into the open-stripe buffer + its final offset
            self._data[row, col : col + take] = chunk
            with trace.stage(self._span, "write_sink"):
                os.pwrite(
                    self._fds[row],
                    buf[pos : pos + take],
                    self.sealed_stripes * block + col,
                )
            self._touched.add(row)
            # incremental parity: P[:, col:col+take] ^= G[:, row] @ chunk
            with trace.stage(self._span, "parity_update"):
                delta = self._dispatch_apply(
                    self._gen[:, row : row + 1], chunk.reshape(1, take)
                )
                self._parity[:, col : col + take] ^= delta
            pos += take
            self._filled += take
            if self._filled == row_bytes:
                self._seal_stripe()

    # ------------------------------------------------------------- flush

    def flush(self) -> int:
        """Make every appended byte durable WITH its parity: process
        the buffer, fsync touched shards, advance the stripe-cursor
        journal, observe per-append time-to-durable-parity. Returns the
        durable head."""
        with self._lock:
            if self.closed:
                return self.durable
            self._process_locked()
            # partial-flush parity for the open stripe: the whole
            # covered column range (rows overwrite columns repeatedly,
            # so per-chunk tracking buys little — the open extent is
            # the honest dirty range)
            if self._filled and self._processed > self.durable:
                block, k = self.block_size, self._k
                full_rows = self._filled // block
                part = self._filled % block
                hi = block if full_rows else part
                base_off = self.sealed_stripes * block
                with trace.stage(self._span, "write_sink"):
                    for j in range(self._m):
                        os.pwrite(
                            self._fds[k + j],
                            self._parity[j, :hi].tobytes(),
                            base_off,
                        )
                        self._touched.add(k + j)
            faults.fire("ec.stream.before_fsync", base=self.base)
            if self._fsync and self._touched:
                with trace.stage(self._span, "fsync_publish"):
                    for i in sorted(self._touched):
                        os.fsync(self._fds[i])
                self._touched.clear()
            faults.fire("ec.stream.before_journal", base=self.base)
            # durable = bytes actually processed+fsynced this cycle;
            # appends racing this flush stay pending for the next one
            self.durable = self._processed
            self._write_journal()
            now = time.monotonic()
            with self._buf_lock:
                while self._lag_q and self._lag_q[0][0] <= self.durable:
                    _, t0 = self._lag_q.pop(0)
                    _parity_lag.observe(max(now - t0, 0.0))
            return self.durable

    def _write_journal(self) -> None:
        j = StreamJournal(
            uuid=self.uuid,
            meta=self.meta,
            durable=self.durable,
            sealed=self.sealed_stripes,
            head=self.head,
            block_size=self.block_size,
            small_block_size=self.small_block_size,
            data_shards=self._k,
            parity_shards=self._m,
        )
        atomic_write(self.base + JOURNAL_SUFFIX, j.to_bytes())

    # ------------------------------------------------------------- close

    def close(self, finalize: bool = True) -> BitrotProtection | None:
        """End the stream.

        ``finalize=True`` re-stripes the ragged tail with small blocks
        (bit-identical to `write_ec_files` over the concatenation),
        publishes the `.ecsum` sidecar, and RETIRES the journal — the
        artifact is now a sealed EC volume layout. ``finalize=False``
        (broker stream rotation) just flushes and closes: the large
        layout + journal stay recoverable."""
        with self._lock:
            if self.closed:
                return None
            prot: BitrotProtection | None = None
            try:
                self.flush()
                if finalize:
                    prot = self._finalize_locked()
            finally:
                # refuse further appends BEFORE the fds go away (the
                # flag is read under the buffer lock on the append path)
                with self._buf_lock:
                    self.closed = True
                for fd in self._fds:
                    try:
                        os.close(fd)
                    except OSError:
                        pass
                self._fds = []
                if self._stream is not None:
                    self._stream.close()
                self._placement.close()
                trace.finish(self._span)
            return prot

    def _finalize_locked(self) -> BitrotProtection:
        ctx = self.ctx
        k, m, block = self._k, self._m, self.block_size
        small = self.small_block_size
        tail_len = self._filled
        if tail_len:
            # the open stripe was written in the LARGE layout for
            # crash recovery; the batch encoder stripes a sub-stripe
            # tail with small rows — rewrite it identically
            base_off = self.sealed_stripes * block
            for fd in self._fds:
                os.ftruncate(fd, base_off)
            tail = b"".join(
                self._data[i].tobytes() for i in range(k)
            )[:tail_len]
            off = 0
            t = 0
            small_row = small * k
            while off < tail_len:
                seg = tail[off : off + small_row]
                mat = np.zeros((k, small), dtype=np.uint8)
                flat = np.frombuffer(seg, dtype=np.uint8)
                mat.reshape(-1)[: len(flat)] = flat
                parity = self._dispatch_apply(self._gen, mat)
                woff = base_off + t * small
                rows = [mat[i].tobytes() for i in range(k)] + [
                    parity[j].tobytes() for j in range(m)
                ]
                with trace.stage(self._span, "write_sink"):
                    for i, row in enumerate(rows):
                        os.pwrite(self._fds[i], row, woff)
                        self._builders[i].write(row)
                        self._touched.add(i)
                off += small_row
                t += 1
            self._data[:] = 0
            self._parity[:] = 0
            self._filled = 0
        faults.fire("ec.stream.before_seal_publish", base=self.base)
        if self._fsync:
            with trace.stage(self._span, "fsync_publish"):
                for fd in self._fds:
                    os.fsync(fd)
            fsync_dir(self.base + ctx.to_ext(0))
        prot = BitrotProtection.from_builders(ctx, self._builders)
        prot.save(self.base + ".ecsum")
        self._finalized = True
        try:
            os.unlink(self.base + JOURNAL_SUFFIX)
            fsync_dir(self.base + JOURNAL_SUFFIX)
        except OSError:
            pass
        return prot

    def __enter__(self) -> "EcStreamEncoder":
        return self

    def __exit__(self, *exc) -> None:
        self.close(finalize=not any(exc))


# --------------------------------------------------------------------------
# Recovery (non-finalized streams: the broker's rotating generations)
# --------------------------------------------------------------------------


def _data_extent_head(
    base: str, ctx: ECContext, block_size: int
) -> int:
    """Largest CONTIGUOUS linear head the on-disk data-row extents can
    support (large-stripe layout). File sizes only ever grow with
    appends, so this is an upper bound on what a frame scan may
    trust."""
    k = ctx.data_shards
    sizes = []
    for i in range(k):
        try:
            sizes.append(os.path.getsize(base + ctx.to_ext(i)))
        except OSError:
            sizes.append(0)
    head = 0
    s = 0
    while True:
        exts = [
            min(max(sz - s * block_size, 0), block_size) for sz in sizes
        ]
        stripe_head = 0
        for e in exts:
            stripe_head += e
            if e < block_size:
                break
        head += stripe_head
        if stripe_head < block_size * k:
            return head
        s += 1


def read_stream_data(
    base: str, ctx: ECContext, block_size: int, lo: int, hi: int
) -> bytes:
    """Linear bytes [lo, hi) of a NON-finalized stream from its
    on-disk data rows (large-stripe layout; absent extents read as
    zeros — the zero-extension recovery verifies against)."""
    if hi <= lo:
        return b""
    k = ctx.data_shards
    row_bytes = block_size * k
    out = bytearray(hi - lo)
    fds = {}
    try:
        pos = lo
        while pos < hi:
            s, rem = divmod(pos, row_bytes)
            row, col = divmod(rem, block_size)
            take = min(hi - pos, block_size - col)
            fd = fds.get(row)
            if fd is None:
                try:
                    fd = os.open(base + ctx.to_ext(row), os.O_RDONLY)
                except OSError:
                    fd = -1
                fds[row] = fd
            if fd >= 0:
                got = os.pread(fd, take, s * block_size + col)
                out[pos - lo : pos - lo + len(got)] = got
            pos += take
    finally:
        for fd in fds.values():
            if fd >= 0:
                os.close(fd)
    return bytes(out)


@dataclass
class StreamRecovery:
    """What :func:`recover_stream` established about one stream."""

    journal: StreamJournal
    head: int  # verified linear head (embedder-framed, parity-repaired)
    data: bytes  # linear bytes [0, head)
    parity_rewritten: int  # stripes whose parity was re-derived
    rolled_back: int  # bytes past `head` discarded


def recover_stream(
    base: str,
    ctx: ECContext | None = None,
    backend=None,
    frame_scan=None,
) -> StreamRecovery | None:
    """Crash-recover a NON-finalized stream.

    Reads the stripe-cursor journal (absent/torn -> None: nothing was
    ever durable under this cursor), bounds the head by the on-disk
    data extents, lets `frame_scan(data) -> head_bytes` trim to the
    embedder's record framing (None accepts the full extent), then
    re-derives parity for every covered stripe and REWRITES any that
    disagrees with the data — data is ground truth, so recovery never
    leaves a stripe whose parity disagrees with its bytes. Bytes past
    the verified head are rolled back (truncated).
    """
    j = load_stream_journal(base)
    if j is None:
        _recovered.inc(outcome="no_journal")
        return None
    if ctx is None:
        ctx = ECContext(j.data_shards, j.parity_shards)
    if (j.data_shards, j.parity_shards) != (ctx.data_shards, ctx.parity_shards):
        _recovered.inc(outcome="config_mismatch")
        return None
    block = j.block_size
    k, m = ctx.data_shards, ctx.parity_shards
    row_bytes = block * k
    hmax = _data_extent_head(base, ctx, block)
    data = read_stream_data(base, ctx, block, 0, hmax)
    head = hmax
    if frame_scan is not None:
        head = min(int(frame_scan(data)), hmax)
        data = data[:head]
    if head < j.durable:
        # fsync promised these bytes; the frames do not reach them —
        # real data loss (torn writes below the cursor), surfaced loud
        log.warning(
            "stream %s: durable cursor %d but only %d bytes recovered",
            base, j.durable, head,
        )
        _recovered.inc(outcome="data_lost")
    if backend is None:
        from .backend import CpuBackend

        backend = CpuBackend(ctx)
    gen = np.ascontiguousarray(
        np.asarray(backend.matrix, dtype=np.uint8)[k : k + m, :]
    )
    # re-derive parity for every covered stripe; rewrite mismatches
    rewritten = 0
    n_stripes = -(-head // row_bytes) if head else 0
    pfds = [
        os.open(base + ctx.to_ext(k + jx), os.O_RDWR | os.O_CREAT, 0o644)
        for jx in range(m)
    ]
    try:
        for s in range(n_stripes):
            lo = s * row_bytes
            seg = data[lo : lo + row_bytes]
            mat = np.zeros((k, block), dtype=np.uint8)
            flat = np.frombuffer(seg, dtype=np.uint8)
            mat.reshape(-1)[: len(flat)] = flat
            want = np.ascontiguousarray(
                backend.apply(gen, mat), dtype=np.uint8
            )
            ok = True
            for jx in range(m):
                have = os.pread(pfds[jx], block, s * block)
                have = have + b"\0" * (block - len(have))
                if have != want[jx].tobytes():
                    ok = False
                    break
            if not ok:
                for jx in range(m):
                    os.pwrite(pfds[jx], want[jx].tobytes(), s * block)
                rewritten += 1
        for fd in pfds:
            os.fsync(fd)
        # roll back data extents past the verified head: a partially
        # written row beyond `head` must not resurface as garbage on
        # the next recovery's extent scan
        rolled = max(hmax - head, 0)
        if rolled:
            for i in range(k):
                path = base + ctx.to_ext(i)
                s, rem = divmod(head, row_bytes)
                row, col = divmod(rem, block)
                try:
                    cur = os.path.getsize(path)
                except OSError:
                    continue
                keep = s * block + (
                    block if i < row else (col if i == row else 0)
                )
                if cur > keep:
                    with open(path, "rb+") as f:
                        f.truncate(keep)
    finally:
        for fd in pfds:
            os.close(fd)
    # the journal reflects the verified state going forward
    j2 = StreamJournal(
        uuid=j.uuid, meta=j.meta, durable=head,
        sealed=head // row_bytes, head=head,
        block_size=block, small_block_size=j.small_block_size,
        data_shards=k, parity_shards=m,
    )
    atomic_write(base + JOURNAL_SUFFIX, j2.to_bytes())
    if rewritten:
        _recovered.inc(rewritten, outcome="parity_rewritten")
    _recovered.inc(outcome="replayed" if head else "rolled_back")
    return StreamRecovery(
        journal=j, head=head, data=data,
        parity_rewritten=rewritten, rolled_back=max(hmax - head, 0),
    )
