"""EC scrub daemon: walk EC volumes, verify shard blocks against the
.ecsum sidecar incrementally, quarantine corrupt shards, trigger
rebuild — the background self-healing loop the reference runs as
ec_volume scrubbing plus shell-driven ec.rebuild.

Design points:

- incremental + resumable: verification walks (shard, block) positions
  with a persisted cursor (<base>.scrubpos), so a restart resumes
  mid-volume instead of rescanning from zero; a budget (`max_blocks`)
  lets the daemon time-slice huge volumes across wakeups. On a v2
  sidecar the walk runs at 64 KiB LEAF granularity: pauses resume
  mid-block, and a mismatch is pinned to its leaf (recorded in the
  report and in a .bad.leaves forensic marker next to the quarantine)
  instead of condemning an anonymous 16 MiB block. v1 sidecars keep
  the block walk.
- rate-limited: a token bucket caps read bandwidth so scrubbing never
  starves foreground traffic.
- repair at the finest granularity the evidence allows: rot pinned to
  specific 64 KiB leaves (v2 sidecar) with k verified-good local
  sources is reconstructed and patched IN PLACE under the write-ahead
  repair journal (ec/repair_journal.py — crash at any point leaves the
  shard fully-old or fully-new verified, never a mix); pending
  journals from a crashed repair are replayed/rolled back at pass
  start, and stale journal litter is TTL-swept at pass end.
- quarantine, never trust: a corrupt shard file that leaf repair
  cannot fix (size rot, v1 sidecar, too few sources) is renamed to
  <shard>.bad (kept for forensics) so it can NEVER be fed to
  Reed-Solomon; reads degrade to reconstruction until rebuild lands.
- fail closed: a malformed sidecar or >parity mismatches stops the
  self-heal (the sidecar itself is suspect) and reports refusal instead
  of "repairing" with untrustworthy inputs.
- rebuild runs under the unified retry policy (utils/retry.py) and an
  optional circuit breaker shared across volumes, so one dead disk
  doesn't turn the daemon into a rebuild-retry storm.
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass, field

from .. import faults
from ..utils import metrics as M
from ..utils import trace
from ..utils.crc import crc32c
from ..utils.fs import atomic_write, fsync_dir
from ..utils.glog import logger
from ..utils.retry import CircuitBreaker, CircuitOpenError, RetryError, RetryPolicy, retry_call
from .bitrot import BitrotError, BitrotProtection
from .context import QUARANTINE_SUFFIX, ECContext, ECError
from .rebuild import rebuild_ec_files
from .repair_journal import (
    apply_leaf_repair,
    leaf_verdict,
    patched_byte_ranges,
    reconstruct_leaves,
    recover_volume_journals,
    sweep_stale_journals,
)

log = logger("ec.scrub")

CURSOR_SUFFIX = ".scrubpos"

# Rebuilds are retried gently for TRANSIENT failures only (OSError: I/O
# flakes). ECError is deterministic (not-enough-shards, sidecar refusal)
# — retrying it just burns disk and poisons the breaker.
DEFAULT_REBUILD_POLICY = RetryPolicy(
    max_attempts=3, base_delay=0.2, max_delay=2.0, retry_on=(OSError,)
)


class RateLimiter:
    """Token-bucket byte limiter (injectable clock/sleep for tests)."""

    def __init__(
        self,
        bytes_per_sec: float,
        burst: float | None = None,
        clock=time.monotonic,
        sleep=time.sleep,
    ):
        self.rate = float(bytes_per_sec)
        self.burst = float(burst if burst is not None else bytes_per_sec)
        self._tokens = self.burst
        self._clock = clock
        self._sleep = sleep
        self._last = clock()
        self._lock = threading.Lock()

    def consume(self, n: int) -> None:
        if self.rate <= 0:  # unlimited
            return
        with self._lock:
            now = self._clock()
            self._tokens = min(self.burst, self._tokens + (now - self._last) * self.rate)
            self._last = now
            self._tokens -= n
            deficit = -self._tokens
        if deficit > 0:
            self._sleep(deficit / self.rate)


@dataclass
class ScrubCursor:
    """Resumable (shard, block[, leaf]) position, pinned to a sidecar
    generation so a re-encode restarts the walk. Carries the corrupt
    shards (and, on v2 sidecars, their corrupt leaf indices) found in
    earlier budget slices of the same pass — quarantine only happens
    once the pass completes, so mid-pass findings must survive a pause
    (and a process restart). `leaf` is the position WITHIN `block` when
    the sidecar records leaves, letting a budget pause land mid-block
    instead of rounding a 16 MiB block down to its start."""

    generation: int = 0
    shard: int = 0
    block: int = 0
    leaf: int = 0
    corrupt: list[int] = field(default_factory=list)
    corrupt_leaves: dict[int, list[int]] = field(default_factory=dict)

    @classmethod
    def load(cls, base: str) -> "ScrubCursor | None":
        try:
            with open(base + CURSOR_SUFFIX) as f:
                doc = json.load(f)
            return cls(
                generation=int(doc["generation"]),
                shard=int(doc["shard"]),
                block=int(doc["block"]),
                leaf=int(doc.get("leaf", 0)),
                corrupt=[int(x) for x in doc.get("corrupt", [])],
                corrupt_leaves={
                    int(k): [int(x) for x in v]
                    for k, v in doc.get("corrupt_leaves", {}).items()
                },
            )
        except (OSError, ValueError, KeyError):
            return None

    def save(self, base: str) -> None:
        atomic_write(
            base + CURSOR_SUFFIX,
            json.dumps(
                {
                    "generation": self.generation,
                    "shard": self.shard,
                    "block": self.block,
                    "leaf": self.leaf,
                    "corrupt": self.corrupt,
                    "corrupt_leaves": {
                        str(k): v for k, v in self.corrupt_leaves.items()
                    },
                }
            ).encode(),
        )

    @staticmethod
    def drop(base: str) -> None:
        try:
            os.unlink(base + CURSOR_SUFFIX)
        except OSError:
            pass


@dataclass
class ScrubReport:
    base: str
    complete: bool = False  # full pass finished (vs budget-paused)
    checked_blocks: int = 0
    checked_leaves: int = 0  # v2 sidecars: 64 KiB granules walked
    checked_bytes: int = 0
    checked_shards: list[int] = field(default_factory=list)  # ids walked
    corrupt_shards: list[int] = field(default_factory=list)
    # v2 sidecars: shard -> leaf indices that mismatched (the forensic
    # leaf-granular verdict; quarantine is still whole-shard, but the
    # .bad marker gains a .leaves sidecar naming the rotten 64 KiB)
    corrupt_leaves: dict[int, list[int]] = field(default_factory=dict)
    missing_shards: list[int] = field(default_factory=list)
    quarantined: list[str] = field(default_factory=list)
    rebuilt: list[int] = field(default_factory=list)
    aged_out: list[str] = field(default_factory=list)  # .bad files retired
    # Leaf-granular in-place repairs this pass (shard -> patched leaf
    # indices): the shard was NEVER quarantined — its rotten 64 KiB
    # leaves were reconstructed from k verified siblings and patched
    # under the repair journal (ec/repair_journal.py).
    leaf_repaired: dict[int, list[int]] = field(default_factory=dict)
    # Crash recovery at pass start: journals replayed (shard -> leaves)
    # and torn journals rolled back.
    journal_replayed: dict[int, list[int]] = field(default_factory=dict)
    journal_rolled_back: list[str] = field(default_factory=list)
    swept_journals: list[str] = field(default_factory=list)  # TTL litter
    refused: str = ""  # non-empty = fail-closed, nothing was touched

    @property
    def healthy(self) -> bool:
        return (
            self.complete
            and not self.corrupt_shards
            and not self.missing_shards
            and not self.refused
        )


def _quarantine(path: str) -> str:
    """Rename a corrupt shard out of Reed-Solomon's reach, atomically.
    An existing old quarantine of the same shard is replaced — the
    freshest corrupt bytes are the forensically interesting ones."""
    dest = path + QUARANTINE_SUFFIX
    os.replace(path, dest)
    try:
        # rename preserves the ORIGINAL shard's mtime; retention aging
        # must count from the quarantine event, so stamp it.
        os.utime(dest)
    except OSError:
        pass
    fsync_dir(path)
    return dest


def scrub_ec_volume(
    base: str,
    ctx: ECContext | None = None,
    *,
    backend=None,
    repair: bool = True,
    rate_limiter: RateLimiter | None = None,
    resumable: bool = True,
    max_blocks: float | None = None,
    rebuild_policy: RetryPolicy = DEFAULT_REBUILD_POLICY,
    breaker: CircuitBreaker | None = None,
    expected_shards: list[int] | None = None,
    on_quarantine=None,
    on_rebuilt=None,
    on_leaf_patched=None,
    bad_retention_s: float | None = None,
    journal_ttl_s: float | None = 86400.0,
    scheduler=None,
) -> ScrubReport:
    """One scrub pass (possibly budget-sliced) over one EC volume.

    Verifies every present shard's blocks against the .ecsum sidecar,
    quarantines mismatching shards (rename to .bad), and — when `repair`
    — regenerates quarantined/missing shards via rebuild_ec_files under
    the retry policy. `on_quarantine(shard_id, new_path)` and
    `on_rebuilt(shard_ids)` let a serving layer unmount/remount shards
    around the repair.

    `expected_shards` bounds which ABSENT shards count as missing (and
    so get rebuilt): on a balanced cluster a server legitimately holds a
    subset, and an absent shard usually lives on a peer — rebuilding it
    here would mint a duplicate copy the master never placed (and, below
    k local files, fail forever). Default None = all shards expected
    (single-node / full-set layouts, tests).

    `bad_retention_s` ages out quarantined <shard>.bad forensic copies:
    once a VERIFIED replacement shard has been published (this pass saw
    the shard present and clean, or just rebuilt it) and the quarantine
    file is older than the retention, it is deleted. None (default)
    keeps quarantines forever — retiring evidence is an operator
    opt-in.

    `on_leaf_patched(shard_id, byte_ranges)` fires whenever this pass
    changes a shard's bytes IN PLACE — a replayed crash journal at pass
    start, or a leaf-granular repair — so a serving layer can drop
    cached reconstructions over exactly those ranges (the fd itself
    stays valid: in-place patching never swaps the inode).

    `journal_ttl_s` retires stale/orphaned `<shard>.repair` journals
    older than the TTL at pass completion (valid pending journals are
    replayed at pass START, never swept); None disables the sweep.

    `scheduler` is the QueueScope whose placement/admission config the
    repair rebuild's scrub-class stream runs under (the daemon passes
    its Store's scope; None = the process-wide default).
    """
    report = ScrubReport(base=base)
    ecsum = base + ".ecsum"
    if not os.path.exists(ecsum):
        report.refused = "no .ecsum sidecar; cannot verify shards"
        return report
    try:
        prot = BitrotProtection.load(ecsum)
    except BitrotError as e:
        # Fail closed: an unreadable sidecar means no trustworthy ground
        # truth; rebuilding from unverified shards could launder rot.
        report.refused = f"sidecar malformed: {e}"
        return report
    if ctx is not None and prot.ctx != ctx:
        report.refused = f"sidecar ratio {prot.ctx} != expected {ctx}"
        return report
    ctx = prot.ctx

    # Crash recovery BEFORE any verification: a pending repair journal
    # is replayed (or a torn one rolled back) so the walk below sees
    # fully-old or fully-new bytes, never a half-applied patch. The
    # replay may flip sidecar leaf CRCs — `prot` is updated in place.
    rec = recover_volume_journals(base, ctx, prot)
    report.journal_replayed = rec["replayed"]
    report.journal_rolled_back = rec["rolled_back"]
    if on_leaf_patched is not None and prot.has_leaves:
        for sid, leaves in rec["replayed"].items():
            on_leaf_patched(sid, patched_byte_ranges(prot, sid, leaves))

    cursor = ScrubCursor.load(base) if resumable else None
    if cursor is None or cursor.generation != prot.generation:
        cursor = ScrubCursor(generation=prot.generation)
    # Verdicts carried from earlier budget slices of this pass; they are
    # re-verified at completion (see below) before any quarantine. A
    # shard whose slice PAUSED mid-walk carries only corrupt_leaves (it
    # never completed, so it is not in cursor.corrupt) — its eventual
    # condemnation rests on those stale leaves, so it needs the same
    # completion re-verify as a fully-carried verdict.
    carried = set(cursor.corrupt) | set(cursor.corrupt_leaves)
    report.corrupt_shards.extend(cursor.corrupt)
    report.corrupt_leaves.update(
        {s: list(ls) for s, ls in cursor.corrupt_leaves.items()}
    )

    want_local = (
        set(range(ctx.total)) if expected_shards is None else set(expected_shards)
    )
    budget = max_blocks if max_blocks is not None else float("inf")
    paused = False
    present_files = 0
    for shard_id in range(ctx.total):
        path = base + ctx.to_ext(shard_id)
        if not os.path.exists(path):
            if shard_id in want_local:
                report.missing_shards.append(shard_id)
            continue
        present_files += 1
        if shard_id < cursor.shard:
            report.checked_shards.append(shard_id)
            continue  # verified in an earlier slice of this pass
        # Finest granularity the sidecar records: v2 walks its 64 KiB
        # leaves (so a budget pause resumes MID-block and a mismatch is
        # pinned to one leaf), v1 keeps today's 16 MiB block walk. The
        # block budget stays denominated in blocks either way — a leaf
        # read consumes its byte-proportional fraction.
        gsize, gcrcs = prot.verify_granularity(shard_id)
        leafwise = gsize != prot.block_size
        per_block = prot.block_size // gsize if leafwise else 1
        granule_cost = gsize / prot.block_size if leafwise else 1
        start_g = 0
        if shard_id == cursor.shard:
            start_g = cursor.block * per_block + (
                cursor.leaf if leafwise else 0
            )
        corrupt = False
        try:
            if os.path.getsize(path) != prot.shard_sizes[shard_id]:
                corrupt = True  # truncation/growth is corruption
            else:
                with open(path, "rb") as f:
                    f.seek(start_g * gsize)
                    for g in range(start_g, len(gcrcs)):
                        if budget <= 0:
                            cursor.shard = shard_id
                            cursor.block, cursor.leaf = divmod(g, per_block)
                            if resumable:
                                cursor.save(base)
                            paused = True
                            break
                        block = f.read(gsize)
                        block = faults.mutate(
                            "ec.scrub.read_block", block, path=path, shard=shard_id
                        )
                        if rate_limiter is not None:
                            rate_limiter.consume(len(block))
                        if leafwise:
                            report.checked_leaves += 1
                            if (g + 1) % per_block == 0 or g + 1 == len(gcrcs):
                                report.checked_blocks += 1
                        else:
                            report.checked_blocks += 1
                        report.checked_bytes += len(block)
                        budget -= granule_cost
                        if crc32c(block) != gcrcs[g]:
                            corrupt = True
                            if not leafwise:
                                break  # v1: one verdict per shard
                            # Leafwise walks KEEP SCANNING on a mismatch:
                            # the .bad.leaves forensic marker (and any
                            # future partial repair) needs EVERY rotten
                            # leaf, not just the first — a corrupt shard
                            # costs one full read, which the v1 upfront
                            # verify paid anyway.
                            report.corrupt_leaves.setdefault(
                                shard_id, []
                            ).append(g)
                            cursor.corrupt_leaves.setdefault(
                                shard_id, []
                            ).append(g)
        except OSError:
            corrupt = True  # unreadable = untrustworthy RS input
        if paused:
            break
        if leafwise and cursor.corrupt_leaves.get(shard_id):
            # Bad leaves found in an EARLIER budget slice of this shard
            # still condemn it, even if this slice's resumed tail read
            # clean.
            corrupt = True
        if corrupt:
            report.corrupt_shards.append(shard_id)
            cursor.corrupt.append(shard_id)
        report.checked_shards.append(shard_id)
        cursor.shard, cursor.block, cursor.leaf = shard_id + 1, 0, 0
        # Persist progress only when a mid-pass pause is possible at all
        # (a block budget is set): an unbounded pass can never resume,
        # so per-shard fsync'd cursor writes would be pure I/O overhead
        # on every healthy pass of every volume.
        if resumable and max_blocks is not None:
            cursor.save(base)

    if paused:
        return report
    report.complete = True
    if resumable:
        ScrubCursor.drop(base)

    # Cursor-carried verdicts are stale across slices: the shard may
    # have been repaired (ec.scrub -repair, ec.rebuild) or removed since
    # its slice ran. Re-verify before trusting — quarantining a freshly
    # rebuilt good shard would undo a repair. The re-read honors the
    # same token bucket as the walk (carried shards can be multi-GB);
    # a leaf-pinned verdict re-reads ONLY the flagged 64 KiB leaves
    # instead of streaming the whole shard.
    def _leaves_still_bad(path: str, sid: int, leaves: list[int]) -> bool:
        if os.path.getsize(path) != prot.shard_sizes[sid]:
            return True
        lsize, lcrcs = prot.verify_granularity(sid)
        with open(path, "rb") as f:
            for li in leaves:
                f.seek(li * lsize)
                chunk = f.read(lsize)
                if rate_limiter is not None:
                    rate_limiter.consume(len(chunk))
                if li >= len(lcrcs) or crc32c(chunk) != lcrcs[li]:
                    return True
        return False

    for sid in [s for s in report.corrupt_shards if s in carried]:
        path = base + ctx.to_ext(sid)
        flagged = report.corrupt_leaves.get(sid)
        try:
            if flagged and prot.has_leaves:
                still_bad = _leaves_still_bad(path, sid, flagged)
                if not still_bad:
                    # Flagged leaves read clean = the shard was repaired
                    # since its slice — but the slice's walk stopped at
                    # the first bad leaf, so the rest of the shard was
                    # never seen. Full verify before CLEARING a verdict;
                    # the leaf fast path only short-circuits confirming
                    # one (still-rotten shards stay cheap).
                    still_bad = bool(
                        prot.verify_shard_file(
                            path,
                            sid,
                            on_block=(
                                rate_limiter.consume if rate_limiter else None
                            ),
                            stop_early=True,
                        )
                    )
            else:
                still_bad = bool(
                    prot.verify_shard_file(
                        path,
                        sid,
                        on_block=rate_limiter.consume if rate_limiter else None,
                        stop_early=True,
                    )
                )
        except FileNotFoundError:
            still_bad = False  # gone: nothing to quarantine; it is
            # already in missing_shards if this server should hold it
        except OSError:
            still_bad = True
        if not still_bad:
            report.corrupt_shards.remove(sid)
            report.corrupt_leaves.pop(sid, None)

    # ---- fail-closed gates mirror rebuild's verify-and-exclude rules ----
    if len(report.corrupt_shards) > ctx.parity_shards:
        # The sidecar is the suspect when "everything" mismatches; do NOT
        # quarantine good shards on its say-so.
        report.refused = (
            f"{len(report.corrupt_shards)} shards mismatch (> parity "
            f"{ctx.parity_shards}); sidecar suspect, refusing to quarantine"
        )
        return report

    # ---- leaf-granular in-place repair ----------------------------------
    # A shard whose rot is pinned to specific 64 KiB leaves (v2 sidecar)
    # and whose siblings still muster k verified-good sources is patched
    # IN PLACE under the repair journal instead of being quarantined:
    # ~k leaves of sibling I/O instead of a whole-shard rebuild, no
    # unmount/remount, no .bad forensic copy. Anything leaf repair
    # cannot fix (size rot, v1 sidecar, <k sources, reconstruction
    # refusal) falls through to the quarantine + rebuild path below.
    if repair and prot.has_leaves and report.corrupt_shards:
        good_sids = sorted(
            sid
            for sid in range(ctx.total)
            if sid not in report.corrupt_shards
            and os.path.exists(base + ctx.to_ext(sid))
        )
        for sid in [s for s in report.corrupt_shards if s in report.corrupt_leaves]:
            path = base + ctx.to_ext(sid)
            if len(good_sids) < ctx.data_shards:
                M.ec_leaf_repairs_total.inc(outcome="refused")
                break  # below the floor for every remaining shard
            # The walk's leaf set may be a stale slice verdict; pin the
            # repair to a FRESH full-leaf verdict (same cost as the
            # carried-verdict re-verify, and it also catches leaves that
            # rotted after the slice ran).
            fresh = leaf_verdict(
                path, sid, prot,
                on_block=rate_limiter.consume if rate_limiter else None,
            )
            if fresh is None:
                continue  # size rot / unreadable: not patchable in place
            if not fresh:
                # the shard verifies clean now (repaired since its
                # slice): clear the verdict rather than quarantine
                report.corrupt_shards.remove(sid)
                report.corrupt_leaves.pop(sid, None)
                continue

            def read_range(src: int, lo: int, size: int) -> bytes | None:
                try:
                    faults.fire(
                        "ec.repair.source_read", shard=src, offset=lo
                    )
                    with open(base + ctx.to_ext(src), "rb") as f:
                        f.seek(lo)
                        got = f.read(size)
                except (OSError, IOError):
                    return None
                return faults.mutate(
                    "ec.repair.source_read", got, shard=src, offset=lo
                )

            # Flight-recorder root per repair op (repair_fetch/
            # crc_verify/repair_patch stages land under it).
            sp = trace.start(
                "ec.leaf_repair",
                name=f"{os.path.basename(base)}.{sid:02d}",
                shard=sid, leaves=sorted(fresh),
            )
            try:
                with trace.activate(sp):
                    patches = reconstruct_leaves(
                        prot, ctx, sid, fresh, read_range, good_sids,
                        backend=backend,
                        span=sp,
                        on_bytes=(
                            rate_limiter.consume if rate_limiter else None
                        ),
                    )
                    apply_leaf_repair(
                        path, sid, prot, patches, ecsum_path=ecsum, span=sp
                    )
            except (ECError, OSError) as e:
                M.ec_leaf_repairs_total.inc(outcome="failed")
                log.warning(
                    "leaf repair of shard %d failed (%s); falling back to "
                    "quarantine", sid, e,
                )
                continue
            finally:
                trace.finish(sp)
            report.corrupt_shards.remove(sid)
            report.corrupt_leaves.pop(sid, None)
            report.leaf_repaired[sid] = sorted(fresh)
            M.ec_leaf_repairs_total.inc(outcome="repaired")
            log.warning(
                "leaf-repaired shard %d in place (leaves %s); quarantine "
                "avoided", sid, sorted(fresh),
            )
            if on_leaf_patched is not None:
                on_leaf_patched(sid, patched_byte_ranges(prot, sid, fresh))

    present_good = present_files - len(report.corrupt_shards)
    if report.corrupt_shards and present_good < ctx.data_shards:
        report.refused = (
            f"only {present_good} verified-good shards (need "
            f"{ctx.data_shards}); refusing to quarantine below rebuild floor"
        )
        return report

    for shard_id in report.corrupt_shards:
        path = base + ctx.to_ext(shard_id)
        try:
            dest = _quarantine(path)
        except FileNotFoundError:
            continue  # vanished since re-verify; missing-walk owns it now
        report.quarantined.append(dest)
        leaves = report.corrupt_leaves.get(shard_id)
        if leaves and prot.has_leaves:
            # Leaf-granular quarantine marker: which 64 KiB regions of
            # the .bad forensic copy actually mismatched — an operator
            # (or a future partial-repair) inspects those offsets
            # instead of diffing a multi-GB shard.
            try:
                atomic_write(
                    dest + ".leaves",
                    json.dumps(
                        {"leaf_size": prot.leaf_size, "leaves": sorted(leaves)}
                    ).encode(),
                )
            except OSError:  # forensics must not block the repair
                pass
        log.warning(
            "quarantined corrupt shard %s -> %s%s", path, dest,
            f" (leaves {sorted(leaves)})" if leaves else "",
        )
        if on_quarantine is not None:
            on_quarantine(shard_id, dest)

    want_rebuild = sorted(set(report.corrupt_shards) | set(report.missing_shards))
    if repair and want_rebuild:
        def attempt() -> list[int]:
            # Scrub-initiated repair is the LOWEST class on the shared
            # device queue: it yields the chip to foreground serving
            # AND to operator/decode-driven recovery rebuilds, keeping
            # only its configured minimum share under contention.
            return rebuild_ec_files(
                base, ctx, backend=backend, only_shards=want_rebuild,
                priority="scrub", scheduler=scheduler,
            )

        try:
            if breaker is not None:
                rebuilt = breaker.call(
                    lambda: retry_call(
                        attempt, rebuild_policy, describe=f"rebuild {base}"
                    )
                )
            else:
                rebuilt = retry_call(
                    attempt, rebuild_policy, describe=f"rebuild {base}"
                )
            report.rebuilt = rebuilt
            if on_rebuilt is not None and rebuilt:
                on_rebuilt(rebuilt)
        except CircuitOpenError as e:
            report.refused = f"rebuild skipped: {e}"
        except (RetryError, ECError) as e:
            report.refused = f"rebuild failed: {e}"

    # ---- age out retired quarantine files -------------------------------
    # A .bad forensic copy is eligible once a verified replacement is
    # published: either this pass walked the live shard clean, or the
    # rebuild above just regenerated it (rebuild_ec_files verifies
    # against the sidecar before renaming). Eligibility is never
    # inferred from absence — a shard neither verified nor rebuilt
    # keeps its quarantine.
    if bad_retention_s is not None and not report.refused:
        # A leaf repair IS a verified replacement (the patched leaves
        # were CRC-verified before publish), so a stale quarantine of
        # the same shard — left by an earlier whole-shard pass — ages
        # out exactly like one retired by a rebuild.
        verified_now = (
            (set(report.checked_shards) - set(report.corrupt_shards))
            | set(report.rebuilt)
            | set(report.leaf_repaired)
        )
        now = time.time()
        for sid in sorted(verified_now):
            bad_path = base + ctx.to_ext(sid) + QUARANTINE_SUFFIX
            try:
                age = now - os.path.getmtime(bad_path)
            except OSError:
                # no .bad — but an ORPHANED .bad.leaves forensic marker
                # (its .bad already retired or manually removed) must
                # not outlive the retention either
                lpath = bad_path + ".leaves"
                try:
                    lage = now - os.path.getmtime(lpath)
                except OSError:
                    continue  # no quarantine artifacts for this shard
                if lage < bad_retention_s:
                    continue
                try:
                    os.unlink(lpath)
                except OSError:
                    continue
                fsync_dir(lpath)
                report.aged_out.append(lpath)
                log.info("retired orphaned leaf marker %s", lpath)
                continue
            if age < bad_retention_s:
                continue
            try:
                os.unlink(bad_path)
            except OSError:
                continue
            try:  # the leaf forensic marker retires with its .bad
                os.unlink(bad_path + ".leaves")
            except OSError:
                pass
            fsync_dir(bad_path)
            report.aged_out.append(bad_path)
            log.info("retired quarantine %s (age %.0fs)", bad_path, age)

    # ---- sweep stale repair-journal litter ------------------------------
    # Valid pending journals were replayed at pass start; what's left is
    # stale intents (volume re-encoded) or orphans (shard gone) — kept
    # for forensics until the TTL, like PR 6's stale-staging sweep.
    if journal_ttl_s is not None:
        report.swept_journals = sweep_stale_journals(base, ctx, journal_ttl_s)
    return report


class ScrubDaemon:
    """Background scrub loop over a Store's mounted EC volumes.

    Walks every EC volume each `interval`, slicing work by `max_blocks`
    per volume per wakeup. Quarantine/rebuild events unmount and remount
    the affected shard on the live EcVolume so reads degrade to
    reconstruction (never a stale fd on a renamed file) and pick the
    regenerated shard back up once it verifies.
    """

    def __init__(
        self,
        store,
        interval: float = 3600.0,
        bytes_per_sec: float = 64 << 20,
        max_blocks_per_volume: float | None = None,
        repair: bool = True,
        breaker: CircuitBreaker | None = None,
        backend=None,
        bad_retention_s: float | None = None,
        journal_ttl_s: float | None = 86400.0,
    ):
        self.store = store
        self.interval = interval
        self.repair = repair
        self.backend = backend
        self.bad_retention_s = bad_retention_s
        self.journal_ttl_s = journal_ttl_s
        self.limiter = RateLimiter(bytes_per_sec)
        self.max_blocks = max_blocks_per_volume
        # One breaker PER VOLUME: a permanently-unrebuildable volume
        # (e.g. a subset holder below k local files) must not starve
        # every other volume's repair on this server. `breaker`, when
        # given, is the template whose thresholds new ones copy.
        self._breaker_template = breaker or CircuitBreaker(
            failure_threshold=3, reset_timeout=300.0
        )
        self.breakers: dict[int, CircuitBreaker] = {}
        self.reports: dict[int, ScrubReport] = {}  # vid -> last report
        self.passes = 0
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="ec-scrub", daemon=True
        )

    # ------------------------------------------------------------ control

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread.is_alive():
            self._thread.join(timeout=5)

    def kick(self) -> None:
        """Request an immediate pass (ops hook / tests)."""
        self._wake.set()

    def breaker_for(self, vid: int) -> CircuitBreaker:
        b = self.breakers.get(vid)
        if b is None:
            t = self._breaker_template
            b = CircuitBreaker(
                failure_threshold=t.failure_threshold,
                reset_timeout=t.reset_timeout,
            )
            self.breakers[vid] = b
        return b

    # -------------------------------------------------------------- loop

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.scrub_once()
            except Exception as e:  # pragma: no cover - daemon must survive
                log.error("scrub pass failed: %s", e)
            self.passes += 1
            self._wake.wait(self.interval)
            self._wake.clear()

    def scrub_once(self) -> dict[int, ScrubReport]:
        """One pass over every mounted EC volume; returns vid->report."""
        out: dict[int, ScrubReport] = {}
        for loc in self.store.locations:
            for vid, ev in list(loc.ec_volumes.items()):
                if self._stop.is_set():
                    return out
                # This server's legitimate shard set = served + on-disk
                # quarantined (EcVolume.legitimate_shards): a shard
                # quarantined+unmounted last pass whose rebuild then
                # failed stays on the repair list instead of vanishing
                # from the mounted set and being reported healthy. An
                # absent shard outside this set lives on a peer; a local
                # rebuild of it would mint a duplicate copy the master
                # never placed (and below k local files, fail every
                # pass and wedge the shared breaker).
                mounted = set(ev.legitimate_shards())
                report = scrub_ec_volume(
                    ev.base,
                    ev.ctx,
                    backend=self.backend,
                    repair=self.repair,
                    rate_limiter=self.limiter,
                    max_blocks=self.max_blocks,
                    breaker=self.breaker_for(vid),
                    expected_shards=sorted(mounted),
                    bad_retention_s=self.bad_retention_s,
                    journal_ttl_s=self.journal_ttl_s,
                    # the Store's own scheduler scope (per-tenant
                    # placement/shares); falls back to the process-wide
                    # default for bare stores
                    scheduler=getattr(self.store, "ec_scheduler", None),
                    # Unmount BEFORE rebuild: the serving fd still points
                    # at the renamed .bad inode and would happily serve
                    # rot; degraded reads reconstruct meanwhile.
                    on_quarantine=lambda sid, dest, ev=ev: ev.unmount_shards([sid]),
                    # Remount only what this server served going in —
                    # rebuild may have regenerated peers' shards too.
                    on_rebuilt=lambda sids, ev=ev, m=mounted: ev.reopen_shards(
                        [s for s in sids if s in m]
                    ),
                    # In-place patches (journal replay, leaf repair)
                    # keep the inode — no fd swap, but any cached
                    # reconstruction over the patched bytes is stale.
                    on_leaf_patched=lambda sid, ranges, ev=ev: (
                        ev.invalidate_shard_ranges(sid, ranges)
                    ),
                )
                out[vid] = report
                self.reports[vid] = report
                if report.refused:
                    log.warning("scrub vol %d refused: %s", vid, report.refused)
                elif report.quarantined or report.rebuilt or report.leaf_repaired:
                    log.warning(
                        "scrub vol %d: quarantined=%s rebuilt=%s "
                        "leaf_repaired=%s",
                        vid, report.quarantined, report.rebuilt,
                        report.leaf_repaired,
                    )
        return out
