"""Shared fleet-maintenance decision logic for EC volumes.

The shell's operator commands (``ec.scrub`` / ``ec.rebuild``) and the
maintenance worker's fleet tasks (``ec_scrub`` / ``ec_rebuild``) walk
the same holder map and make the same per-holder verdicts. The decision
kernel lives here exactly once so the two paths cannot drift: what
counts as missing, what counts as hurt, and when a holder is
quarantined-but-unrebuildable (< k verified-good local shards — the
case per-server repair can never fix and a peer-fetch rebuild must).
"""

from __future__ import annotations

__all__ = [
    "grpc_addr",
    "holder_maps",
    "holder_scrub_facts",
    "pick_rebuild_holder",
]


def grpc_addr(loc) -> str:
    """Location (public `url` host:port + `grpc_port`) -> the holder's
    gRPC address."""
    return f"{loc.url.split(':')[0]}:{loc.grpc_port}"


def holder_maps(shard_locs) -> tuple[dict, dict]:
    """Invert the master's ``lookup_ec`` map into per-holder views:
    ``by_url`` (url -> set of advertised shard ids) and ``loc_by_url``
    (url -> a Location carrying the grpc port)."""
    by_url: dict[str, set[int]] = {}
    loc_by_url: dict[str, object] = {}
    for sid, locs in shard_locs.items():
        for loc in locs:
            by_url.setdefault(loc.url, set()).add(int(sid))
            loc_by_url[loc.url] = loc
    return by_url, loc_by_url


def holder_scrub_facts(resp, advertised, data_shards: int) -> dict:
    """Fold one successful ``ScrubEcVolume`` response into the verdict
    both the shell and the fleet worker act on.

    ``missing`` is the real per-sid set difference (shards the master
    lists on this holder whose files the scrub did not find). A server
    that checked ZERO shards genuinely has no shard files — total local
    loss — so every advertised shard is missing; only a legacy server
    (``checked > 0`` with no ``checked_shards``) degrades to the count
    comparison in ``legacy_gone`` because per-sid ids are unknowable.

    ``unrebuildable``: hurt in any way AND fewer than ``data_shards``
    verified-good local shards, so local repair can never fix it.
    """
    advertised = set(int(s) for s in advertised)
    bad = sorted(int(x) for x in resp.bad_shards)
    quarantined = sorted(int(x) for x in resp.quarantined_shards)
    checked = int(resp.checked)
    if resp.checked_shards or checked == 0:
        missing = sorted(advertised - {int(x) for x in resp.checked_shards})
        legacy_gone = 0
    else:
        missing = []
        legacy_gone = max(0, len(advertised) - checked)
    hurt = bool(bad or missing or legacy_gone or quarantined)
    good = checked - len(bad)
    return {
        "checked": checked,
        "bad": bad,
        "missing": missing,
        "legacy_gone": legacy_gone,
        "quarantined": quarantined,
        "hurt": hurt,
        "good": good,
        "unrebuildable": hurt and good < data_shards,
    }


def pick_rebuild_holder(by_url: dict, smallest: bool = False) -> str:
    """The rebuild-holder heuristic: the BIGGEST holder (most local
    sources) for a local rebuild, the SMALLEST (the subset holder a
    local rebuild refuses on) for ``fromPeers``. Deterministic: ties
    break on the url."""
    key = lambda u: (len(by_url[u]), u)  # noqa: E731
    return min(by_url, key=key) if smallest else max(by_url, key=key)
