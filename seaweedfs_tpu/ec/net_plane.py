"""Native shard byte plane: the network half of the zero-copy EC path.

PR 10 made the LOCAL byte path native; every network byte still
round-tripped through Python — `VolumeEcShardRead` serializes pooled
buffers into Python gRPC messages, and peer-fetch rebuild re-buffers
fetched ranges through `bytes`. This module is the wire twin of that
RPC (the analog of the reference architecture's native RDMA data-plane
engine, PAPER.md layer map): a tiny TCP sidecar next to each volume
server's gRPC port that serves EC shard byte ranges with

- **native egress**: `sn_send_file` splices the shard fd straight into
  the socket (sendfile(2), kernel-to-kernel, GIL released) — Python
  touches only the 38-byte request header (+ trace metadata);
- **native ingress**: the client lands streams DIRECTLY in caller-owned
  pooled 4096-aligned buffers (`sn_recv_into`) with the fused
  granule-CRC32C rolling during the copy-in, so the sidecar verify in
  ec/peer_rebuild.py costs no extra byte pass.

The plane is an ACCELERATOR, not a dependency: gRPC `VolumeEcShardRead`
remains the canonical, generation-fenced transport and the
bit-identical fallback. Fallback routing (the same contract as PR 10's
local plane):

- `SEAWEED_EC_NATIVE=0` or a missing .so: callers never take this path
  (ec/native_io.enabled() is the single gate);
- an ARMED fault registry: the server answers through the Python
  pread/sendall path so byte-mutating chaos has materialized bytes to
  chew on, and peer_rebuild routes its client side to the Python fetch
  — the PR 6/8/11 chaos contracts hold unchanged;
- a peer without the sidecar (older build, port collision): the client
  memoizes the refusal and raises :class:`NetPlaneUnavailable`, which
  peer_rebuild turns into a per-stream fallback to the gRPC fetch.

Protocol (little-endian, persistent connection, one in-flight request
per connection):

    request:  b"SWNP" | u32 volume_id | u32 shard_id | u64 generation
              | u64 offset | u64 size | u16 meta_len       (38 bytes)
              | meta_len bytes of "key\\tvalue" lines — the SAME
              x-sw-trace-id / x-sw-parent-span / x-request-id metadata
              the gRPC stream carries, so a peer-fetch over the native
              plane still lands in the dispatcher's ONE trace (the
              PR 7 cross-RPC contract holds transport-independently)
    response: u8 status | u64 n | n bytes
              status 0 = ok (n = payload length, may be < size at EOF);
              status 1 = error (n = UTF-8 message length)

The sidecar listens on ``grpc_port + NET_PLANE_PORT_OFFSET`` so peers
derive its address from the holder map's gRPC address without any new
topology plumbing; a dead port is just a memoized fallback.
"""

from __future__ import annotations

import os
import socket
import struct
import threading
import time

import numpy as np

from .. import faults
from ..utils import metrics as M
from ..utils import request_id as _rid
from ..utils import trace
from ..utils.glog import logger

log = logger("ec.netplane")

MAGIC = b"SWNP"
# magic, volume, shard, gen, offset, size, meta_len
_REQ = struct.Struct("<4sIIQQQH")
_RESP = struct.Struct("<BQ")      # status, n
NET_PLANE_PORT_OFFSET = 10000     # net plane port = grpc port + this

_SEND_CHUNK = 1 << 20             # python-plane egress chunking
_MAX_REQUEST = 1 << 32
_MAX_META = 4096


def _encode_meta() -> bytes:
    """The active request-id / trace context as a metadata blob —
    exactly what trace.grpc_metadata() would put on the RPC."""
    md = trace.grpc_metadata()
    if not md:
        return b""
    blob = "\n".join(f"{k}\t{v}" for k, v in md).encode()
    return blob[:_MAX_META]


def _decode_meta(blob: bytes) -> dict:
    md: dict = {}
    for line in blob.decode(errors="replace").splitlines():
        k, _, v = line.partition("\t")
        if k and v:
            md[k.lower()] = v
    return md


class NetPlaneError(Exception):
    """Transport/protocol failure on an established plane connection —
    transient from the caller's point of view (retry or fall back)."""


class NetPlaneUnavailable(Exception):
    """The peer serves no shard net plane (connect refused / bad
    protocol greeting). Memoized per peer; callers route the stream to
    the gRPC fetch instead."""


def derive_port(grpc_port: int) -> int:
    """Net-plane port derived from a gRPC port — the SAME pure function
    on the serving and connecting side, so no topology plumbing is
    needed. High ephemeral gRPC ports wrap back into the valid range
    deterministically; a collision there just fails the bind (server:
    plane disabled with one warning) or the connect (client: memoized
    gRPC fallback)."""
    p = grpc_port + NET_PLANE_PORT_OFFSET
    if p > 65535:
        p = 1024 + (p % 64512)
    return p


def net_addr(grpc_peer: str) -> tuple[str, int]:
    """Net-plane (host, port) derived from a holder-map gRPC address."""
    host, _, port = grpc_peer.rpartition(":")
    return host, derive_port(int(port))


def _native_mod():
    try:
        from ..utils import native

        return native
    except ImportError:
        return None


def egress_native() -> bool:
    """True when the server side should splice with sendfile: native
    plane on AND the fault registry disarmed (byte-mutating chaos needs
    materialized bytes — the armed registry routes to the Python
    egress, same contract as the local plane)."""
    from . import native_io

    return native_io.enabled() and not faults.active()


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise NetPlaneError("connection closed mid-message")
        got += r
    return bytes(buf)


# ---------------------------------------------------------------------------
# Server
# ---------------------------------------------------------------------------


class ShardNetPlane:
    """TCP sidecar serving EC shard byte ranges — the native twin of the
    ``VolumeEcShardRead`` gRPC stream, sharing its semantics (generation
    fence, short-read-at-EOF torn-stream contract, the
    ``server.ec_shard_read`` chaos point) but not its byte path.

    ``resolve(volume_id, shard_id, generation) -> (fd, size)`` supplies
    the shard fd and its byte size; it raises :class:`NetPlaneError`
    with the refusal message (not mounted / stale generation / shard
    not local). The server never closes resolved fds — they belong to
    the store's mounted EC volume, exactly like the gRPC servicer.
    """

    def __init__(self, ip: str, port: int, resolve,
                 request_timeout: float = 60.0, server_label: str = ""):
        self.resolve = resolve
        self.request_timeout = request_timeout
        self.server_label = server_label
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((ip, port))
        self._sock.listen(128)
        self.ip, self.port = self._sock.getsockname()[:2]
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="shard-net-plane"
        )
        self._conns: set[socket.socket] = set()
        self._conns_lock = threading.Lock()
        self.requests = 0
        self.sendfile_bytes = 0
        self.python_bytes = 0

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
        self._thread.join(timeout=2.0)

    # ------------------------------------------------------------ serving

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # listener closed
            with self._conns_lock:
                self._conns.add(conn)
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            ).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            conn.settimeout(self.request_timeout)
            while not self._stop.is_set():
                try:
                    hdr = _recv_exact(conn, _REQ.size)
                except (NetPlaneError, OSError):
                    return  # client went away between requests
                magic, vid, sid, gen, off, size, mlen = _REQ.unpack(hdr)
                if magic != MAGIC or size > _MAX_REQUEST or mlen > _MAX_META:
                    return  # not our protocol: drop the connection
                try:
                    md = _decode_meta(_recv_exact(conn, mlen)) if mlen else {}
                except (NetPlaneError, OSError):
                    return
                self.requests += 1
                # Observability parity with the gRPC stream: adopt the
                # caller's request id + trace context and open the SAME
                # rpc.ec_shard_read span — a peer-fetch heal stays ONE
                # trace whichever transport carried the bytes.
                _rid.ensure(md.get(trace.REQUEST_ID_KEY))
                sp = trace.start_from_metadata(
                    "rpc.ec_shard_read", md, server=self.server_label,
                    volume=vid, shard=sid, offset=off, size=size,
                    plane="native",
                )
                t0 = time.perf_counter()
                try:
                    ok = self._serve_one(conn, vid, sid, gen, off, size)
                finally:
                    trace.add_stage(sp, "stream", time.perf_counter() - t0)
                    trace.finish(sp)
                if not ok:
                    return
        finally:
            with self._conns_lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _error(self, conn, msg: str) -> bool:
        body = msg.encode(errors="replace")
        try:
            conn.sendall(_RESP.pack(1, len(body)) + body)
            return True
        except OSError:
            return False

    def _serve_one(self, conn, vid, sid, gen, off, size) -> bool:
        """Serve one range request; False = connection must close."""
        try:
            # Same named chaos point as the gRPC servicer: a raised
            # IOError is a refused stream (client replans); a mutate is
            # applied on the PYTHON egress below — the armed registry
            # routes there, never through sendfile.
            faults.fire("server.ec_shard_read", volume=vid, shard=sid)
        except IOError as e:
            return self._error(conn, str(e))
        try:
            fd, fsize = self.resolve(vid, sid, gen)
        except NetPlaneError as e:
            return self._error(conn, str(e))
        n = max(0, min(size, fsize - off)) if off < fsize else 0
        try:
            conn.sendall(_RESP.pack(0, n))
        except OSError:
            return False
        if n == 0:
            return True
        native = _native_mod() if egress_native() else None
        if native is not None:
            try:
                sent = native.send_file(
                    conn.fileno(), fd, off, n,
                    timeout_ms=int(self.request_timeout * 1000),
                )
            except OSError:
                return False  # peer died mid-splice: header already out
            self.sendfile_bytes += sent
            M.net_bytes_sent_total.inc(sent, plane="native")
            return sent == n
        # Python egress (fallback plane / armed registry): pread ->
        # mutate -> sendall, byte-identical to the gRPC stream's
        # chunking. A mutate that shrinks the chunk tears the stream,
        # which the client must catch — never served silently.
        remaining, o = n, off
        while remaining > 0:
            chunk = os.pread(fd, min(_SEND_CHUNK, remaining), o)
            if not chunk:
                break
            orig = len(chunk)
            chunk = faults.mutate(
                "server.ec_shard_read", chunk, volume=vid, shard=sid, offset=o
            )
            M.net_bytes_copied_total.inc(orig, plane="python")
            try:
                if chunk:
                    conn.sendall(chunk)
            except OSError:
                return False
            self.python_bytes += len(chunk)
            M.net_bytes_sent_total.inc(len(chunk), plane="python")
            if len(chunk) < orig:
                return False  # torn stream: connection is dead
            o += orig
            remaining -= orig
        return remaining == 0

    def status(self) -> dict:
        """Sidecar state for /status and /debug/gateway surfaces."""
        return {
            "port": self.port,
            "requests": self.requests,
            "sendfile_bytes": self.sendfile_bytes,
            "python_bytes": self.python_bytes,
        }


# ---------------------------------------------------------------------------
# Client
# ---------------------------------------------------------------------------


class NetPlaneClient:
    """Pooled client connections to peers' shard net planes, landing
    payload bytes straight in caller buffers (``sn_recv_into``) with the
    fused granule CRC rolled during the copy-in.

    One cached connection per peer address (requests on one address are
    serialized — peer-fetch streams one shard from a given holder at a
    time, so the lock is uncontended on the rebuild path). A peer whose
    plane port refuses the connect is memoized and every later call
    raises :class:`NetPlaneUnavailable` immediately.
    """

    def __init__(self, timeout: float = 30.0, connect_timeout: float = 2.0):
        self.timeout = timeout
        self.connect_timeout = connect_timeout
        self._conns: dict[tuple[str, int], socket.socket] = {}
        self._locks: dict[tuple[str, int], threading.Lock] = {}
        self._no_plane: set[tuple[str, int]] = set()
        self._lock = threading.Lock()

    def close(self) -> None:
        with self._lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for c in conns:
            try:
                c.close()
            except OSError:
                pass

    def _addr_lock(self, addr) -> threading.Lock:
        with self._lock:
            return self._locks.setdefault(addr, threading.Lock())

    def _conn(self, addr) -> socket.socket:
        with self._lock:
            if addr in self._no_plane:
                raise NetPlaneUnavailable(f"{addr[0]}:{addr[1]}")
            s = self._conns.get(addr)
        if s is not None:
            return s
        try:
            s = socket.create_connection(addr, timeout=self.connect_timeout)
        except OSError as e:
            with self._lock:
                self._no_plane.add(addr)
            raise NetPlaneUnavailable(f"{addr[0]}:{addr[1]}: {e}") from e
        s.settimeout(self.timeout)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        with self._lock:
            self._conns[addr] = s
        return s

    def _drop(self, addr) -> None:
        with self._lock:
            s = self._conns.pop(addr, None)
        if s is not None:
            try:
                s.close()
            except OSError:
                pass

    def _request(self, addr, vid, sid, gen, off, size) -> socket.socket:
        """Send one range request, parse the response header, return the
        connection positioned at the payload (exactly `size` bytes —
        a server-side clamp or refusal raises)."""
        s = self._conn(addr)
        meta = _encode_meta()
        try:
            s.sendall(
                _REQ.pack(MAGIC, vid, sid, gen, off, size, len(meta)) + meta
            )
            head = _recv_exact(s, _RESP.size)
        except (OSError, NetPlaneError) as e:
            self._drop(addr)
            raise NetPlaneError(f"{addr}: {e}") from e
        status, n = _RESP.unpack(head)
        if status != 0:
            try:
                msg = _recv_exact(s, n).decode(errors="replace")
            except (OSError, NetPlaneError):
                self._drop(addr)
                msg = "(error body lost)"
            raise NetPlaneError(f"{addr}: {msg}")
        if n != size:
            # EOF clamp — the gRPC stream's short read. The connection
            # still holds n payload bytes; cheaper to drop it than to
            # drain and resync.
            self._drop(addr)
            raise NetPlaneError(f"{addr}: short stream {n}/{size}")
        return s

    def read_into(
        self,
        addr: tuple[str, int],
        vid: int,
        sid: int,
        gen: int,
        off: int,
        size: int,
        dst: np.ndarray,
        *,
        granule: int = 0,
    ) -> np.ndarray | None:
        """Land `size` bytes of shard `sid` @`off` DIRECTLY in `dst`
        (1-D C-contiguous uint8 view of a pooled aligned buffer). With
        granule > 0 returns the granule CRCs rolled during the copy-in
        (completed granules plus the partial tail) as a u32 ndarray —
        the caller compares them against the .ecsum sidecar with no
        extra pass over the bytes."""
        native = _native_mod()
        with self._addr_lock(addr):
            return self._read_into_locked(
                addr, vid, sid, gen, off, size, dst,
                granule=granule, native=native,
            )

    def _read_into_locked(
        self, addr, vid, sid, gen, off, size, dst, *, granule, native
    ):
        s = self._request(addr, vid, sid, gen, off, size)
        try:
            if native is not None:
                crc_state = np.zeros(1, np.uint32)
                filled = np.zeros(1, np.uint64)
                max_out = (size // granule + 2) if granule else 1
                out_crcs = np.zeros(max_out, np.uint32)
                out_counts = np.zeros(1, np.int32)
                got = native.recv_into(
                    s.fileno(), dst, size,
                    timeout_ms=int(self.timeout * 1000),
                    granule=granule, crc_state=crc_state,
                    filled_state=filled, out_crcs=out_crcs,
                    out_counts=out_counts,
                )
                if got != size:
                    raise NetPlaneError(
                        f"{addr}: torn stream {got}/{size}"
                    )
                M.net_bytes_received_total.inc(got, plane="native")
                if not granule:
                    return None
                crcs = list(out_crcs[: int(out_counts[0])])
                if size % granule:
                    crcs.append(int(crc_state[0]))
                return np.asarray(crcs, dtype=np.uint32)
            # Python landing (no .so): same buffer, Python recv loop.
            view = memoryview(dst)[:size]
            got = 0
            while got < size:
                r = s.recv_into(view[got:], size - got)
                if r == 0:
                    raise NetPlaneError(f"{addr}: torn stream {got}/{size}")
                got += r
            M.net_bytes_received_total.inc(got, plane="python")
            if not granule:
                return None
            from ..utils.crc import crc32c as _crc

            return np.array(
                [
                    _crc(dst[i : min(i + granule, size)])
                    for i in range(0, size, granule)
                ],
                dtype=np.uint32,
            )
        except (OSError, NetPlaneError) as e:
            self._drop(addr)
            if isinstance(e, NetPlaneError):
                raise
            raise NetPlaneError(f"{addr}: {e}") from e

    def read_bytes(
        self, addr, vid, sid, gen, off, size
    ) -> bytes:
        """Python-plane fetch over the same wire: materializes the
        payload as `bytes` (counted against the python plane's
        copied/received totals). Used by granule re-reads and by the
        bench's same-transport Python-plane comparison."""
        with self._addr_lock(addr):
            s = self._request(addr, vid, sid, gen, off, size)
            try:
                data = _recv_exact(s, size)
            except (OSError, NetPlaneError) as e:
                self._drop(addr)
                raise NetPlaneError(f"{addr}: {e}") from e
        M.net_bytes_received_total.inc(size, plane="python")
        M.net_bytes_copied_total.inc(size, plane="python")
        return data


def make_fetch_into(client: NetPlaneClient, vid: int, generation: int,
                    addr_of=net_addr):
    """Adapt a :class:`NetPlaneClient` to peer_rebuild's injected
    ``fetch_into(peer, sid, off, size, dst, granule)`` transport,
    translating plane exceptions into the rebuild's retry/fallback
    vocabulary (NetPlaneError -> PeerFetchTransient, NetPlaneUnavailable
    -> PeerPlaneUnavailable)."""
    from .peer_rebuild import PeerFetchTransient, PeerPlaneUnavailable

    def fetch_into(peer, sid, off, size, dst, granule):
        try:
            return client.read_into(
                addr_of(peer), vid, sid, generation, off, size, dst,
                granule=granule,
            )
        except NetPlaneUnavailable as e:
            raise PeerPlaneUnavailable(str(e)) from e
        except NetPlaneError as e:
            raise PeerFetchTransient(str(e)) from e

    return fetch_into
