"""Native shard byte plane: the network half of the zero-copy EC path.

PR 10 made the LOCAL byte path native; every network byte still
round-tripped through Python — `VolumeEcShardRead` serializes pooled
buffers into Python gRPC messages, and peer-fetch rebuild re-buffers
fetched ranges through `bytes`. This module is the wire twin of that
RPC (the analog of the reference architecture's native RDMA data-plane
engine, PAPER.md layer map): a tiny TCP sidecar next to each volume
server's gRPC port that serves EC shard byte ranges with

- **native egress**: `sn_send_file` splices the shard fd straight into
  the socket (sendfile(2), kernel-to-kernel, GIL released) — Python
  touches only the 38-byte request header (+ trace metadata);
- **native ingress**: the client lands streams DIRECTLY in caller-owned
  pooled 4096-aligned buffers (`sn_recv_into`) with the fused
  granule-CRC32C rolling during the copy-in, so the sidecar verify in
  ec/peer_rebuild.py costs no extra byte pass.

The plane is an ACCELERATOR, not a dependency: gRPC `VolumeEcShardRead`
remains the canonical, generation-fenced transport and the
bit-identical fallback. Fallback routing (the same contract as PR 10's
local plane):

- `SEAWEED_EC_NATIVE=0` or a missing .so: callers never take this path
  (ec/native_io.enabled() is the single gate);
- an ARMED fault registry: the server answers through the Python
  pread/sendall path so byte-mutating chaos has materialized bytes to
  chew on, and peer_rebuild routes its client side to the Python fetch
  — the PR 6/8/11 chaos contracts hold unchanged;
- a peer without the sidecar (older build, port collision): the client
  memoizes the refusal and raises :class:`NetPlaneUnavailable`, which
  peer_rebuild turns into a per-stream fallback to the gRPC fetch.

Protocol (little-endian, persistent connection, one in-flight request
per connection):

    request:  b"SWNP" | u32 volume_id | u32 shard_id | u64 generation
              | u64 offset | u64 size | u16 meta_len       (38 bytes)
              | meta_len bytes of "key\\tvalue" lines — the SAME
              x-sw-trace-id / x-sw-parent-span / x-request-id metadata
              the gRPC stream carries, so a peer-fetch over the native
              plane still lands in the dispatcher's ONE trace (the
              PR 7 cross-RPC contract holds transport-independently)
    response: u8 status | u64 n | n bytes
              status 0 = ok (n = payload length, may be < size at EOF);
              status 1 = error (n = UTF-8 message length);
              status 2 = VOLUME-level refusal (needle opcode: the whole
              volume can never be served here — EC/TTL'd/tiered — so
              clients negative-cache the vid instead of paying a
              refusal round trip per chunk; same frame shape as 1)

The sidecar listens on ``grpc_port + NET_PLANE_PORT_OFFSET`` so peers
derive its address from the holder map's gRPC address without any new
topology plumbing; a dead port is just a memoized fallback.
"""

from __future__ import annotations

import base64
import os
import socket
import struct
import threading
import time

import numpy as np

from .. import faults
from ..utils import metrics as M
from ..utils import request_id as _rid
from ..utils import trace
from ..utils.glog import logger

log = logger("ec.netplane")

MAGIC = b"SWNP"
# Needle/chunk-read opcode (ISSUE 13): the warm gateway path's
# filer->volume chunk fetch over the SAME sidecar and framing. The
# 38-byte header shape is reused with reinterpreted fields —
# shard -> cookie, generation -> needle id, offset/size unused — and
# the OK response carries the needle's stored CRC32C between the
# length and the payload, so the client's fused copy-in CRC verifies
# with no extra byte pass.
MAGIC_NEEDLE = b"SWNR"
# Needle/blob WRITE opcode (ISSUE 18): the same 38-byte header frames a
# PUT — for kind=needle the fields are reinterpreted shard -> cookie,
# generation -> needle id, offset -> the CLIENT-computed CRC32C of the
# payload (so the server's fused copy-in CRC verifies transit with no
# extra byte pass); for kind=blob (remote stream-shard extents) offset
# is the real file offset and the CRC rides the metadata. The payload
# (`size` bytes) follows the metadata. An OK response carries
# n = stored size and the _NEEDLE_CRC trailer = the CRC as STORED, which
# the client compares against what it sent — an ack therefore certifies
# the exact bytes that hit the disk, end to end. Refusals (status 1/2)
# are sent only after the payload is drained, so the persistent
# connection stays in frame sync and pooled connections survive
# refusals.
MAGIC_WRITE = b"SWNW"
# magic, volume, shard, gen, offset, size, meta_len
_REQ = struct.Struct("<4sIIQQQH")
_RESP = struct.Struct("<BQ")      # status, n
_NEEDLE_CRC = struct.Struct("<I")  # appended to an OK needle response
NET_PLANE_PORT_OFFSET = 10000     # net plane port = grpc port + this

_SEND_CHUNK = 1 << 20             # python-plane egress chunking
_MAX_REQUEST = 1 << 32
_MAX_META = 4096
# error-response bodies are short refusal strings; a length beyond this
# means the stream desynced (or a hostile peer) — allocating it blindly
# would raise MemoryError past the callers' NetPlaneError fallback
_MAX_ERROR = 1 << 16
# needle payloads beyond this ride the HTTP path: chunks are filer
# chunk_size (MiBs), so a bigger OK-frame length is a desynced/hostile
# response — landing it would pin an immortal pooled buffer that size
_MAX_NEEDLE = 64 << 20
# never park landing buffers wider than this in the process-wide pool
_POOL_MAX_WIDTH = 8 << 20
# blob writes (stream-shard extents pushed at flush boundaries) may be
# wider than a needle; anything beyond this is a desynced/hostile frame
_MAX_BLOB = 256 << 20

# Write-opcode chaos routing: the write plane keeps serving while the
# ONLY armed fault points live on the write path's own seams (the
# net-plane pwrite window and the volume append/fsync window) — that is
# exactly the crash matrix that must ride the native path. Any OTHER
# armed point (byte-mutating storage chaos, read-path faults) refuses
# write service so the Python/gRPC fallback — which carries those
# points — stays the chaos surface, same contract as the read opcodes.
_WRITE_CHAOS_NS = ("ec.net.write.", "volume.write.")


def write_plane_admissible() -> bool:
    """True when the write opcode may serve despite an armed registry:
    every armed point lives in the write path's own chaos namespaces
    (or nothing is armed at all)."""
    return all(
        p.startswith(_WRITE_CHAOS_NS) for p in faults.armed_points()
    )


def _pool_width(n: int) -> int:
    """Pool width class for an n-byte payload. The landing pool
    free-lists by EXACT width and retains forever — pooling raw payload
    sizes (objects/tail chunks take arbitrary sizes) would grow one
    immortal buffer per distinct size. Rounding up to the next power of
    two (floor 64 KiB) bounds the class count to ~a dozen regardless of
    object-size mix."""
    return max(64 * 1024, 1 << (max(1, n) - 1).bit_length())


def _encode_meta(extra: dict | None = None) -> bytes:
    """The active request-id / trace context as a metadata blob —
    exactly what trace.grpc_metadata() would put on the RPC — plus any
    opcode-specific key/value pairs (the write opcode's kind / flags /
    name / jwt lines). Values must not contain tab or newline; binary
    fields ride urlsafe base64 (see _b64)."""
    md = list(trace.grpc_metadata() or [])
    if extra:
        md.extend(
            (k, str(v)) for k, v in extra.items()
            if v is not None and str(v) != ""
        )
    if not md:
        return b""
    blob = "\n".join(f"{k}\t{v}" for k, v in md).encode()
    return blob[:_MAX_META]


def _b64(value: bytes | str) -> str:
    if isinstance(value, str):
        value = value.encode()
    return base64.urlsafe_b64encode(value).decode()


def _unb64(value: str) -> bytes:
    try:
        return base64.urlsafe_b64decode(value.encode())
    except (ValueError, TypeError):
        return b""


def _decode_meta(blob: bytes) -> dict:
    md: dict = {}
    for line in blob.decode(errors="replace").splitlines():
        k, _, v = line.partition("\t")
        if k and v:
            md[k.lower()] = v
    return md


class NetPlaneError(Exception):
    """Transport/protocol failure on an established plane connection —
    transient from the caller's point of view (retry or fall back)."""


class NetPlaneVolumeRefusal(NetPlaneError):
    """Needle-opcode refusal that applies to the WHOLE volume (not
    mounted here / EC / TTL'd / tiered): the server answers status 2 so
    clients can negative-cache the vid. Raised by resolve_needle
    implementations server-side; surfaces client-side as a
    NetPlaneError with ``volume_refusal=True``."""


class NetPlaneUnavailable(Exception):
    """The peer serves no shard net plane (connect refused / bad
    protocol greeting). Memoized per peer; callers route the stream to
    the gRPC fetch instead."""


def derive_port(grpc_port: int) -> int:
    """Net-plane port derived from a gRPC port — the SAME pure function
    on the serving and connecting side, so no topology plumbing is
    needed. High ephemeral gRPC ports wrap back into the valid range
    deterministically; a collision there just fails the bind (server:
    plane disabled with one warning) or the connect (client: memoized
    gRPC fallback)."""
    p = grpc_port + NET_PLANE_PORT_OFFSET
    if p > 65535:
        p = 1024 + (p % 64512)
    return p


def net_addr(grpc_peer: str) -> tuple[str, int]:
    """Net-plane (host, port) derived from a holder-map gRPC address."""
    host, _, port = grpc_peer.rpartition(":")
    return host, derive_port(int(port))


def _native_mod():
    try:
        from ..utils import native

        return native
    except ImportError:
        return None


def egress_native() -> bool:
    """True when the server side should splice with sendfile: native
    plane on AND the fault registry disarmed (byte-mutating chaos needs
    materialized bytes — the armed registry routes to the Python
    egress, same contract as the local plane)."""
    from . import native_io

    return native_io.enabled() and not faults.active()


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise NetPlaneError("connection closed mid-message")
        got += r
    return bytes(buf)


# ---------------------------------------------------------------------------
# Server
# ---------------------------------------------------------------------------


class ShardNetPlane:
    """TCP sidecar serving EC shard byte ranges — the native twin of the
    ``VolumeEcShardRead`` gRPC stream, sharing its semantics (generation
    fence, short-read-at-EOF torn-stream contract, the
    ``server.ec_shard_read`` chaos point) but not its byte path.

    ``resolve(volume_id, shard_id, generation) -> (fd, size)`` supplies
    the shard fd and its byte size; it raises :class:`NetPlaneError`
    with the refusal message (not mounted / stale generation / shard
    not local). The server never closes resolved fds — they belong to
    the store's mounted EC volume, exactly like the gRPC servicer.

    ``resolve_needle(volume_id, needle_id, cookie) -> (fd, offset,
    size, crc32c, close_after)`` (optional) supplies a needle payload's
    location for the chunk-read opcode — the net-plane twin of the
    ``?locate=true`` control plane; ``close_after`` marks fds the
    server must close once the response is sent (per-request opens).
    Raising :class:`NetPlaneError` refuses the request (not here / EC /
    TTL'd / cookie mismatch) and the client falls back to HTTP.

    ``resolve_write(volume_id, needle_id, cookie, data, md) ->
    (stored_size, stored_crc)`` (optional) lands one needle append for
    the write opcode — the net-plane twin of the ``WriteNeedle`` gRPC —
    building the SAME needle record the gRPC/HTTP paths build (bit
    identity on disk) and triggering replica fan-out unless the request
    is itself a replica. :class:`NetPlaneVolumeRefusal` means the whole
    volume can never take plane writes here; :class:`NetPlaneError` /
    ``IOError`` / ``ValueError`` refuse this one write (client retries
    over the fallback transport).

    ``resolve_blob(path, op, md) -> fd | None`` (optional) serves
    kind=blob writes — remote durable-parity stream-shard extents. It
    validates `path` against the server's blob root, returning an fd
    the server pwrites into and closes (``op == "write"``), or handling
    the operation itself and returning None (``op == "unlink"``).
    """

    def __init__(self, ip: str, port: int, resolve,
                 request_timeout: float = 60.0, server_label: str = "",
                 resolve_needle=None, resolve_write=None,
                 resolve_blob=None):
        self.resolve = resolve
        self.resolve_needle = resolve_needle
        self.resolve_write = resolve_write
        self.resolve_blob = resolve_blob
        self.request_timeout = request_timeout
        self.server_label = server_label
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((ip, port))
        self._sock.listen(128)
        self.ip, self.port = self._sock.getsockname()[:2]
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="shard-net-plane"
        )
        self._conns: set[socket.socket] = set()
        self._conns_lock = threading.Lock()
        self.requests = 0
        self.needle_requests = 0
        self.sendfile_bytes = 0
        self.python_bytes = 0
        self.write_requests = 0
        self.write_native_bytes = 0
        self.write_python_bytes = 0

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        try:
            # close() alone does not wake a thread blocked in accept();
            # shutdown() does, so the join below returns immediately
            # instead of eating its full timeout
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
        self._thread.join(timeout=2.0)

    # ------------------------------------------------------------ serving

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # listener closed
            with self._conns_lock:
                self._conns.add(conn)
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            ).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            conn.settimeout(self.request_timeout)
            while not self._stop.is_set():
                try:
                    hdr = _recv_exact(conn, _REQ.size)
                except (NetPlaneError, OSError):
                    return  # client went away between requests
                magic, vid, sid, gen, off, size, mlen = _REQ.unpack(hdr)
                if (
                    magic not in (MAGIC, MAGIC_NEEDLE, MAGIC_WRITE)
                    or size > _MAX_REQUEST
                    or mlen > _MAX_META
                ):
                    return  # not our protocol: drop the connection
                try:
                    md = _decode_meta(_recv_exact(conn, mlen)) if mlen else {}
                except (NetPlaneError, OSError):
                    return
                self.requests += 1
                # Observability parity with the gRPC stream: adopt the
                # caller's request id + trace context and open the SAME
                # rpc.ec_shard_read span — a peer-fetch heal stays ONE
                # trace whichever transport carried the bytes. Needle
                # reads open rpc.needle_read instead, joined to the
                # gateway's trace the same way — one warm GET stays
                # ONE trace across the chunk-fetch hop.
                _rid.ensure(md.get(trace.REQUEST_ID_KEY))
                if magic == MAGIC_WRITE:
                    # field reinterpretation (kind=needle): sid slot =
                    # cookie, gen slot = needle id, off slot = the
                    # client's payload CRC32C
                    sp = trace.start_from_metadata(
                        "rpc.needle_write", md, server=self.server_label,
                        volume=vid, needle=gen, size=size, plane="native",
                    )
                    t0 = time.perf_counter()
                    try:
                        ok = self._serve_write(conn, vid, sid, gen, off,
                                               size, md)
                    finally:
                        trace.add_stage(
                            sp, "stream", time.perf_counter() - t0
                        )
                        trace.finish(sp)
                    if not ok:
                        return
                    continue
                if magic == MAGIC_NEEDLE:
                    # field reinterpretation: sid slot = cookie,
                    # gen slot = needle id
                    sp = trace.start_from_metadata(
                        "rpc.needle_read", md, server=self.server_label,
                        volume=vid, needle=gen, plane="native",
                    )
                    t0 = time.perf_counter()
                    try:
                        ok = self._serve_needle(conn, vid, gen, sid)
                    finally:
                        trace.add_stage(
                            sp, "stream", time.perf_counter() - t0
                        )
                        trace.finish(sp)
                    if not ok:
                        return
                    continue
                sp = trace.start_from_metadata(
                    "rpc.ec_shard_read", md, server=self.server_label,
                    volume=vid, shard=sid, offset=off, size=size,
                    plane="native",
                )
                t0 = time.perf_counter()
                try:
                    ok = self._serve_one(conn, vid, sid, gen, off, size)
                finally:
                    trace.add_stage(sp, "stream", time.perf_counter() - t0)
                    trace.finish(sp)
                if not ok:
                    return
        finally:
            with self._conns_lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _error(self, conn, msg: str, status: int = 1) -> bool:
        body = msg.encode(errors="replace")
        try:
            conn.sendall(_RESP.pack(status, len(body)) + body)
            return True
        except OSError:
            return False

    def _serve_one(self, conn, vid, sid, gen, off, size) -> bool:
        """Serve one range request; False = connection must close."""
        try:
            # Same named chaos point as the gRPC servicer: a raised
            # IOError is a refused stream (client replans); a mutate is
            # applied on the PYTHON egress below — the armed registry
            # routes there, never through sendfile.
            faults.fire("server.ec_shard_read", volume=vid, shard=sid)
        except IOError as e:
            return self._error(conn, str(e))
        try:
            fd, fsize = self.resolve(vid, sid, gen)
        except NetPlaneError as e:
            return self._error(conn, str(e))
        n = max(0, min(size, fsize - off)) if off < fsize else 0
        try:
            conn.sendall(_RESP.pack(0, n))
        except OSError:
            return False
        if n == 0:
            return True
        native = _native_mod() if egress_native() else None
        if native is not None:
            try:
                sent = native.send_file(
                    conn.fileno(), fd, off, n,
                    timeout_ms=int(self.request_timeout * 1000),
                )
            except OSError:
                return False  # peer died mid-splice: header already out
            self.sendfile_bytes += sent
            M.net_bytes_sent_total.inc(sent, plane="native", direction="read")
            return sent == n
        # Python egress (fallback plane / armed registry): pread ->
        # mutate -> sendall, byte-identical to the gRPC stream's
        # chunking. A mutate that shrinks the chunk tears the stream,
        # which the client must catch — never served silently.
        remaining, o = n, off
        while remaining > 0:
            chunk = os.pread(fd, min(_SEND_CHUNK, remaining), o)
            if not chunk:
                break
            orig = len(chunk)
            chunk = faults.mutate(
                "server.ec_shard_read", chunk, volume=vid, shard=sid, offset=o
            )
            M.net_bytes_copied_total.inc(orig, plane="python", direction="read")
            try:
                if chunk:
                    conn.sendall(chunk)
            except OSError:
                return False
            self.python_bytes += len(chunk)
            M.net_bytes_sent_total.inc(len(chunk), plane="python", direction="read")
            if len(chunk) < orig:
                return False  # torn stream: connection is dead
            o += orig
            remaining -= orig
        return remaining == 0

    def _serve_needle(self, conn, vid, nid, cookie) -> bool:
        """Serve one whole-needle payload (the warm gateway chunk
        fetch); False = connection must close. Refused outright when
        the fault registry is ARMED: byte-mutating chaos belongs to the
        Python-HTTP path, which carries the storage-layer fault points
        — the client's fallback is the chaos surface, same contract as
        the peer-fetch plane."""
        if self.resolve_needle is None:
            return self._error(conn, "needle reads not served here")
        if faults.active():
            return self._error(conn, "fault registry armed: use HTTP")
        try:
            fd, off, size, crc, close_after = self.resolve_needle(
                vid, nid, cookie
            )
        except NetPlaneVolumeRefusal as e:
            # the whole volume can never be served here: status 2 lets
            # the client negative-cache the vid
            return self._error(conn, str(e), status=2)
        except NetPlaneError as e:
            return self._error(conn, str(e))
        self.needle_requests += 1
        try:
            try:
                conn.sendall(
                    _RESP.pack(0, size) + _NEEDLE_CRC.pack(crc & 0xFFFFFFFF)
                )
            except OSError:
                return False
            if size == 0:
                return True
            native = _native_mod() if egress_native() else None
            if native is not None:
                try:
                    sent = native.send_file(
                        conn.fileno(), fd, off, size,
                        timeout_ms=int(self.request_timeout * 1000),
                    )
                except OSError:
                    return False
                self.sendfile_bytes += sent
                M.net_bytes_sent_total.inc(sent, plane="native", direction="read")
                return sent == size
            # Python egress (no .so): pread -> sendall, the same bytes.
            remaining, o = size, off
            while remaining > 0:
                chunk = os.pread(fd, min(_SEND_CHUNK, remaining), o)
                if not chunk:
                    return False  # short file: torn stream
                M.net_bytes_copied_total.inc(len(chunk), plane="python", direction="read")
                try:
                    conn.sendall(chunk)
                except OSError:
                    return False
                self.python_bytes += len(chunk)
                M.net_bytes_sent_total.inc(len(chunk), plane="python", direction="read")
                o += len(chunk)
                remaining -= len(chunk)
            return True
        finally:
            if close_after:
                try:
                    os.close(fd)
                except OSError:
                    pass

    # ------------------------------------------------------------- writes

    @staticmethod
    def _drain(conn, n: int) -> bool:
        """Consume `n` unread payload bytes so a refusal sent AFTER the
        header leaves the persistent connection in frame sync — pooled
        client connections survive refusals instead of desyncing."""
        if n <= 0:
            return True
        buf = bytearray(min(n, _SEND_CHUNK))
        view = memoryview(buf)
        left = n
        try:
            while left > 0:
                r = conn.recv_into(view[: min(left, len(buf))])
                if r == 0:
                    return False
                left -= r
        except OSError:
            return False
        return True

    def _land_payload(self, conn, row, size: int, native) -> int:
        """Land `size` payload bytes into pooled-buffer `row`, rolling
        the CRC32C during the copy-in (fused in `sn_recv_into` when the
        .so is present). Returns the landed CRC; raises NetPlaneError /
        OSError on a torn ingress (connection is then dead)."""
        if size == 0:
            return 0
        if native is not None:
            crc_state = np.zeros(1, np.uint32)
            filled = np.zeros(1, np.uint64)
            out_crcs = np.zeros(2, np.uint32)
            out_counts = np.zeros(1, np.int32)
            got = native.recv_into(
                conn.fileno(), row, size,
                timeout_ms=int(self.request_timeout * 1000),
                granule=size, crc_state=crc_state, filled_state=filled,
                out_crcs=out_crcs, out_counts=out_counts,
            )
            if got != size:
                raise NetPlaneError(f"torn write payload {got}/{size}")
            self.write_native_bytes += got
            M.net_bytes_received_total.inc(
                got, plane="native", direction="write"
            )
            return (
                int(out_crcs[0]) if int(out_counts[0]) > 0
                else int(crc_state[0])
            )
        view = memoryview(row)[:size]
        got = 0
        while got < size:
            r = conn.recv_into(view[got:], size - got)
            if r == 0:
                raise NetPlaneError(f"torn write payload {got}/{size}")
            got += r
        from ..utils.crc import crc32c as _crc

        self.write_python_bytes += size
        M.net_bytes_received_total.inc(
            size, plane="python", direction="write"
        )
        return _crc(row[:size])

    def _serve_write(self, conn, vid, cookie, nid, off_or_crc, size,
                     md) -> bool:
        """Serve one write request; False = connection must close.
        Refused while the fault registry holds points OUTSIDE the write
        path's own chaos namespaces (see write_plane_admissible) — the
        gRPC/HTTP fallback carries that chaos, while the write-path
        crash matrix rides through here."""
        kind = md.get("x-sw-w-kind", "")
        op = md.get("x-sw-w-op", "write")
        refusal = None
        if kind == "needle":
            if size > _MAX_NEEDLE:
                return False  # desynced/hostile frame: drop
            if self.resolve_write is None:
                refusal = "needle writes not served here"
        elif kind == "blob":
            if size > _MAX_BLOB:
                return False
            if self.resolve_blob is None:
                refusal = "blob writes not served here"
        else:
            return False  # unknown kind: protocol desync
        if refusal is None and not write_plane_admissible():
            refusal = "fault registry armed: use the fallback transport"
        if refusal is not None:
            if not self._drain(conn, size):
                return False
            return self._error(conn, refusal)
        self.write_requests += 1
        if kind == "blob":
            return self._serve_blob_write(conn, op, md, off_or_crc, size)
        return self._serve_needle_write(
            conn, vid, cookie, nid, off_or_crc, size, md
        )

    def _serve_needle_write(self, conn, vid, cookie, nid, want_crc,
                            size, md) -> bool:
        from . import native_io

        native = _native_mod() if native_io.enabled() else None
        pool = native_io.landing_pool()
        buf = pool.get(_pool_width(size))
        row = buf[0]
        try:
            try:
                landed_crc = self._land_payload(conn, row, size, native)
            except (OSError, NetPlaneError):
                return False
            if size and landed_crc != (want_crc & 0xFFFFFFFF):
                # payload fully consumed — the stream is in sync, so a
                # refusal (not a drop) lets the client retry/fall back
                return self._error(conn, "write payload CRC mismatch")
            # the one Python-level materialization on this path: the
            # needle record wants bytes it can keep
            data = row[:size].tobytes()
            M.net_bytes_copied_total.inc(
                size, plane="native" if native is not None else "python",
                direction="write",
            )
        finally:
            if buf.shape[1] <= _POOL_MAX_WIDTH:
                pool.put(buf)
        try:
            faults.fire(
                "ec.net.write.before_pwrite",
                volume=vid, needle=nid, size=size,
            )
            stored_size, stored_crc = self.resolve_write(
                vid, nid, cookie, data, md
            )
            faults.fire("ec.net.write.after_pwrite", volume=vid, needle=nid)
        except NetPlaneVolumeRefusal as e:
            return self._error(conn, str(e), status=2)
        except (NetPlaneError, OSError, ValueError) as e:
            return self._error(conn, str(e))
        try:
            conn.sendall(
                _RESP.pack(0, stored_size)
                + _NEEDLE_CRC.pack(stored_crc & 0xFFFFFFFF)
            )
        except OSError:
            return False
        return True

    def _serve_blob_write(self, conn, op, md, off, size) -> bool:
        try:
            path = _unb64(md.get("x-sw-w-path", "")).decode()
        except (ValueError, UnicodeDecodeError):
            path = ""
        try:
            want_crc = int(md.get("x-sw-w-crc", "0"))
        except ValueError:
            want_crc = 0
        do_fsync = md.get("x-sw-w-fsync", "0") == "1"
        try:
            fd = self.resolve_blob(path, op, md)
        except NetPlaneVolumeRefusal as e:
            if not self._drain(conn, size):
                return False
            return self._error(conn, str(e), status=2)
        except (NetPlaneError, OSError) as e:
            if not self._drain(conn, size):
                return False
            return self._error(conn, str(e))
        if fd is None:
            # op handled entirely by the resolver (unlink)
            if not self._drain(conn, size):
                return False
            try:
                conn.sendall(_RESP.pack(0, 0) + _NEEDLE_CRC.pack(0))
            except OSError:
                return False
            return True
        try:
            try:
                faults.fire(
                    "ec.net.write.before_pwrite", path=path, size=size
                )
            except IOError as e:
                if not self._drain(conn, size):
                    return False
                return self._error(conn, str(e))
            from . import native_io

            native = _native_mod() if native_io.enabled() else None
            landed_crc = 0
            if size:
                if native is not None and native.has_recv_file():
                    # socket -> disk with the CRC fused into the landing
                    # loop: Python never touches a payload byte
                    try:
                        got, landed_crc = native.recv_file(
                            conn.fileno(), fd, off, size,
                            timeout_ms=int(self.request_timeout * 1000),
                        )
                    except OSError:
                        return False
                    if got != size:
                        return False
                    self.write_native_bytes += got
                    M.net_bytes_received_total.inc(
                        got, plane="native", direction="write"
                    )
                else:
                    from ..utils.crc import crc32c as _crc

                    chunk = bytearray(min(size, _SEND_CHUNK))
                    view = memoryview(chunk)
                    remaining, o, crc = size, off, 0
                    try:
                        while remaining > 0:
                            want = min(len(chunk), remaining)
                            got = conn.recv_into(view[:want], want)
                            if got == 0:
                                return False
                            crc = _crc(view[:got], crc)
                            os.pwrite(fd, view[:got], o)
                            o += got
                            remaining -= got
                    except OSError:
                        return False
                    landed_crc = crc
                    self.write_python_bytes += size
                    M.net_bytes_received_total.inc(
                        size, plane="python", direction="write"
                    )
                    M.net_bytes_copied_total.inc(
                        size, plane="python", direction="write"
                    )
            if want_crc and landed_crc != (want_crc & 0xFFFFFFFF):
                # corrupt extent is already on disk, but the pushed
                # watermark only advances on an ACK — the client retries
                # the same extent at the same offset
                return self._error(conn, "blob payload CRC mismatch")
            try:
                faults.fire("ec.net.write.after_pwrite", path=path)
                if do_fsync:
                    os.fsync(fd)
            except (IOError, OSError) as e:
                return self._error(conn, str(e))
            try:
                conn.sendall(
                    _RESP.pack(0, size)
                    + _NEEDLE_CRC.pack(landed_crc & 0xFFFFFFFF)
                )
            except OSError:
                return False
            return True
        finally:
            try:
                os.close(fd)
            except OSError:
                pass

    def status(self) -> dict:
        """Sidecar state for /status and /debug/gateway surfaces."""
        return {
            "port": self.port,
            "requests": self.requests,
            "needle_requests": self.needle_requests,
            "sendfile_bytes": self.sendfile_bytes,
            "python_bytes": self.python_bytes,
            "write_requests": self.write_requests,
            "write_native_bytes": self.write_native_bytes,
            "write_python_bytes": self.write_python_bytes,
        }


# ---------------------------------------------------------------------------
# Client
# ---------------------------------------------------------------------------


class NetPlaneClient:
    """Pooled client connections to peers' shard net planes, landing
    payload bytes straight in caller buffers (``sn_recv_into``) with the
    fused granule CRC rolled during the copy-in.

    One cached connection per peer address (requests on one address are
    serialized — peer-fetch streams one shard from a given holder at a
    time, so the lock is uncontended on the rebuild path). A peer whose
    plane port refuses the connect is memoized and later calls raise
    :class:`NetPlaneUnavailable` immediately — but only for
    ``unavailable_ttl`` seconds (``SEAWEED_EC_NET_PLANE_RETRY_S``,
    default 30): a sidecar that comes up later (rolling restart, late
    boot) is re-probed and re-adopted instead of being written off for
    the life of the process. :meth:`reset` drops the memo immediately
    (operator hook — e.g. right after healing a peer).
    """

    def __init__(self, timeout: float = 30.0, connect_timeout: float = 2.0,
                 unavailable_ttl: float | None = None):
        self.timeout = timeout
        self.connect_timeout = connect_timeout
        if unavailable_ttl is None:
            try:
                unavailable_ttl = float(
                    os.environ.get("SEAWEED_EC_NET_PLANE_RETRY_S", "30")
                )
            except ValueError:
                unavailable_ttl = 30.0
        self.unavailable_ttl = unavailable_ttl
        self._conns: dict[tuple[str, int], socket.socket] = {}
        self._locks: dict[tuple[str, int], threading.Lock] = {}
        # needle-read connection pool: warm GETs arrive from N HTTP
        # workers concurrently, so chunk fetches check OUT a connection
        # per request (creating one on empty) instead of serializing on
        # the shard paths' one-conn-per-addr lock. Entries are
        # (socket, checkin-time): the server reaps idle connections at
        # its request_timeout (60 s), so anything parked longer than
        # _npool_idle_s is discarded at checkout instead of burning a
        # request on a dead socket (which would silently demote that
        # GET to the HTTP path).
        self._npool: dict[
            tuple[str, int], list[tuple[socket.socket, float]]
        ] = {}
        self._npool_max = 16
        self._npool_idle_s = 30.0
        # addr -> monotonic time of the refused connect (TTL'd memo)
        self._no_plane: dict[tuple[str, int], float] = {}
        self._lock = threading.Lock()

    def close(self) -> None:
        with self._lock:
            conns = list(self._conns.values())
            self._conns.clear()
            for lst in self._npool.values():
                conns.extend(s for s, _t in lst)
            self._npool.clear()
        for c in conns:
            try:
                c.close()
            except OSError:
                pass

    def reset(self, addr: tuple[str, int] | None = None) -> None:
        """Forget the no-plane memo for `addr` (or every peer): the
        next call re-probes the connect instead of waiting out the
        TTL."""
        with self._lock:
            if addr is None:
                self._no_plane.clear()
            else:
                self._no_plane.pop(addr, None)

    def _addr_lock(self, addr) -> threading.Lock:
        with self._lock:
            return self._locks.setdefault(addr, threading.Lock())

    def _check_memo(self, addr) -> None:
        """Raise if `addr` is inside its no-plane TTL; forget an
        expired refusal so the next connect re-probes (a sidecar that
        has since come up gets re-adopted). Caller holds self._lock."""
        refused_at = self._no_plane.get(addr)
        if refused_at is not None:
            if time.monotonic() - refused_at < self.unavailable_ttl:
                raise NetPlaneUnavailable(f"{addr[0]}:{addr[1]}")
            del self._no_plane[addr]

    def _connect(self, addr) -> socket.socket:
        """Fresh plane connection (no caching); a refused connect is
        memoized for `unavailable_ttl` seconds."""
        try:
            s = socket.create_connection(addr, timeout=self.connect_timeout)
        except OSError as e:
            with self._lock:
                self._no_plane[addr] = time.monotonic()
            raise NetPlaneUnavailable(f"{addr[0]}:{addr[1]}: {e}") from e
        s.settimeout(self.timeout)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return s

    def _conn(self, addr) -> socket.socket:
        with self._lock:
            self._check_memo(addr)
            s = self._conns.get(addr)
        if s is not None:
            return s
        s = self._connect(addr)
        with self._lock:
            self._conns[addr] = s
        return s

    def _checkout(self, addr) -> socket.socket:
        """Take a pooled needle-read connection (or dial a new one):
        one connection per IN-FLIGHT request, so concurrent warm GETs
        fan out instead of serializing on one socket. Connections
        parked longer than `_npool_idle_s` are discarded — the server
        side reaps idle peers, and a dead pooled socket would cost the
        next GET its fast path."""
        stale: list[socket.socket] = []
        fresh = None
        with self._lock:
            self._check_memo(addr)
            lst = self._npool.get(addr)
            now = time.monotonic()
            while lst:
                s, t = lst.pop()
                if now - t < self._npool_idle_s:
                    fresh = s
                    break
                stale.append(s)
        for s in stale:
            try:
                s.close()
            except OSError:
                pass
        if fresh is not None:
            return fresh
        return self._connect(addr)

    def _checkin(self, addr, s: socket.socket) -> None:
        now = time.monotonic()
        expired: list[socket.socket] = []
        with self._lock:
            lst = self._npool.setdefault(addr, [])
            # reap expired entries from the FRONT (oldest): checkout
            # pops LIFO and stops at the first fresh socket, so without
            # this sweep the old ones below it would pin dead fds (and
            # pool slots) for the life of the process
            while lst and now - lst[0][1] >= self._npool_idle_s:
                expired.append(lst.pop(0)[0])
            if len(lst) < self._npool_max:
                lst.append((s, now))
                s = None  # type: ignore[assignment]
        for dead in expired:
            try:
                dead.close()
            except OSError:
                pass
        if s is not None:
            try:
                s.close()
            except OSError:
                pass

    def _drop(self, addr) -> None:
        with self._lock:
            s = self._conns.pop(addr, None)
        if s is not None:
            try:
                s.close()
            except OSError:
                pass

    def _request(
        self, addr, vid, sid, gen, off, size, exact: bool = True
    ) -> tuple[socket.socket, int]:
        """Send one range request, parse the response header, return
        (connection positioned at the payload, payload length). With
        `exact` (the default) a server-side EOF clamp raises — range
        callers sized their landing buffer; `exact=False` accepts the
        clamp (whole-shard fetches discover the size this way)."""
        s = self._conn(addr)
        meta = _encode_meta()
        try:
            s.sendall(
                _REQ.pack(MAGIC, vid, sid, gen, off, size, len(meta)) + meta
            )
            head = _recv_exact(s, _RESP.size)
        except (OSError, NetPlaneError) as e:
            self._drop(addr)
            raise NetPlaneError(f"{addr}: {e}") from e
        status, n = _RESP.unpack(head)
        if status != 0:
            try:
                msg = self._read_refusal(addr, s, n)
            except NetPlaneError:
                self._drop(addr)
                raise
            raise NetPlaneError(f"{addr}: {msg}")
        if n > size:
            # the server only ever clamps DOWN (n = min(size, fsize));
            # a longer claim is a desynced or hostile peer — honoring
            # it would stream garbage past the caller's sizing
            self._drop(addr)
            raise NetPlaneError(f"{addr}: oversized frame {n}/{size}")
        if exact and n != size:
            # EOF clamp — the gRPC stream's short read. The connection
            # still holds n payload bytes; cheaper to drop it than to
            # drain and resync.
            self._drop(addr)
            raise NetPlaneError(f"{addr}: short stream {n}/{size}")
        return s, n

    def read_into(
        self,
        addr: tuple[str, int],
        vid: int,
        sid: int,
        gen: int,
        off: int,
        size: int,
        dst: np.ndarray,
        *,
        granule: int = 0,
    ) -> np.ndarray | None:
        """Land `size` bytes of shard `sid` @`off` DIRECTLY in `dst`
        (1-D C-contiguous uint8 view of a pooled aligned buffer). With
        granule > 0 returns the granule CRCs rolled during the copy-in
        (completed granules plus the partial tail) as a u32 ndarray —
        the caller compares them against the .ecsum sidecar with no
        extra pass over the bytes."""
        native = _native_mod()
        with self._addr_lock(addr):
            return self._read_into_locked(
                addr, vid, sid, gen, off, size, dst,
                granule=granule, native=native,
            )

    def _read_into_locked(
        self, addr, vid, sid, gen, off, size, dst, *, granule, native
    ):
        s, _n = self._request(addr, vid, sid, gen, off, size)
        try:
            if native is not None:
                crc_state = np.zeros(1, np.uint32)
                filled = np.zeros(1, np.uint64)
                max_out = (size // granule + 2) if granule else 1
                out_crcs = np.zeros(max_out, np.uint32)
                out_counts = np.zeros(1, np.int32)
                got = native.recv_into(
                    s.fileno(), dst, size,
                    timeout_ms=int(self.timeout * 1000),
                    granule=granule, crc_state=crc_state,
                    filled_state=filled, out_crcs=out_crcs,
                    out_counts=out_counts,
                )
                if got != size:
                    raise NetPlaneError(
                        f"{addr}: torn stream {got}/{size}"
                    )
                M.net_bytes_received_total.inc(got, plane="native", direction="read")
                if not granule:
                    return None
                crcs = list(out_crcs[: int(out_counts[0])])
                if size % granule:
                    crcs.append(int(crc_state[0]))
                return np.asarray(crcs, dtype=np.uint32)
            # Python landing (no .so): same buffer, Python recv loop.
            view = memoryview(dst)[:size]
            got = 0
            while got < size:
                r = s.recv_into(view[got:], size - got)
                if r == 0:
                    raise NetPlaneError(f"{addr}: torn stream {got}/{size}")
                got += r
            M.net_bytes_received_total.inc(got, plane="python", direction="read")
            if not granule:
                return None
            from ..utils.crc import crc32c as _crc

            return np.array(
                [
                    _crc(dst[i : min(i + granule, size)])
                    for i in range(0, size, granule)
                ],
                dtype=np.uint32,
            )
        except (OSError, NetPlaneError) as e:
            self._drop(addr)
            if isinstance(e, NetPlaneError):
                raise
            raise NetPlaneError(f"{addr}: {e}") from e

    def read_bytes(
        self, addr, vid, sid, gen, off, size
    ) -> bytes:
        """Python-plane fetch over the same wire: materializes the
        payload as `bytes` (counted against the python plane's
        copied/received totals). Used by granule re-reads and by the
        bench's same-transport Python-plane comparison."""
        with self._addr_lock(addr):
            s, _n = self._request(addr, vid, sid, gen, off, size)
            try:
                data = _recv_exact(s, size)
            except (OSError, NetPlaneError) as e:
                self._drop(addr)
                raise NetPlaneError(f"{addr}: {e}") from e
        M.net_bytes_received_total.inc(size, plane="python", direction="read")
        M.net_bytes_copied_total.inc(size, plane="python", direction="read")
        return data

    def fetch_shard_to_file(
        self, addr, vid, sid, gen, fobj, *, chunk: int = 4 << 20
    ) -> int:
        """Fetch one WHOLE shard (size discovered from the server's EOF
        clamp) into an open binary file object — the migration copy
        path (ec/rebalance.py): the source splices the shard file with
        sendfile(2) and this side lands it through a pooled aligned
        buffer in `chunk`-sized pieces. Returns bytes written. The wire
        bytes are attributed to the native plane
        (`sw_net_bytes_received_total{plane=native}` — or python when
        the .so is absent), which is the bench's migration evidence.
        Raises :class:`NetPlaneUnavailable` (memoized) for peers
        without the sidecar and :class:`NetPlaneError` for refusals
        (stale generation, shard not local) — callers fall back to the
        gRPC CopyFile stream."""
        from . import native_io

        native = _native_mod() if native_io.enabled() else None
        plane = "native" if native is not None else "python"
        pool = native_io.landing_pool()
        buf = pool.get(chunk)
        row = buf[0]
        total = 0
        try:
            with self._addr_lock(addr):
                # one request for the whole file: ask for the 4 GiB
                # protocol max and let the server clamp to the size
                s, n = self._request(
                    addr, vid, sid, gen, 0, _MAX_REQUEST, exact=False
                )
                try:
                    remaining = n
                    while remaining > 0:
                        want = min(chunk, remaining)
                        if native is not None:
                            got = native.recv_into(
                                s.fileno(), row, want,
                                timeout_ms=int(self.timeout * 1000),
                                granule=0,
                                crc_state=np.zeros(1, np.uint32),
                                filled_state=np.zeros(1, np.uint64),
                                out_crcs=np.zeros(1, np.uint32),
                                out_counts=np.zeros(1, np.int32),
                            )
                            if got != want:
                                raise NetPlaneError(
                                    f"{addr}: torn stream "
                                    f"{total + got}/{n}"
                                )
                        else:
                            view = memoryview(row)[:want]
                            got = 0
                            while got < want:
                                r = s.recv_into(view[got:], want - got)
                                if r == 0:
                                    raise NetPlaneError(
                                        f"{addr}: torn stream "
                                        f"{total + got}/{n}"
                                    )
                                got += r
                        M.net_bytes_received_total.inc(want, plane=plane, direction="read")
                        fobj.write(row[:want])
                        total += want
                        remaining -= want
                except (OSError, NetPlaneError) as e:
                    self._drop(addr)
                    if isinstance(e, NetPlaneError):
                        raise
                    raise NetPlaneError(f"{addr}: {e}") from e
        finally:
            if buf.shape[1] <= _POOL_MAX_WIDTH:
                pool.put(buf)
        return total


    # ------------------------------------------------------- needle reads

    @staticmethod
    def _read_refusal(addr, s, n: int) -> str:
        """Decode a status!=0 error frame's body (shared by the shard
        and needle paths so the protocol-error handling can't drift).
        Raises NetPlaneError when the frame is desynced (length beyond
        any real refusal string) or the body can't be read — the
        connection is then unusable and the caller must discard it."""
        if n > _MAX_ERROR:
            raise NetPlaneError(f"{addr}: desynced error frame ({n})")
        try:
            return _recv_exact(s, n).decode(errors="replace")
        except (OSError, NetPlaneError) as e:
            raise NetPlaneError(f"{addr}: error body lost ({e})") from e

    def read_needle(
        self, addr: tuple[str, int], vid: int, nid: int, cookie: int
    ) -> bytes:
        """Whole-needle payload over the chunk-read opcode (the warm
        gateway path's filer->volume fetch): the server resolves
        (fd, offset, size, crc) from its needle map and splices the
        payload with sendfile; this side lands it DIRECTLY in a pooled
        4096-aligned buffer via ``sn_recv_into`` with the CRC32C fused
        into the copy-in and verified against the needle's stored CRC —
        a vacuum racing the read, or a stale location, surfaces as a
        mismatch (raise -> caller falls back to HTTP), never as silent
        wrong bytes. Raises :class:`NetPlaneUnavailable` for peers
        without the sidecar (memoized with TTL). Connections come from
        a per-address checkout pool — concurrent warm GETs fan out
        over parallel sockets instead of serializing."""
        s = self._checkout(addr)
        healthy = False
        try:
            meta = _encode_meta()
            try:
                s.sendall(
                    _REQ.pack(
                        MAGIC_NEEDLE, vid, cookie & 0xFFFFFFFF, nid,
                        0, 0, len(meta),
                    )
                    + meta
                )
                head = _recv_exact(s, _RESP.size)
            except (OSError, NetPlaneError) as e:
                raise NetPlaneError(f"{addr}: {e}") from e
            status, n = _RESP.unpack(head)
            if status != 0:
                msg = self._read_refusal(addr, s, n)
                healthy = True  # refusal leaves the stream in sync
                err = NetPlaneError(f"{addr}: {msg}")
                # status 2 = volume-level refusal: callers negative-
                # cache the vid instead of re-probing per chunk
                err.volume_refusal = status == 2
                raise err
            if n > _MAX_NEEDLE:
                raise NetPlaneError(f"{addr}: oversized needle {n}")
            try:
                (want_crc,) = _NEEDLE_CRC.unpack(
                    _recv_exact(s, _NEEDLE_CRC.size)
                )
            except (OSError, NetPlaneError) as e:
                raise NetPlaneError(f"{addr}: {e}") from e
            if n == 0:
                healthy = True
                return b""
            data = self._land_needle(addr, s, int(n), want_crc)
            healthy = True
            return data
        finally:
            if healthy:
                self._checkin(addr, s)
            else:
                try:
                    s.close()
                except OSError:
                    pass

    # pool width class for an n-byte needle payload (see _pool_width —
    # shared with the server's write landing so the classes can't drift)
    _landing_width = staticmethod(_pool_width)

    def _land_needle(self, addr, s, n: int, want_crc: int) -> bytes:
        from . import native_io

        native = _native_mod() if native_io.enabled() else None
        pool = native_io.landing_pool()
        buf = pool.get(self._landing_width(n))
        row = buf[0]
        try:
            try:
                if native is not None:
                    crc_state = np.zeros(1, np.uint32)
                    filled = np.zeros(1, np.uint64)
                    out_crcs = np.zeros(2, np.uint32)
                    out_counts = np.zeros(1, np.int32)
                    got = native.recv_into(
                        s.fileno(), row, n,
                        timeout_ms=int(self.timeout * 1000),
                        granule=n, crc_state=crc_state,
                        filled_state=filled, out_crcs=out_crcs,
                        out_counts=out_counts,
                    )
                    if got != n:
                        raise NetPlaneError(
                            f"{addr}: torn needle stream {got}/{n}"
                        )
                    landed_crc = (
                        int(out_crcs[0]) if int(out_counts[0]) > 0
                        else int(crc_state[0])
                    )
                    M.net_bytes_received_total.inc(got, plane="native", direction="read")
                else:
                    view = memoryview(row)[:n]
                    got = 0
                    while got < n:
                        r = s.recv_into(view[got:], n - got)
                        if r == 0:
                            raise NetPlaneError(
                                f"{addr}: torn needle stream {got}/{n}"
                            )
                        got += r
                    from ..utils.crc import crc32c as _crc

                    landed_crc = _crc(row[:n])
                    M.net_bytes_received_total.inc(n, plane="python", direction="read")
            except OSError as e:
                raise NetPlaneError(f"{addr}: {e}") from e
            if landed_crc != (want_crc & 0xFFFFFFFF):
                raise NetPlaneError(f"{addr}: needle CRC mismatch")
            # the one Python-level materialization on this path: pooled
            # landing buffer -> the bytes object the chunk cache keeps
            data = row[:n].tobytes()
            M.net_bytes_copied_total.inc(
                n, plane="native" if native is not None else "python",
                direction="read",
            )
            return data
        finally:
            # a raise out of here (torn stream, CRC mismatch) leaves
            # the caller to close the checked-out socket. Oversized
            # landings never park in the immortal pool.
            if buf.shape[1] <= _POOL_MAX_WIDTH:
                pool.put(buf)

    # ------------------------------------------------------ needle writes

    def _write_request(
        self, addr, vid, sid, gen, off, payload, extra_meta
    ) -> tuple[int, int]:
        """One write-opcode round trip on a pooled connection: header +
        meta + payload out, (status, n [, stored CRC]) back. Returns
        (stored_size, stored_crc). Refusals leave the stream in sync
        (the server drains the payload first), so the connection goes
        back to the pool even on a refusal."""
        s = self._checkout(addr)
        healthy = False
        try:
            meta = _encode_meta(extra_meta)
            try:
                s.sendall(
                    _REQ.pack(
                        MAGIC_WRITE, vid, sid, gen, off,
                        len(payload), len(meta),
                    )
                    + meta
                )
                if payload:
                    s.sendall(payload)
                head = _recv_exact(s, _RESP.size)
            except (OSError, NetPlaneError) as e:
                raise NetPlaneError(f"{addr}: {e}") from e
            status, n = _RESP.unpack(head)
            if status != 0:
                msg = self._read_refusal(addr, s, n)
                healthy = True
                err = NetPlaneError(f"{addr}: {msg}")
                err.volume_refusal = status == 2
                raise err
            try:
                (stored_crc,) = _NEEDLE_CRC.unpack(
                    _recv_exact(s, _NEEDLE_CRC.size)
                )
            except (OSError, NetPlaneError) as e:
                raise NetPlaneError(f"{addr}: {e}") from e
            healthy = True
            from . import native_io

            M.net_bytes_sent_total.inc(
                len(payload),
                plane="native" if native_io.enabled() else "python",
                direction="write",
            )
            return int(n), int(stored_crc)
        finally:
            if healthy:
                self._checkin(addr, s)
            else:
                try:
                    s.close()
                except OSError:
                    pass

    def write_needle(
        self, addr: tuple[str, int], vid: int, nid: int, cookie: int,
        data: bytes, *, flags: int = 0, name: bytes | str = b"",
        mime: bytes | str = b"", jwt: str = "", fsync: bool = False,
        replicate: bool = True,
    ) -> tuple[int, int]:
        """Append one needle over the write opcode (the PUT path's
        native twin of the ``WriteNeedle`` gRPC / HTTP upload). The
        payload CRC32C rides the header; the server's fused copy-in CRC
        verifies transit, and the ACK's STORED CRC is verified here
        against what was sent — an accepted write certifies the exact
        bytes on disk end to end. Returns (stored_size, stored_crc).
        Raises :class:`NetPlaneUnavailable` (memoized, TTL'd) for peers
        without the sidecar; a refusal with ``volume_refusal=True``
        means the whole volume can never take plane writes here."""
        from ..utils.crc import crc32c as _crc

        crc = _crc(data) if data else 0
        extra = {
            "x-sw-w-kind": "needle",
            "x-sw-w-flags": str(int(flags)),
        }
        if name:
            extra["x-sw-w-name"] = _b64(name)
        if mime:
            extra["x-sw-w-mime"] = _b64(mime)
        if jwt:
            extra["x-sw-w-jwt"] = jwt
        if fsync:
            extra["x-sw-w-fsync"] = "1"
        if not replicate:
            extra["x-sw-w-replicate"] = "0"
        stored_size, stored_crc = self._write_request(
            addr, vid, cookie & 0xFFFFFFFF, nid, crc, data, extra
        )
        if data and stored_crc != crc:
            raise NetPlaneError(
                f"{addr}: stored CRC mismatch "
                f"(ack {stored_crc:#010x} != sent {crc:#010x})"
            )
        return stored_size, stored_crc

    def write_blob(
        self, addr: tuple[str, int], path: str, off: int, data, *,
        fsync: bool = True, jwt: str = "",
    ) -> int:
        """Write one extent of a remote stream-shard blob at `off`
        (kind=blob): the true network transport behind `net:` remote
        roots, replacing the shared-mount assumption. The server lands
        socket->disk (``sn_recv_file``, CRC fused) and fsyncs before
        ACKing when `fsync` — the remote extent is DURABLE once this
        returns. Returns bytes stored."""
        from ..utils.crc import crc32c as _crc

        data = bytes(data)
        extra = {
            "x-sw-w-kind": "blob",
            "x-sw-w-path": _b64(path),
            "x-sw-w-crc": str(_crc(data) if data else 0),
        }
        if fsync:
            extra["x-sw-w-fsync"] = "1"
        if jwt:
            extra["x-sw-w-jwt"] = jwt
        stored, _crc_ack = self._write_request(
            addr, 0, 0, 0, off, data, extra
        )
        return stored

    def unlink_blob(
        self, addr: tuple[str, int], path: str, *, jwt: str = ""
    ) -> None:
        """Remove a remote stream-shard blob (best-effort GC of
        superseded generations)."""
        extra = {
            "x-sw-w-kind": "blob",
            "x-sw-w-op": "unlink",
            "x-sw-w-path": _b64(path),
        }
        if jwt:
            extra["x-sw-w-jwt"] = jwt
        self._write_request(addr, 0, 0, 0, 0, b"", extra)


def make_fetch_into(client: NetPlaneClient, vid: int, generation: int,
                    addr_of=net_addr):
    """Adapt a :class:`NetPlaneClient` to peer_rebuild's injected
    ``fetch_into(peer, sid, off, size, dst, granule)`` transport,
    translating plane exceptions into the rebuild's retry/fallback
    vocabulary (NetPlaneError -> PeerFetchTransient, NetPlaneUnavailable
    -> PeerPlaneUnavailable)."""
    from .peer_rebuild import PeerFetchTransient, PeerPlaneUnavailable

    def fetch_into(peer, sid, off, size, dst, granule):
        try:
            return client.read_into(
                addr_of(peer), vid, sid, generation, off, size, dst,
                granule=granule,
            )
        except NetPlaneUnavailable as e:
            raise PeerPlaneUnavailable(str(e)) from e
        except NetPlaneError as e:
            raise PeerFetchTransient(str(e)) from e

    return fetch_into
