"""Pluggable Reed-Solomon compute backends: -ec.backend=cpu|tpu|auto.

The EC pipeline (encoder/rebuild/decoder/read-recovery) is written
against this interface; the reference's equivalent seam is the
reedsolomon.Encoder handed around weed/storage/erasure_coding.

- CpuBackend: C++ AVX2 PSHUFB GF(2^8) (native/seaweed_native.cpp), the
  klauspost-equivalent path. Default for latency-sensitive single-
  interval recovery (SURVEY.md hard part (d)).
- JaxBackend: bit-matrix matmul on the local JAX device (TPU MXU via
  XLA or the fused Pallas kernel). Best at bulk batches; bit-identical
  to the CPU path by construction.

All backends consume/produce numpy uint8 arrays of shape (rows, n).
"""

from __future__ import annotations

import functools
import itertools
import weakref
from typing import Protocol

import numpy as np

from .. import faults
from ..ops import gf256
from ..utils import metrics as _M
from ..utils.glog import logger
from .context import ECContext, ECError


class RSBackend(Protocol):
    ctx: ECContext

    def encode(self, data: np.ndarray) -> np.ndarray:
        """(k, n) data -> (m, n) parity."""
        ...

    # Async device pipeline hooks (overlap H2D / compute / D2H). On the
    # CPU backend these degenerate to identity + synchronous encode, so
    # the encoder pipeline is written once against this surface.
    def to_device(self, data: np.ndarray):
        """Stage host data toward the compute device (async when the
        backend is a device; returns a handle encode_staged accepts)."""
        ...

    def encode_staged(self, staged):
        """Dispatch encode on staged input; returns a result handle
        WITHOUT waiting for completion."""
        ...

    def apply_staged(self, coeffs: np.ndarray, staged):
        """Dispatch a general GF(256) apply (see `apply`) on staged
        input; returns a result handle WITHOUT waiting for completion.
        The staged analog of `apply` — what rebuild/decode/degraded
        reconstruction use to overlap H2D, compute, and D2H."""
        ...

    def to_host(self, result) -> np.ndarray:
        """Block until `result` is complete and return host uint8."""
        ...

    def reconstruct(
        self, shards: dict[int, np.ndarray], want: list[int] | None = None
    ) -> dict[int, np.ndarray]:
        """Any >=k present shards -> the missing shards (all of them, or
        just `want` — e.g. one shard on the latency-sensitive read path)."""
        ...

    def apply(self, coeffs: np.ndarray, data: np.ndarray) -> np.ndarray:
        """General GF(256) matrix apply: out[r] = sum_j coeffs[r,j]*data[j]."""
        ...


def _decode_coeffs(
    matrix: np.ndarray, k: int, out_rows: tuple[int, ...], src_rows: tuple[int, ...]
) -> np.ndarray:
    """Rows mapping shards[src_rows] (k of them) -> shards[out_rows]."""
    sub = matrix[list(src_rows), :]
    inv = gf256.invert(sub)
    return gf256.matmul(matrix[list(out_rows), :], inv)


class _BackendBase:
    def __init__(self, ctx: ECContext):
        self.ctx = ctx
        self._ref = gf256.ReedSolomon(ctx.data_shards, ctx.parity_shards)
        self.matrix = self._ref.matrix

    def _plan_reconstruct(
        self, shards: dict[int, np.ndarray], want: list[int] | None
    ) -> tuple[tuple[int, ...], tuple[int, ...]]:
        k, total = self.ctx.data_shards, self.ctx.total
        present = tuple(sorted(i for i in shards if 0 <= i < total))
        if len(present) < k:
            raise ECError(f"need {k} shards to reconstruct, have {len(present)}")
        targets = range(total) if want is None else want
        missing = tuple(i for i in targets if i not in shards)
        return present[:k], missing

    def reconstruct(
        self, shards: dict[int, np.ndarray], want: list[int] | None = None
    ) -> dict[int, np.ndarray]:
        src, missing = self._plan_reconstruct(shards, want)
        if not missing:
            return {}
        coeffs = _decode_coeffs(self.matrix, self.ctx.data_shards, missing, src)
        data = np.stack([np.asarray(shards[i], dtype=np.uint8) for i in src])
        out = self.apply(coeffs, data)
        return {idx: out[i] for i, idx in enumerate(missing)}

    def verify(self, shards: np.ndarray) -> bool:
        shards = np.asarray(shards, dtype=np.uint8)
        k = self.ctx.data_shards
        return bool(np.array_equal(self.encode(shards[:k]), shards[k:]))

    # Default (synchronous) pipeline hooks; device backends override.
    # apply_staged degenerates to the synchronous apply, so CpuBackend
    # output through the staged pipeline is bit-identical to apply() by
    # construction.
    def to_device(self, data: np.ndarray):
        return data

    def encode_staged(self, staged):
        return self.encode(staged)

    def apply_staged(self, coeffs: np.ndarray, staged):
        return self.apply(coeffs, staged)

    def to_host(self, result) -> np.ndarray:
        return np.asarray(result, dtype=np.uint8)


class CpuBackend(_BackendBase):
    """Native C++ SIMD GF(2^8); falls back to numpy tables if the .so
    is unavailable."""

    # Below this width, thread spawn overhead beats the win from
    # splitting columns; single-interval read recovery stays 1-thread.
    _MT_MIN_WIDTH = 1 << 20

    def __init__(self, ctx: ECContext):
        super().__init__(ctx)
        try:
            from ..utils import native

            self._apply_fn = native.rs_apply
            self._apply_mt = getattr(native, "rs_apply_mt", None)
        except Exception:
            self._apply_fn = gf256.matrix_apply
            self._apply_mt = None

    def apply(self, coeffs: np.ndarray, data: np.ndarray) -> np.ndarray:
        coeffs = np.asarray(coeffs, np.uint8)
        data = np.asarray(data, np.uint8)
        if (
            self._apply_mt is not None
            and data.ndim == 2
            and data.shape[1] >= self._MT_MIN_WIDTH
        ):
            return self._apply_mt(coeffs, data)
        return self._apply_fn(coeffs, data)

    def encode(self, data: np.ndarray) -> np.ndarray:
        return self.apply(self._ref.parity, data)


class JaxBackend(_BackendBase):
    """Local JAX device(s) via bit-matrix matmuls.

    With more than one local device the PRODUCTION encode path shards
    batch columns across a 1-D mesh (parallel.MeshRS): parity is
    columnwise-independent, so the split is bit-exact and XLA inserts
    no collectives — each chip encodes its column slice (SURVEY §7
    stage 2: pjit across chips for large volumes). Single-device
    behavior is unchanged."""

    def __init__(
        self,
        ctx: ECContext,
        impl: str = "auto",
        interpret: bool = False,
        n_devices: int | None = None,
    ):
        super().__init__(ctx)
        import jax

        from ..ops.rs_jax import RSJax

        impl_was_auto = impl == "auto"
        if impl_was_auto:
            impl = "pallas" if jax.devices()[0].platform == "tpu" else "xla"
        self._rs = RSJax(
            ctx.data_shards, ctx.parity_shards, impl=impl, interpret=interpret
        )
        self._mesh_rs = None
        # Device counting calls jax.devices(), which HANGS forever on a
        # dead TPU relay. Only do it when the caller implicitly already
        # did (impl='auto') or explicitly asked for a mesh; an explicit
        # single-impl construction keeps the pre-mesh hang-free path.
        if n_devices == 1:
            want = 1
        elif impl_was_auto or n_devices is not None:
            avail = len(jax.devices())
            if n_devices is not None and avail < n_devices:
                # explicit request: fail loudly, never silently shrink
                raise RuntimeError(
                    f"need {n_devices} devices, have {avail}"
                )
            want = n_devices if n_devices is not None else avail
        else:
            want = 1
        if want > 1:
            # shard_map wraps the impl's own per-chip encode (XLA or
            # Pallas) over the column mesh
            from ..parallel import MeshRS, make_mesh

            self._mesh_rs = MeshRS(self._rs, make_mesh(want))

    def encode(self, data: np.ndarray) -> np.ndarray:
        return np.asarray(self._rs.encode(data))

    # -- async pipeline: JAX dispatch is non-blocking, so staging batch
    # N+1 while batch N computes (and N-1 drains to host) only requires
    # NOT forcing np.asarray between the stages. The encoder's bounded
    # queues provide the double-buffering window.
    def to_device(self, data: np.ndarray):
        import jax

        data = np.ascontiguousarray(data, dtype=np.uint8)
        if self._mesh_rs is not None:
            from ..parallel import pad_cols

            padded, n = pad_cols(data, self._mesh_rs.n_devices)
            return (self._mesh_rs.put(padded), n)
        return jax.device_put(data)

    def encode_staged(self, staged):
        if self._mesh_rs is not None:
            arr, n = staged
            return (self._mesh_rs.encode(arr), n)
        return self._rs.encode(staged)

    def apply_staged(self, coeffs: np.ndarray, staged):
        coeffs = np.ascontiguousarray(coeffs, dtype=np.uint8)
        if self._mesh_rs is not None:
            arr, n = staged
            bits = self._rs.coeff_bits(coeffs)
            return (self._mesh_rs.apply(bits, arr, coeffs.shape[0]), n)
        return self._rs.apply(coeffs, staged)

    def to_host(self, result) -> np.ndarray:
        # TPU-side chaos hook: the kernel was LAUNCHED (encode_staged/
        # apply_staged dispatched it non-blocking) and this fetch is
        # where a reset/hung device actually surfaces. A raised IOError
        # here models a mid-kernel device reset, so FallbackBackend's
        # to_host failover (CPU replay of the carried host batch) is
        # exercisable — not just pre-dispatch death.
        faults.fire(
            "ec.device.kernel_fetch", impl=getattr(self._rs, "impl", "")
        )
        if self._mesh_rs is not None:
            arr, n = result
            return np.asarray(arr, dtype=np.uint8)[:, :n]
        return np.asarray(result, dtype=np.uint8)

    def reconstruct(
        self, shards: dict[int, np.ndarray], want: list[int] | None = None
    ) -> dict[int, np.ndarray]:
        out = self._rs.reconstruct(
            {i: np.asarray(s, np.uint8) for i, s in shards.items()}, want=want
        )
        return {i: np.asarray(v) for i, v in out.items()}

    def apply(self, coeffs: np.ndarray, data: np.ndarray) -> np.ndarray:
        return np.asarray(self._rs.apply(coeffs, np.asarray(data, np.uint8)))


# Live FallbackBackend registry for the breaker-health gauge: sampled
# at /metrics scrape time (callback gauge), so an open breaker shows up
# without any code path having to remember to publish it. Weak refs —
# the gauge must never keep a dead backend (and its device state) alive.
_FALLBACKS: "weakref.WeakSet" = weakref.WeakSet()
_fallback_seq = itertools.count()


def _breaker_samples():
    # Dedupe by chip label: several live FallbackBackends can wrap the
    # SAME physical chip (one per pooled backend / Store / EC ratio),
    # and duplicate series in one exposition are invalid Prometheus —
    # the whole scrape would fail exactly when the pod is busy. Any
    # open breaker marks the chip degraded.
    by_chip: dict[str, float] = {}
    for be in list(_FALLBACKS):
        label = be.chip_label or f"{type(be.primary).__name__}@{be._seq}"
        is_open = 1.0 if be.breaker.state == "open" else 0.0
        by_chip[label] = max(by_chip.get(label, 0.0), is_open)
    for label, val in sorted(by_chip.items()):
        yield {"chip": label}, val


_M.REGISTRY.gauge(
    "sw_ec_chip_breaker_open",
    "EC device fallback breaker open per chip (1 = streams on CPU)",
    ("chip",),
    fn=_breaker_samples,
)


class FallbackBackend(_BackendBase):
    """Device backend with a verified CPU escape hatch, mid-batch.

    Wraps a primary (JaxBackend) and a CpuBackend producing bit-identical
    outputs by construction. Every staged handle carries the HOST copy of
    its batch alongside the device handle, so when the device dies
    between dispatch and drain (the to_host block is where a hung/reset
    TPU actually surfaces) the batch is re-encoded on CPU and the encode
    stream continues without data loss — the encoder pipeline never
    learns a failover happened.

    A circuit breaker (utils/retry.py) stops feeding a repeatedly-failing
    device: after `failure_threshold` consecutive device errors all
    batches go straight to CPU until the reset timeout admits a probe.
    InjectedCrash (a BaseException) is NOT absorbed — a simulated process
    death must not turn into a graceful failover.
    """

    def __init__(self, primary: RSBackend, fallback: "CpuBackend", breaker=None):
        self.ctx = primary.ctx
        self.primary = primary
        self.fallback = fallback
        # Both wrapped backends derive from the same ctx, so they share
        # one encoding matrix; expose it like every other backend does
        # (degraded reads precompute decode coefficients from it).
        self.matrix = fallback.matrix
        if breaker is None:
            from ..utils.retry import CircuitBreaker

            breaker = CircuitBreaker(failure_threshold=3, reset_timeout=60.0)
        self.breaker = breaker
        self.fallback_batches = 0  # observability: batches served by CPU
        # Chip identity when this wraps one chip of a pool
        # (ec/chip_pool.py): rides into the fault-point context so
        # chaos tests can kill ONE chip, and into queue stats labels.
        self.chip_label = getattr(primary, "chip_label", "")
        self._seq = next(_fallback_seq)
        _FALLBACKS.add(self)
        self._log = logger("ec.backend")

    # Deterministic caller errors (bad shape/dtype/shard-count): the CPU
    # would fail identically, so they re-raise untouched — counting them
    # against the breaker would demote a healthy device on user input.
    _CALLER_ERRORS = (TypeError, ValueError, ECError)

    def _device_failed(self, stage: str, e: Exception) -> None:
        if isinstance(e, self._CALLER_ERRORS):
            raise e
        self.breaker.record_failure()
        self._log.warning(
            "device backend failed in %s (%s); falling back to CPU "
            "(breaker %s)", stage, e, self.breaker.state,
        )

    # -- synchronous surface ------------------------------------------------

    def encode(self, data: np.ndarray) -> np.ndarray:
        if self.breaker.allows():
            try:
                faults.fire("ec.backend.device.encode", width=data.shape[1])
                out = self.primary.encode(data)
                self.breaker.record_success()
                return out
            except Exception as e:
                self._device_failed("encode", e)
        self.fallback_batches += 1
        return self.fallback.encode(data)

    def apply(self, coeffs: np.ndarray, data: np.ndarray) -> np.ndarray:
        if self.breaker.allows():
            try:
                faults.fire("ec.backend.device.apply")
                out = self.primary.apply(coeffs, data)
                self.breaker.record_success()
                return out
            except Exception as e:
                self._device_failed("apply", e)
        self.fallback_batches += 1
        return self.fallback.apply(coeffs, data)

    def reconstruct(
        self, shards: dict[int, np.ndarray], want: list[int] | None = None
    ) -> dict[int, np.ndarray]:
        if self.breaker.allows():
            try:
                faults.fire("ec.backend.device.reconstruct")
                out = self.primary.reconstruct(shards, want=want)
                self.breaker.record_success()
                return out
            except Exception as e:
                self._device_failed("reconstruct", e)
        self.fallback_batches += 1
        return self.fallback.reconstruct(shards, want=want)

    # -- staged pipeline --------------------------------------------------
    #
    # to_device handles are (host_batch, device_handle|None); dispatched
    # handles are (kind, host_batch, device_result|None, coeffs|None) so
    # to_host knows WHICH computation to replay on CPU when the device
    # dies between dispatch and drain — encode_staged batches re-encode,
    # apply_staged batches re-apply the same coefficients, both
    # bit-identical to what the device would have produced.

    def to_device(self, data: np.ndarray):
        data = np.ascontiguousarray(data, dtype=np.uint8)
        if self.breaker.allows():
            try:
                faults.fire(
                    "ec.backend.device.to_device",
                    width=data.shape[1], chip=self.chip_label,
                )
                return (data, self.primary.to_device(data))
            except Exception as e:
                self._device_failed("to_device", e)
        return (data, None)

    def encode_staged(self, staged):
        host, dev = staged
        if dev is not None:
            try:
                faults.fire(
                    "ec.backend.device.encode_staged", chip=self.chip_label
                )
                return ("encode", host, self.primary.encode_staged(dev), None)
            except Exception as e:
                self._device_failed("encode_staged", e)
        return ("encode", host, None, None)

    def apply_staged(self, coeffs: np.ndarray, staged):
        host, dev = staged
        coeffs = np.ascontiguousarray(coeffs, dtype=np.uint8)
        if dev is not None:
            try:
                faults.fire(
                    "ec.backend.device.apply_staged", chip=self.chip_label
                )
                return (
                    "apply", host, self.primary.apply_staged(coeffs, dev), coeffs
                )
            except Exception as e:
                self._device_failed("apply_staged", e)
        return ("apply", host, None, coeffs)

    def to_host(self, result) -> np.ndarray:
        kind, host, dev, coeffs = result
        if dev is not None:
            try:
                faults.fire("ec.backend.device.to_host", chip=self.chip_label)
                out = np.asarray(self.primary.to_host(dev), dtype=np.uint8)
                self.breaker.record_success()
                return out
            except Exception as e:
                self._device_failed("to_host", e)
        # Mid-batch failover: the host copy recomputes on CPU,
        # bit-identical to what the device would have produced.
        self.fallback_batches += 1
        if kind == "apply":
            return self.fallback.apply(coeffs, host)
        return self.fallback.encode(host)


@functools.lru_cache(maxsize=16)
def get_backend(name: str, data_shards: int, parity_shards: int) -> RSBackend:
    """name: cpu | tpu | auto. 'auto' prefers the TPU when one is
    attached, wrapped in the CPU-fallback shim so a device that dies
    mid-stream degrades to the (bit-identical) CPU path instead of
    failing the encode."""
    ctx = ECContext(data_shards, parity_shards)
    if name == "cpu":
        return CpuBackend(ctx)
    if name == "tpu":
        return JaxBackend(ctx)
    if name == "auto":
        # NEVER call jax.devices() in-process here: with a dead TPU
        # relay the backend init hangs forever, wedging the volume
        # server's first EC generate (and everything queued behind it).
        from ..utils.devices import accelerator_available

        if accelerator_available():
            try:
                return FallbackBackend(JaxBackend(ctx), CpuBackend(ctx))
            except Exception:
                pass
        return CpuBackend(ctx)
    raise ECError(f"unknown EC backend {name!r} (want cpu|tpu|auto)")
