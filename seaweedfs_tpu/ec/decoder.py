"""EC decode: shard files -> normal volume (.dat + .idx).

Reference: weed/storage/erasure_coding/ec_decoder.go — .ecx+.ecj -> .idx
(tombstones appended for journaled deletes), live extent from the max
.ecx entry, de-striping honoring the encode-time layout, and crash-safe
temp+fsync+rename+dir-fsync publication throughout.
"""

from __future__ import annotations

import os
import struct
from typing import Iterator

import numpy as np

from .. import faults
from ..utils import trace
from ..storage.needle import footer_size
from ..storage.super_block import SUPER_BLOCK_SIZE
from ..utils.fs import fsync_dir as _fsync_dir
from ..storage.types import (
    NEEDLE_HEADER_SIZE,
    NEEDLE_MAP_ENTRY_SIZE,
    TOMBSTONE_FILE_SIZE,
    NeedleValue,
    actual_offset,
    padded_record_size,
)
from .context import LARGE_BLOCK_SIZE, SMALL_BLOCK_SIZE, ECError
from .volume_info import VolumeInfo


def iterate_ecx(base: str) -> Iterator[NeedleValue]:
    with open(base + ".ecx", "rb") as f:
        while True:
            b = f.read(NEEDLE_MAP_ENTRY_SIZE)
            if not b:
                return
            if len(b) != NEEDLE_MAP_ENTRY_SIZE:
                raise ECError(f"{base}.ecx: partial trailing record (corrupt)")
            yield NeedleValue.from_bytes(b)


def iterate_ecj(base: str) -> Iterator[int]:
    path = base + ".ecj"
    if not os.path.exists(path):
        return
    with open(path, "rb") as f:
        while True:
            b = f.read(8)
            if len(b) < 8:
                return
            yield struct.unpack(">Q", b)[0]


def has_live_needles(base: str) -> bool:
    """True if .ecx holds at least one non-deleted entry (reference
    HasLiveNeedles; used by ec.decode to no-op fully-deleted volumes).
    Runtime deletes live in .ecj until rebuild_ecx_file folds them in —
    callers run that first, as the reference's decode RPC does."""
    for nv in iterate_ecx(base):
        if not nv.is_deleted:
            return True
    return False


def rebuild_ecx_file(base: str) -> None:
    """Fold the .ecj deletion journal into .ecx as in-place tombstones,
    then drop the journal (reference RebuildEcxFile,
    ec_volume_delete.go:103; run before decode and shard-set moves)."""
    ecj = base + ".ecj"
    if not os.path.exists(ecj):
        return
    size = os.path.getsize(base + ".ecx")
    count = size // NEEDLE_MAP_ENTRY_SIZE
    with open(base + ".ecx", "r+b") as f:

        def search(nid: int) -> int:
            lo, hi = 0, count
            while lo < hi:
                mid = (lo + hi) // 2
                f.seek(mid * NEEDLE_MAP_ENTRY_SIZE)
                entry = NeedleValue.from_bytes(f.read(NEEDLE_MAP_ENTRY_SIZE))
                if entry.needle_id == nid:
                    return mid
                if entry.needle_id < nid:
                    lo = mid + 1
                else:
                    hi = mid
            return -1

        for nid in iterate_ecj(base):
            i = search(nid)
            if i < 0:
                continue
            # size field lives after needleId(8) + offset(4)
            f.seek(i * NEEDLE_MAP_ENTRY_SIZE + 12)
            f.write(struct.pack(">i", TOMBSTONE_FILE_SIZE))
        f.flush()
        os.fsync(f.fileno())
    os.unlink(ecj)
    _fsync_dir(ecj)


def record_actual_size(size: int, version: int) -> int:
    """Full on-disk record length for an idx `size` (GetActualSize)."""
    return padded_record_size(NEEDLE_HEADER_SIZE + size + footer_size(version))


def write_idx_from_ecx(base: str) -> None:
    """.ecx + .ecj -> .idx (sorted entries then journaled tombstones),
    atomically published."""
    idx_path = base + ".idx"
    tmp = idx_path + ".tmp"
    try:
        with open(tmp, "wb") as out, open(base + ".ecx", "rb") as ecx:
            while True:
                chunk = ecx.read(1 << 20)
                if not chunk:
                    break
                out.write(chunk)
            for nid in iterate_ecj(base):
                out.write(NeedleValue(nid, 0, TOMBSTONE_FILE_SIZE).to_bytes())
            out.flush()
            faults.fire("ec.decode.idx.before_fsync", base=base)
            os.fsync(out.fileno())
        faults.fire("ec.decode.idx.before_rename", base=base)
        os.replace(tmp, idx_path)
        _fsync_dir(idx_path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def find_dat_file_size(base: str, version: int) -> int:
    """Live data extent: max over live .ecx entries of record end; at
    least the superblock (reference FindDatFileSize, issue #7748)."""
    dat_size = SUPER_BLOCK_SIZE
    for nv in iterate_ecx(base):
        if nv.is_deleted:
            continue
        end = actual_offset(nv.offset) + record_actual_size(nv.size, version)
        dat_size = max(dat_size, end)
    return dat_size


def write_dat_file(
    base: str,
    dat_file_size: int,
    encoded_dat_file_size: int,
    shard_paths: list[str],
    large_block_size: int = LARGE_BLOCK_SIZE,
    small_block_size: int = SMALL_BLOCK_SIZE,
) -> None:
    """De-stripe the k data shards back into base.dat (first
    dat_file_size bytes). encoded_dat_file_size fixes the block layout;
    pass 0 to infer it from the physical shard size (ambiguous when that
    is an exact large-block multiple — then this fails closed, reference
    writeDatFile)."""
    if not shard_paths:
        raise ECError("no data shard files")
    k = len(shard_paths)

    fds = [os.open(p, os.O_RDONLY) for p in shard_paths]
    dat_path = base + ".dat"
    tmp = dat_path + ".tmp"
    try:
        if encoded_dat_file_size <= 0:
            shard_size = os.fstat(fds[0]).st_size
            if (
                shard_size % large_block_size == 0
                and dat_file_size
                > (shard_size // large_block_size - 1) * large_block_size * k
            ):
                raise ECError(
                    f"shard size {shard_size} does not identify the block "
                    f"layout; re-encode to record the dat size in .vif"
                )
            encoded_dat_file_size = k * shard_size
        if dat_file_size > encoded_dat_file_size:
            raise ECError(
                f"dat size {dat_file_size} exceeds encoded size {encoded_dat_file_size}"
            )

        large_rows = encoded_dat_file_size // (k * large_block_size)

        def read_plan():
            """(fd, offset, length) pieces in .dat order: large rows,
            then small rows; within a row, shard order."""
            remaining = dat_file_size
            row = 0
            while remaining > 0:
                if row < large_rows:
                    block = large_block_size
                    off = row * large_block_size
                else:
                    block = small_block_size
                    off = large_rows * large_block_size + (
                        row - large_rows
                    ) * small_block_size
                for fd in fds:
                    if remaining <= 0:
                        break
                    take = min(remaining, block)
                    pos = 0
                    while pos < take:
                        piece = min(4 << 20, take - pos)
                        yield fd, off + pos, piece
                        pos += piece
                    remaining -= take
                row += 1

        with open(tmp, "wb") as out:
            # Shared recovery pipeline (ec/pipeline.py, pass-through
            # configuration of the staged-apply driver): shard preads in
            # the reader thread overlap the sequential .dat writes in
            # the writer thread — the serial read→write loop left the
            # output disk idle during every input read. There is nothing
            # to compute here (all k data shards are on disk; a missing
            # one is regenerated through the staged rebuild before
            # decode starts, see ec_decode_volume).
            from . import native_io
            from .pipeline import run_staged_apply

            def produce():
                # Zero-copy plane: each piece lands in a numpy buffer
                # (native batched pread when available, preadv loop
                # otherwise) and is handed to the writer as-is — no
                # bytes objects, no b"".join of short-read fragments.
                for fd, off, want in read_plan():
                    buf = np.empty(want, dtype=np.uint8)
                    try:
                        native_io.read_exact_into(fd, buf, off)
                    except OSError as e:
                        raise ECError(
                            f"short shard read at {off}: {e}"
                        ) from e
                    yield None, buf

            sp = trace.current()  # the ec.decode root, when armed
            run_staged_apply(
                None,
                None,
                produce,
                lambda _tag, chunk: out.write(chunk),
                describe="ec decode pipeline",
                span=sp,
                read_stage="disk_read",
                write_stage="write_sink",
            )
            with trace.stage(sp, "fsync_publish"):
                out.flush()
                faults.fire("ec.decode.dat.before_fsync", base=base)
                os.fsync(out.fileno())
        faults.fire("ec.decode.dat.before_rename", base=base)
        with trace.stage(sp, "fsync_publish"):
            os.replace(tmp, dat_path)
            _fsync_dir(dat_path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    finally:
        for fd in fds:
            os.close(fd)


def ec_decode_volume(base: str, ctx=None, backend=None, scheduler=None) -> bool:
    """Shards -> normal volume. Returns False (no-op) when no live
    needles remain. Layout and version come from the .vif.

    Degraded decode: a missing or corrupt DATA shard no longer refuses
    or launders rot — the staged rebuild path runs first
    (sidecar-verified verify-and-exclude, crash-safe temp+rename
    publish, H2D/compute/D2H overlap on a device), regenerating absent
    data shards and replacing present-but-rotten ones, healing the
    shard set as a side effect; the de-stripe then proceeds with k
    verified data shards on disk. The verification pass reads every
    present shard once — decode is a maintenance op, and publishing a
    .dat de-striped from unverified bytes would defeat the sidecar.
    Fewer than k good shards still fails closed inside rebuild.
    `scheduler` is the QueueScope the self-heal stream runs under
    (server wiring passes the Store's scope)."""
    vi = VolumeInfo.maybe_load(base + ".vif") or VolumeInfo()
    if ctx is None:
        from .context import DEFAULT_EC_CONTEXT

        ctx = vi.ec_ctx or DEFAULT_EC_CONTEXT
    sp = trace.start("ec.decode", name=os.path.basename(base), base=base)
    try:
        with trace.activate(sp):
            rebuild_ecx_file(base)
            if not has_live_needles(base):
                return False
            write_idx_from_ecx(base)
            dat_size = find_dat_file_size(base, vi.version)
            shard_paths = [
                base + ctx.to_ext(i) for i in range(ctx.data_shards)
            ]
            missing_ids = [
                i for i, p in enumerate(shard_paths) if not os.path.exists(p)
            ]
            from .rebuild import rebuild_ec_files

            # Always invoked: with nothing missing this is the sidecar
            # verify(-and-repair-in-place) of every present shard;
            # `only_shards` keeps absent-shard regeneration scoped to
            # the data shards decode needs (a parity shard lost on a
            # subset holder is not this op's business to mint). The
            # self-heal runs as a RECOVERY stream on the shared device
            # queue: colocated foreground encode/reads go first.
            rebuild_ec_files(
                base, ctx, backend=backend, only_shards=missing_ids,
                priority="recovery", scheduler=scheduler,
            )
            still = [p for p in shard_paths if not os.path.exists(p)]
            if still:  # pragma: no cover - rebuild publishes or raises
                raise ECError(f"missing data shards for decode: {still}")
            write_dat_file(base, dat_size, vi.dat_file_size, shard_paths)
            return True
    finally:
        trace.finish(sp)


