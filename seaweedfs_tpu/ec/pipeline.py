"""Shared bounded-queue recovery pipeline + fused shard sinks.

Factored out of the encoder (PR 2) so rebuild and decode run the same
4-stage overlap the encode path already enjoyed: disk read (reader
thread) / H2D stage + device dispatch (calling thread) / D2H + disk
write with CRC rolled cache-hot (writer thread), with bounded queues
between stages. BENCH_r03 measured 87% of encode e2e as host-side
overhead before the encoder grew this shape; the serial
read→reconstruct→write loops in rebuild/decode had the same disease.

Shutdown discipline (inherited verbatim from the encoder, where it was
hardened against hung-device postmortems): both worker threads are
JOINED before any caller-owned fd may be closed; on error the abort
event stops the producer (its queue put is abort-aware), the consumer
always drains to the None sentinel, and a thread that refuses to die
raises — truncated output with self-consistent CRCs must never be
reported as success.
"""

from __future__ import annotations

import queue as _queue
import threading as _threading
import time as _time
from typing import Callable, Iterator, Sequence

import numpy as np

from ..utils import trace
from .bitrot import (
    BitrotProtection,
    ShardChecksumBuilder,
)
from .context import BITROT_BLOCK_SIZE, ECContext, ECError


def _traced_produce(span, stage: str, produce):
    """Wrap a producer generator so time spent INSIDE it (disk reads)
    is attributed per batch; time blocked handing batches downstream is
    the queue's to report."""

    def wrapped():
        it = produce()
        while True:
            t0 = _time.perf_counter()
            try:
                item = next(it)
            except StopIteration:
                return
            span.add_stage(stage, _time.perf_counter() - t0)
            yield item

    return wrapped


def _traced_call(span, stage: str, fn):
    def wrapped(item):
        t0 = _time.perf_counter()
        try:
            return fn(item)
        finally:
            span.add_stage(stage, _time.perf_counter() - t0)

    return wrapped


def run_pipeline(
    produce: Callable[[], Iterator],
    transform: Callable,
    consume: Callable,
    *,
    queue_size: int = 2,
    join_timeout: float = 120.0,
    describe: str = "ec pipeline",
    span=None,
    stage_names: tuple = (None, None, None),
) -> None:
    """Run `produce()` items through `transform` then `consume` as three
    overlapped stages.

    - `produce()` is a generator, iterated in a reader thread (disk
      reads happen here, overlapping everything downstream).
    - `transform(item)` runs in the calling thread — the place for
      non-blocking device dispatch (H2D + kernel launch). Its return
      value is handed to `consume`.
    - `consume(result)` runs in a writer thread — the place that may
      BLOCK on device results (to_host) and disk writes, while the
      calling thread keeps dispatching the batches queued behind it.

    Queue residency bound: up to `2*queue_size` items are alive at once
    (one per stage plus the queues); callers sizing device memory must
    budget accordingly.

    `span` + `stage_names` attribute wall time to the flight recorder
    (utils/trace.py): stage_names is (produce, transform, consume) —
    a None name skips tagging that stage (the caller tags finer-grained
    stages inside its own closure). Time blocked on a FULL bounded
    queue is tagged "queue_wait" (backpressure from the slower
    neighbor), measured only when the put actually blocks. span=None
    (the disarmed tracer) leaves every closure untouched.
    """
    if span is not None:
        if stage_names[0]:
            produce = _traced_produce(span, stage_names[0], produce)
        if stage_names[1]:
            transform = _traced_call(span, stage_names[1], transform)
        if stage_names[2]:
            consume = _traced_call(span, stage_names[2], consume)
    read_q: "_queue.Queue" = _queue.Queue(maxsize=queue_size)
    write_q: "_queue.Queue" = _queue.Queue(maxsize=queue_size)
    abort = _threading.Event()
    errors: list[BaseException] = []

    def _put(q, item) -> bool:
        """Abort-aware put: never blocks forever on a full queue whose
        consumer has stopped."""
        try:
            q.put_nowait(item)
            return True
        except _queue.Full:
            pass
        t0 = _time.perf_counter() if span is not None else 0.0
        try:
            while True:
                try:
                    q.put(item, timeout=0.2)
                    return True
                except _queue.Full:
                    if abort.is_set():
                        return False
        finally:
            if span is not None:
                span.add_stage(
                    "queue_wait", _time.perf_counter() - t0
                )

    def reader():
        try:
            for item in produce():
                if abort.is_set():
                    return
                if not _put(read_q, item):
                    return
        except BaseException as e:  # pragma: no cover - disk errors
            errors.append(e)
            abort.set()
        finally:
            _put(read_q, None)

    def writer():
        try:
            while True:
                item = write_q.get()
                if item is None:
                    return
                consume(item)
        except BaseException as e:  # pragma: no cover - disk errors
            errors.append(e)
            abort.set()
            while write_q.get() is not None:
                pass

    rt = _threading.Thread(target=reader, daemon=True)
    wt = _threading.Thread(target=writer, daemon=True)
    rt.start()
    wt.start()
    try:
        while True:
            item = read_q.get()
            if item is None or abort.is_set():
                break
            if not _put(write_q, transform(item)):
                break
    except BaseException as e:
        errors.append(e)
    finally:
        # JOIN both threads before the caller may close any fd — a
        # reader mid-pread on a closed (possibly reused) fd would read
        # someone else's file. The writer always drains write_q until
        # the None sentinel (its error path keeps consuming), so a
        # BLOCKING put(None) never deadlocks and never drops queued
        # batches on the happy path.
        if errors:
            abort.set()
            try:
                while True:
                    read_q.get_nowait()
            except _queue.Empty:
                pass
        write_q.put(None)
        rt.join(timeout=join_timeout)
        wt.join(timeout=join_timeout)
        if rt.is_alive() or wt.is_alive():  # pragma: no cover
            # A stuck thread (e.g. wedged in a device to_host against a
            # hung TPU relay) means the output files are TRUNCATED but
            # any CRC builders are self-consistent with the truncation —
            # returning success here would publish undetectable data
            # loss. Chain the root cause so it isn't masked.
            abort.set()
            raise ECError(
                f"{describe} thread did not finish (producer alive="
                f"{rt.is_alive()}, consumer alive={wt.is_alive()}); "
                f"output is incomplete"
            ) from (errors[0] if errors else None)
    if errors:
        raise errors[0]


def run_staged_apply(
    backend,
    coeffs,
    produce: Callable[[], Iterator],
    consume: Callable,
    *,
    queue_size: int = 2,
    join_timeout: float = 120.0,
    describe: str = "ec staged apply",
    priority: str = "recovery",
    device_queue="auto",
    scheduler=None,
    cost_hint: int = 0,
    wide: bool = False,
    span=None,
    read_stage: str = "disk_read",
    write_stage: str = "write_sink",
) -> None:
    """The staged device `apply` driver shared by rebuild, decode, and
    degraded reconstruction: run_pipeline where the transform stage is
    `backend.apply_staged(coeffs, backend.to_device(batch))` — a
    NON-BLOCKING H2D upload + device dispatch — and the writer stage
    forces the result with `backend.to_host` before handing the host
    uint8 matrix to `consume`. Batch N computes on the device while
    batch N+1 uploads and batch N-1 drains, the same double-buffered
    window `encode_staged` gave the encoder.

    `produce()` yields `(tag, batch)` pairs; `consume(tag, out)` gets
    the tag back untouched (offset bookkeeping stays with the caller).
    `coeffs=None` is the pass-through configuration: no device
    round-trip, the batch flows to `consume` unchanged (decode's
    de-stripe, where reads must overlap writes but there is nothing to
    compute).

    The device dispatch is a CLIENT of the shared per-chip scheduler
    (ec/device_queue.py): `priority` tags this stream's class
    (foreground|recovery|scrub) and `device_queue` selects the queue —
    "auto" resolves the stream's PLACEMENT (ec/chip_pool.py: on a
    multi-chip mesh backend the whole stream is routed to the
    least-loaded chip's backend+queue unless `wide` and the pod is
    idle, per `scheduler`'s `ec_placement` mode), an explicit
    DeviceQueue pins one on the given backend (tests), None keeps the
    PR 3 private window. `scheduler` is the QueueScope (None = the
    process-wide default scope); `cost_hint` is the stream's estimated
    total admission cost (rows x bytes) used for least-loaded routing.
    Per-batch admission is cost-denominated (out_rows x width, see
    device_queue.batch_cost), so a 1-row reconstruction stream no
    longer charges like a parity encode. With the scheduler on, the
    chip-wide in-flight bound lives in the queue's window; without it,
    up to ~2*queue_size staged batches are alive at once per call site.

    `span` is the op's flight-recorder span (utils/trace.py; None =
    disarmed): the produce stage is tagged `read_stage` per batch, the
    H2D upload + device dispatch "h2d_dispatch", the blocking to_host
    "device_drain", the consume callback `write_stage`, bounded-queue
    backpressure "queue_wait", and (on the scheduled path) the
    admission wait "admission_wait" — all labeled with the chip the
    stream landed on.
    """
    if coeffs is None:
        run_pipeline(
            produce,
            lambda item: item,
            lambda item: consume(item[0], item[1]),
            queue_size=queue_size,
            join_timeout=join_timeout,
            describe=describe,
            span=span,
            stage_names=(read_stage, None, write_stage),
        )
        return
    coeffs = np.ascontiguousarray(coeffs, dtype=np.uint8)
    placement = None
    if device_queue == "auto":
        from .chip_pool import place_stream

        placement = place_stream(
            backend, priority,
            scope=scheduler, cost_hint=cost_hint, wide=wide, span=span,
        )
        backend = placement.backend
        device_queue = placement.queue
    chip = getattr(backend, "chip_label", "")

    if device_queue is None:

        def transform(item):
            tag, batch = item
            with trace.stage(span, "h2d_dispatch", chip):
                handle = backend.apply_staged(
                    coeffs, backend.to_device(batch)
                )
            return tag, handle

        def drain(item):
            tag, handle = item
            # Blocks until the device result is ready — while it does,
            # the calling thread keeps dispatching the batches queued
            # behind it.
            with trace.stage(span, "device_drain", chip):
                out = np.ascontiguousarray(
                    backend.to_host(handle), dtype=np.uint8
                )
            with trace.stage(span, write_stage):
                consume(tag, out)

        try:
            run_pipeline(
                produce,
                transform,
                drain,
                queue_size=queue_size,
                join_timeout=join_timeout,
                describe=describe,
                span=span,
                stage_names=(read_stage, None, None),
            )
        finally:
            if placement is not None:
                placement.close()
        return

    from .device_queue import batch_cost

    out_rows = int(coeffs.shape[0])
    stream = device_queue.stream(priority, label=describe, span=span)

    def transform_q(item):
        tag, batch = item
        width = (
            int(batch.shape[-1])
            if getattr(batch, "ndim", 1) > 1
            else int(getattr(batch, "nbytes", len(batch)))
        )
        ticket, handle = stream.dispatch(
            lambda: backend.apply_staged(coeffs, backend.to_device(batch)),
            batch_cost(out_rows, width),
        )
        return tag, ticket, handle

    def drain_q(item):
        tag, ticket, handle = item
        try:
            with trace.stage(span, "device_drain", device_queue.label):
                out = np.ascontiguousarray(
                    backend.to_host(handle), dtype=np.uint8
                )
        finally:
            # Success or failure, the window slot frees — a dying stream
            # must not wedge the chip for the other streams.
            stream.release(ticket)
        with trace.stage(span, write_stage):
            consume(tag, out)

    try:
        run_pipeline(
            produce,
            transform_q,
            drain_q,
            queue_size=queue_size,
            join_timeout=join_timeout,
            describe=describe,
            span=span,
            stage_names=(read_stage, None, None),
        )
    finally:
        # Batches parked in an aborted pipeline's write queue never
        # reach drain_q; their slots are released here — and the chip's
        # placement charge drains with the stream.
        stream.close()
        if placement is not None:
            placement.close()


# --------------------------------------------------------------------------
# Shard sinks: the write stage shared by encode and rebuild. Both write
# N parallel byte streams (one per shard file) while rolling the bitrot
# CRCs in the same pass the bytes are cache-hot.
# --------------------------------------------------------------------------


class FusedShardSink:
    """Write stage backed by the STATEFUL native sink (sn_sink_*): one
    GIL-releasing C++ call per batch, a worker thread per shard,
    pwrite(2) at internally-tracked offsets straight from the source
    buffers — no tobytes()/slice copies, and the Python file objects'
    positions are never moved. This is what closed the BENCH_r03
    finding that 87% of encode e2e wall time was host-side overhead
    (reference equivalent: the single fused encode+CRC loop in
    weed/storage/erasure_coding/ec_encoder.go, and the native volume
    server's byte path the reference grew for the same reason).

    With `leaf_size` set, BOTH sidecar CRC levels come out of ONE
    cache-hot byte pass on the C++ side: leaves are byte-rolled and the
    block level is folded from completed leaf CRCs via the cached
    CRC-shift operator (sn_crc32c_combine) — no Python-side folding,
    no second pass over the bytes.
    `early_writeback` starts background writeback for each just-written
    extent (sync_file_range) so the publish-time fsync drains an
    already-flushing range instead of the whole file — a win on slow
    disks with deep page caches, a loss on filesystems whose write(2)
    is already synchronous (measured -15% on 9p), so it defaults to the
    SEAWEED_EC_EARLY_WB env knob (off unless "1").

    `direct` opts the shard fds into O_DIRECT (page-cache-bypassing)
    writes WHILE every append stays 4096-aligned: the pooled matrices
    are 4096-aligned by construction, so full batches qualify, and the
    ragged tail (or a filesystem that rejects the flag/write — 9p)
    drops that fd back to buffered transparently, bit-identically.
    Defaults to the SEAWEED_EC_ODIRECT env knob (off unless "1"): a win
    for encode/rebuild streams larger than RAM (no page-cache
    eviction storm at fsync), pointless when the page cache absorbs
    the volume anyway.
    """

    def __init__(
        self,
        files: list,
        block_size: int = BITROT_BLOCK_SIZE,
        leaf_size: int = 0,
        early_writeback: bool | None = None,
        direct: bool | None = None,
    ):
        import os as _os

        from ..utils import native

        if early_writeback is None:
            early_writeback = (
                _os.environ.get("SEAWEED_EC_EARLY_WB", "0") == "1"
            )
        if direct is None:
            direct = _os.environ.get("SEAWEED_EC_ODIRECT", "0") == "1"
        if leaf_size and block_size % leaf_size != 0:
            raise ECError(
                f"leaf size {leaf_size} does not divide block size {block_size}"
            )
        self.fds = [f.fileno() for f in files]
        n = len(files)
        self.block_size = block_size
        self.leaf_size = leaf_size
        self._sink = native.NativeSink(
            self.fds, block_size, leaf_size,
            early_writeback=early_writeback, direct=direct,
        )
        self.crcs: list[list[int]] = [[] for _ in range(n)]
        self._leaf_crcs: list[list[int]] = [[] for _ in range(n)]
        self.sizes = [0] * n
        self._out: tuple | None = None
        self._finished = False
        self._direct_flags = None

    def direct_flags(self):
        """Per-shard O_DIRECT engagement (u8[n], 1 = still direct) —
        whether the page-cache bypass survived this stream's alignment;
        all-zero when SEAWEED_EC_ODIRECT is off or the fs refused.
        Snapshotted at finish (the native handle is freed there)."""
        if self._direct_flags is not None:
            return self._direct_flags
        return self._sink.direct_flags()

    def append_rows(self, rows: Sequence[np.ndarray]) -> None:
        """Append one equal-width batch to every shard stream; rows[i]
        goes to fds[i]. Rows must be 1-D C-contiguous uint8 (row views
        of a contiguous matrix qualify — no copies are made), and must
        stay alive until this call returns (the C side writes straight
        from them)."""
        n = len(self.fds)
        if len(rows) != n:
            raise ECError(f"expected {n} rows, got {len(rows)}")
        if self._finished:
            raise ECError("shard sink already finished")
        width = len(rows[0])
        if any(len(r) != width for r in rows):
            raise ECError("shard sink rows have unequal widths")
        granule = self.leaf_size or self.block_size
        max_out = width // granule + 2
        out = self._out
        if out is None or out[0].shape[1] < max_out:
            out = (
                np.empty((n, max_out), np.uint32),  # block crcs
                np.empty(n, np.int32),
                np.empty((n, max_out), np.uint32),  # leaf crcs
                np.empty(n, np.int32),
            )
            self._out = out
        ptrs = []
        for r in rows:
            if not (r.flags.c_contiguous and r.dtype == np.uint8):
                raise ECError("shard sink rows must be contiguous uint8")
            ptrs.append(r.ctypes.data)
        obc, obn, olc, oln = out
        # overflow (count -1) cannot reach here: the C side flags the
        # shard failed and NativeSink.append raises OSError first
        self._sink.append(ptrs, width, obc, obn, olc, oln)
        for i in range(n):
            c = int(obn[i])
            if c:
                self.crcs[i].extend(int(x) for x in obc[i, :c])
            if self.leaf_size:
                c = int(oln[i])
                if c:
                    self._leaf_crcs[i].extend(int(x) for x in olc[i, :c])
            self.sizes[i] += width

    def _finish(self) -> None:
        if self._finished:
            return
        self._finished = True
        self._direct_flags = self._sink.direct_flags()
        tb, tbv, tl, tlv, _sizes = self._sink.finish()
        for i in range(len(self.fds)):
            if tbv[i]:
                self.crcs[i].append(int(tb[i]))
            if self.leaf_size and tlv[i]:
                self._leaf_crcs[i].append(int(tl[i]))
        self._sink.destroy()

    def block_crcs(self) -> list[list[int]]:
        self._finish()
        return [list(c) for c in self.crcs]

    def leaf_crcs(self) -> list[list[int]]:
        self._finish()
        return [list(c) for c in self._leaf_crcs] if self.leaf_size else []

    def to_protection(self, ctx: ECContext) -> BitrotProtection:
        import uuid as _uuid

        return BitrotProtection(
            ctx=ctx,
            block_size=self.block_size,
            uuid=_uuid.uuid4().bytes,
            shard_sizes=list(self.sizes),
            shard_crcs=self.block_crcs(),
            leaf_size=self.leaf_size,
            shard_leaf_crcs=self.leaf_crcs(),
        )


class PyShardSink:
    """Pure-Python fallback write stage (native .so unavailable, or a
    byte-mutating fault point needs materialized bytes)."""

    def __init__(
        self,
        files: list,
        block_size: int = BITROT_BLOCK_SIZE,
        leaf_size: int = 0,
    ):
        self.files = files
        self.block_size = block_size
        self.leaf_size = leaf_size
        self.builders = [
            ShardChecksumBuilder(block_size, leaf_size) for _ in files
        ]

    @property
    def sizes(self) -> list[int]:
        return [b.total for b in self.builders]

    def append_rows(self, rows: Sequence) -> None:
        if len(rows) != len(self.files):
            raise ECError(f"expected {len(self.files)} rows, got {len(rows)}")
        for i, (f, row) in enumerate(zip(self.files, rows)):
            b = row if isinstance(row, (bytes, bytearray)) else np.asarray(
                row, dtype=np.uint8
            ).tobytes()
            mv = memoryview(b)
            while mv:  # raw FileIO may short-write
                mv = mv[f.write(mv) :]
            self.builders[i].write(b)

    def block_crcs(self) -> list[list[int]]:
        return [b.finish() for b in self.builders]

    def leaf_crcs(self) -> list[list[int]]:
        if not self.leaf_size:
            return []
        return [b.finish_leaves() for b in self.builders]

    def to_protection(self, ctx: ECContext) -> BitrotProtection:
        return BitrotProtection.from_builders(ctx, self.builders)


def make_shard_sink(
    files: list,
    block_size: int = BITROT_BLOCK_SIZE,
    leaf_size: int = 0,
    prefer_fused: bool = True,
) -> FusedShardSink | PyShardSink:
    """Fused native sink when the .so is available (and the native
    plane isn't disabled via SEAWEED_EC_NATIVE=0), Python otherwise."""
    from . import native_io

    if prefer_fused and native_io.enabled():
        try:
            return FusedShardSink(files, block_size, leaf_size)
        except Exception:
            pass
    return PyShardSink(files, block_size, leaf_size)
