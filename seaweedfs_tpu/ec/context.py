"""EC layout constants and context.

Reference: weed/storage/erasure_coding/ec_encoder.go:21-28 — default 10+4,
max 32 shards, 1GB large blocks then 1MB small blocks, row-major striping
over the data shards.
"""

from __future__ import annotations

from dataclasses import dataclass, field

DATA_SHARDS = 10
PARITY_SHARDS = 4
TOTAL_SHARDS = DATA_SHARDS + PARITY_SHARDS
MAX_SHARD_COUNT = 32  # ShardBits is a uint32 bitmap
LARGE_BLOCK_SIZE = 1024 * 1024 * 1024  # 1GB
SMALL_BLOCK_SIZE = 1024 * 1024  # 1MB

# Bitrot sidecar granularity (reference ec_bitrot.go BitrotBlockSize).
BITROT_BLOCK_SIZE = 16 * 1024 * 1024  # 16 MiB

# Sub-block leaf granularity for the v2 .ecsum sidecar: degraded reads
# verify and reconstruct only the leaves covering the requested extent,
# cutting the verified-degraded-read amplification by up to
# BITROT_BLOCK_SIZE / BITROT_LEAF_SIZE (256x at the defaults). 0
# disables leaves (writes a v1 sidecar).
BITROT_LEAF_SIZE = 64 * 1024  # 64 KiB

# Quarantined shard suffix: scrub renames corrupt shards to
# <shard>.bad so they can never be fed to Reed-Solomon (kept for
# forensics until a verified replacement lands).
QUARANTINE_SUFFIX = ".bad"


class ECError(Exception):
    pass


@dataclass(frozen=True)
class ECContext:
    """Shard-count configuration for one EC volume."""

    data_shards: int = DATA_SHARDS
    parity_shards: int = PARITY_SHARDS

    def __post_init__(self):
        if self.data_shards <= 0 or self.parity_shards <= 0:
            raise ECError(f"invalid EC config {self}")
        if self.total > MAX_SHARD_COUNT:
            raise ECError(f"{self}: total shards exceed {MAX_SHARD_COUNT}")

    @property
    def total(self) -> int:
        return self.data_shards + self.parity_shards

    def to_ext(self, shard_id: int) -> str:
        """Shard file extension (reference ToExt: '.ec00' .. '.ec31')."""
        if not 0 <= shard_id < self.total:
            raise ECError(f"shard id {shard_id} out of range for {self}")
        return f".ec{shard_id:02d}"

    def __str__(self) -> str:
        return f"{self.data_shards}+{self.parity_shards}"


DEFAULT_EC_CONTEXT = ECContext()
