"""Shared per-chip device-queue scheduler for the EC compute pipeline.

Before this module every staged-apply call site (encode, rebuild,
decode self-heal, wide degraded reads) drove its own private in-flight
window against the device, so a background rebuild and a foreground
encode on the same chip serialized at the JAX runtime's mercy — or
fought for HBM with two uncoordinated windows. Haystack-style stores
avoid exactly this by prioritizing serving traffic over repair; the
ROADMAP named the shared scheduler as the open perf item from PR 3.

Model
-----

One `DeviceQueue` per chip. A single-device backend is one chip; a
column-mesh backend spans several chips but dispatches as a unit, so it
still gets ONE queue — the pod-level answer is `ec/chip_pool.py`, which
places whole streams onto per-chip backends (each with its own queue
from this module) instead of slicing every stream across the mesh.
Producers open a `DeviceStream` tagged with a priority class and submit
batches through it; the queue admits batch dispatches (the H2D +
device-dispatch step) one at a time under a policy, and bounds the
TOTAL number of in-flight device batches across all streams (`window` —
the device-memory residency bound that used to be per call site).

Priority classes, highest first:

- ``foreground`` — encode, degraded reads (serving traffic);
- ``recovery``  — rebuild, decode self-heal (restore redundancy);
- ``scrub``     — scrub-initiated repair (background hygiene).

Cost model
----------

Admission is denominated in COST UNITS, not payload bytes: one unit is
one output row-byte (``out_rows x batch_width``, see
:func:`batch_cost`). Device time for a GF(256) apply scales with the
output rows it computes, so a 1-row degraded reconstruction of a 64 KiB
leaf (cost 64Ki) no longer counts like a full parity encode of the same
width (cost m x width = 4 x width at 10+4): under the minimum-share
policy a recovery stream of single-row repairs gets proportionally MORE
batches admitted per unit of banked credit than a byte-denominated
accounting would allow — the heterogeneous-batch fairness the ROADMAP
recorded after PR 4.

Admission is strict-priority with a weighted-deficit minimum share for
the background classes: every cost unit admitted for a higher class
banks ``share/(1-share)`` units of credit for each LOWER class that has
work waiting; a lower class whose credit covers its head batch is
admitted ahead of the higher class. Under saturation each background
class therefore gets ~``share`` of admitted cost (no starvation), while
an arriving foreground batch goes ahead of every queued background
batch that is not yet "due" (batch-granularity preemption: a long
rebuild window can no longer head-of-line-block an encode — the rebuild
yields the H2D slot at its next batch boundary). ``share=0`` degrades
to strict priority for that class.

Fault semantics are unchanged and PER STREAM: the queue never touches
batch payloads or results, so a FallbackBackend device death between
dispatch and drain replays only the dying stream's in-flight batches on
CPU (the carried host copies), other streams keep the device until the
shared breaker trips, and bit-identity of every stream's output to the
synchronous apply holds by construction. A stream that dies releases
its window slots (``DeviceStream.close`` is leak-proof), so one
aborted producer can never wedge the chip for everyone else.

Scopes
------

Knobs live in a :class:`QueueScope` — one config domain with its own
queue registry. The module-level :func:`configure` / :func:`for_backend`
/ :func:`stats_snapshot` operate on the process-wide DEFAULT scope
(kept for embedders and tests; still last-caller-wins there), while a
`Store` may carry its own scope so two tenants in one process stop
clobbering each other's shares/window/placement (`storage/store.py`
threads it exactly like the shared interval cache). Per-class
depth/wait/throughput counters surface through ``stats_snapshot`` and
the Prometheus registry (``sw_ec_queue_*``), keyed per chip: each queue
carries a ``chip`` label (the device id for pool chips, the backend
class name otherwise), so a second chip's counters land in their own
gauge set instead of silently aliasing into the first's.
"""

from __future__ import annotations

import contextlib
import itertools
import threading
import time
import weakref
from collections import deque

from ..utils import metrics as _M
from .context import ECError

# Highest priority first; admission prefers earlier classes.
PRIORITIES = ("foreground", "recovery", "scrub")

# Minimum admitted-cost share per background class under saturation.
# Small on purpose: this is a SERVING store — repair proceeds, but
# foreground keeps ~90% of the chip when it wants it (the bench
# acceptance bar is foreground >= 85% of isolated throughput with a
# concurrent rebuild stream still making progress).
DEFAULT_SHARES = {"recovery": 0.10, "scrub": 0.02}

# Default bound on in-flight device batches across ALL streams of one
# chip. PR 3's per-call-site windows allowed ~2*queue_size = 4 staged
# batches each; the shared window keeps the same residency for the chip
# as one saturated call site used to claim.
DEFAULT_WINDOW = 4

# Stream placement policy for multi-chip (mesh-capable) backends — see
# ec/chip_pool.py. "auto" routes each new stream to the least-loaded
# chip unless the stream is explicitly wide and the pod is idle;
# "chip" always routes; "mesh" always column-slices (the PR 4 shape).
PLACEMENT_MODES = ("auto", "mesh", "chip")
DEFAULT_PLACEMENT = "auto"

# Credit never banks more than this many cost units per class: a
# background class idle through a long foreground burst must not repay
# itself with an equally long background burst afterwards.
CREDIT_CAP_COST = 1 << 30

# Admission liveness bound. Window slots are freed by OTHER streams'
# drain threads; a stream wedged in to_host against a hung device holds
# its slots and (unlike the pre-scheduler private windows) would freeze
# every other stream's dispatch on the chip, silently and forever —
# run_pipeline's join_timeout can never fire for a thread stuck INSIDE
# the transform stage. Past this deadline admission raises instead:
# a loud per-stream ECError (callers fail/retry/fall back) beats a
# chip-wide freeze with no error. Generous on purpose — only a truly
# wedged chip waits minutes for a slot.
DEFAULT_ADMIT_TIMEOUT = 300.0

_queue_depth = _M.REGISTRY.gauge(
    "sw_ec_queue_depth", "EC device-queue waiting batches", ("cls", "chip")
)
_queue_inflight = _M.REGISTRY.gauge(
    "sw_ec_queue_inflight", "EC device-queue in-flight batches", ("cls", "chip")
)
_queue_admitted = _M.REGISTRY.counter(
    "sw_ec_queue_admitted_total",
    "EC device-queue admitted batches", ("cls", "chip"),
)
_queue_admitted_cost = _M.REGISTRY.counter(
    "sw_ec_queue_admitted_cost_total",
    "EC device-queue admitted cost units (output rows x batch width)",
    ("cls", "chip"),
)
_queue_wait_seconds = _M.REGISTRY.counter(
    "sw_ec_queue_wait_seconds_total",
    "EC device-queue admission wait", ("cls", "chip"),
)


def batch_cost(out_rows: int, width: int) -> int:
    """Admission cost of one batch: output rows x batch width (bytes per
    row). Tracks device time — a GF(256) apply computes out_rows x k x
    width byte-products, and k is fixed per volume — so a 1-row
    reconstruction is ~1/m the cost of a parity encode at equal width."""
    return max(int(out_rows), 1) * max(int(width), 1)


class _Waiter:
    __slots__ = ("priority", "cost", "t_submit")

    def __init__(self, priority: str, cost: int, t_submit: float):
        self.priority = priority
        self.cost = cost
        self.t_submit = t_submit


class Ticket:
    """One admitted (in-flight) batch; released after to_host drains it
    (or the stream dies). Idempotent release — close() may race a drain
    thread's finally. `wait_s` is the admission wait this batch paid
    (the flight recorder's "admission_wait" stage)."""

    __slots__ = ("priority", "cost", "released", "wait_s")

    def __init__(self, priority: str, cost: int, wait_s: float = 0.0):
        self.priority = priority
        self.cost = cost
        self.released = False
        self.wait_s = wait_s


class ClassStats:
    __slots__ = (
        "submitted", "admitted", "admitted_cost", "drained",
        "drained_cost", "wait_s_total", "wait_s_max", "inflight",
    )

    def __init__(self):
        self.submitted = 0
        self.admitted = 0
        self.admitted_cost = 0
        self.drained = 0
        self.drained_cost = 0
        self.wait_s_total = 0.0
        self.wait_s_max = 0.0
        self.inflight = 0

    def as_dict(self, depth: int) -> dict:
        return {
            "depth": depth,
            "inflight": self.inflight,
            "submitted": self.submitted,
            "admitted": self.admitted,
            "admitted_cost": self.admitted_cost,
            "drained": self.drained,
            "drained_cost": self.drained_cost,
            "wait_s_total": round(self.wait_s_total, 6),
            "wait_s_max": round(self.wait_s_max, 6),
        }


class DeviceStream:
    """One producer's tagged batch stream into a DeviceQueue. Not
    thread-safe for concurrent dispatch (each pipeline dispatches from
    one thread), but release/close may run from the drain thread.
    `span` (utils/trace.py, None = tracer disarmed) gets per-batch
    "admission_wait" and "h2d_dispatch" stages labeled with this
    queue's chip."""

    def __init__(
        self,
        queue: "DeviceQueue",
        priority: str,
        label: str = "",
        span=None,
    ):
        self.queue = queue
        self.priority = priority
        self.label = label
        self.span = span
        self._outstanding: set[Ticket] = set()
        self._lock = threading.Lock()

    def dispatch(self, fn, cost: int):
        """Block until this stream's batch is admitted under the queue
        policy, then run `fn()` (the caller's H2D upload + non-blocking
        device dispatch) and return ``(ticket, handle)``. `cost` is the
        batch's admission weight in cost units (see :func:`batch_cost`).
        The window slot is held until :meth:`release` — call it after
        `to_host` completes (success OR failure). If `fn` itself raises
        (device refused the dispatch; FallbackBackend turns that into a
        CPU handle instead, so this is the raw-backend path), the slot
        is released before the exception propagates."""
        ticket = self.queue._admit(self.priority, cost)
        span = self.span
        if span is not None:
            span.add_stage(
                "admission_wait", ticket.wait_s, self.queue.label
            )
        with self._lock:
            self._outstanding.add(ticket)
        ok = False
        t0 = time.perf_counter() if span is not None else 0.0
        try:
            handle = fn()
            ok = True
        finally:
            if span is not None:
                span.add_stage(
                    "h2d_dispatch",
                    time.perf_counter() - t0,
                    self.queue.label,
                )
            if not ok:
                self.release(ticket)
        return ticket, handle

    def release(self, ticket: Ticket) -> None:
        with self._lock:
            self._outstanding.discard(ticket)
        self.queue._release(ticket)

    def close(self) -> None:
        """Release any slots this stream still holds — the leak-proofing
        for a pipeline that aborted with batches parked in its write
        queue (whose drain stage will never run)."""
        with self._lock:
            leftover = list(self._outstanding)
            self._outstanding.clear()
        for t in leftover:
            self.queue._release(t)

    def __enter__(self) -> "DeviceStream":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class DeviceQueue:
    """Priority-multiplexed admission scheduler for one chip. See the
    module docstring for the policy. `label` identifies the chip in
    stats and metrics (device id for pool chips)."""

    def __init__(
        self,
        window: int = DEFAULT_WINDOW,
        shares: dict[str, float] | None = None,
        clock=time.monotonic,
        admit_timeout: float = DEFAULT_ADMIT_TIMEOUT,
        label: str = "",
    ):
        self.window = max(1, int(window))
        self.admit_timeout = float(admit_timeout)
        self.label = label
        self.shares = dict(DEFAULT_SHARES)
        if shares:
            for cls, s in shares.items():
                if cls not in PRIORITIES:
                    raise ECError(f"unknown priority class {cls!r}")
                self.shares[cls] = min(max(float(s), 0.0), 0.9)
        self._cond = threading.Condition()
        self._waiters: dict[str, deque[_Waiter]] = {
            c: deque() for c in PRIORITIES
        }
        self._credit: dict[str, float] = {c: 0.0 for c in PRIORITIES}
        self._inflight = 0
        # Total un-drained cost (waiting + in-flight): live-load
        # introspection (accounting asserts, ops tooling). NOTE:
        # chip_pool routing does NOT read this — it charges each
        # stream's static cost hint at placement time and drains it at
        # stream close; wiring routing to live queue load is a recorded
        # ROADMAP item.
        self._pending_cost = 0
        self._stats: dict[str, ClassStats] = {c: ClassStats() for c in PRIORITIES}
        self._clock = clock
        # Liveness signal for the admission deadline: bumped on every
        # admit AND release. A waiter past its deadline while this keeps
        # moving is merely bypassed (e.g. share=0 strict priority under
        # sustained foreground) — that is the configured behavior, not a
        # wedge; only a chip with NO progress for the whole window
        # raises.
        self._last_progress = clock()

    # ------------------------------------------------------------ public

    def stream(
        self, priority: str, label: str = "", span=None
    ) -> DeviceStream:
        if priority not in PRIORITIES:
            raise ECError(
                f"unknown priority class {priority!r} (want one of {PRIORITIES})"
            )
        return DeviceStream(self, priority, label, span=span)

    @contextlib.contextmanager
    def admission(self, priority: str, cost: int, span=None):
        """One-shot admission for work that is not a staged batch
        stream — e.g. a single-shot degraded-read reconstruction on the
        gateway serving path. Blocks until this queue admits `cost`
        units in `priority`'s class, holds ONE window slot for the body
        of the ``with``, and releases it on exit (success or raise).
        The admission wait is recorded on `span` as the
        "admission_wait" stage labeled with this queue's chip, exactly
        like the staged path's, so per-stage attribution shows where a
        scheduled read waited."""
        if priority not in PRIORITIES:
            raise ECError(
                f"unknown priority class {priority!r} (want one of {PRIORITIES})"
            )
        ticket = self._admit(priority, cost)
        if span is not None:
            span.add_stage("admission_wait", ticket.wait_s, self.label)
        try:
            yield ticket
        finally:
            self._release(ticket)

    def stats(self) -> dict:
        with self._cond:
            return {
                c: self._stats[c].as_dict(len(self._waiters[c]))
                for c in PRIORITIES
            }

    @property
    def inflight(self) -> int:
        with self._cond:
            return self._inflight

    def load(self) -> int:
        """Queued + in-flight cost units not yet drained."""
        with self._cond:
            return self._pending_cost

    # ------------------------------------------------------------ policy

    def _pick(self) -> _Waiter | None:
        """Next admissible waiter (under self._cond). Only head-of-class
        waiters are eligible, so per-stream FIFO order is preserved by
        construction."""
        if self._inflight >= self.window:
            return None
        nonempty = [c for c in PRIORITIES if self._waiters[c]]
        if not nonempty:
            return None
        # A lower class whose banked credit covers its head batch is due
        # ahead of the best class — the minimum-share guarantee. Among
        # due classes, the higher-priority one wins (recovery > scrub).
        for c in nonempty[1:]:
            if self._credit[c] >= self._waiters[c][0].cost:
                return self._waiters[c][0]
        return self._waiters[nonempty[0]][0]

    def _admit(self, priority: str, cost: int) -> Ticket:
        cost = max(int(cost), 1)
        w = _Waiter(priority, cost, self._clock())
        with self._cond:
            self._waiters[priority].append(w)
            self._pending_cost += cost
            st = self._stats[priority]
            st.submitted += 1
            _queue_depth.inc(cls=priority, chip=self.label)
            while self._pick() is not w:
                deadline = (
                    max(w.t_submit, self._last_progress) + self.admit_timeout
                )
                left = deadline - self._clock()
                if left <= 0 or not self._cond.wait(timeout=left):
                    if self._pick() is w:  # admitted at the wire
                        break
                    if self._clock() - self._last_progress < self.admit_timeout:
                        continue  # bypassed, not wedged: keep waiting
                    # Liveness escape: window slots are freed by other
                    # streams' drains; a full deadline with NO admit or
                    # release anywhere means the chip is wedged (e.g. a
                    # stream stuck in to_host against a hung device
                    # holding every slot). Fail THIS stream loudly
                    # instead of freezing the whole chip's dispatch
                    # silently forever.
                    self._waiters[priority].remove(w)
                    self._pending_cost -= cost
                    _queue_depth.dec(cls=priority, chip=self.label)
                    self._cond.notify_all()
                    raise ECError(
                        f"device queue admission timed out after "
                        f"{self.admit_timeout:.0f}s without progress "
                        f"({priority}, inflight="
                        f"{self._inflight}/{self.window}): chip wedged?"
                    )
            popped = self._waiters[priority].popleft()
            assert popped is w  # only heads are ever picked
            _queue_depth.dec(cls=priority, chip=self.label)
            # Bank minimum-share credit for every lower class with work
            # waiting; spend this class's own credit (floored at 0 so a
            # work-conserving free ride never becomes debt).
            idx = PRIORITIES.index(priority)
            for lower in PRIORITIES[idx + 1 :]:
                if self._waiters[lower]:
                    s = self.shares.get(lower, 0.0)
                    if s > 0.0:
                        self._credit[lower] = min(
                            self._credit[lower] + cost * s / (1.0 - s),
                            float(CREDIT_CAP_COST),
                        )
            self._credit[priority] = max(self._credit[priority] - cost, 0.0)
            self._inflight += 1
            self._last_progress = self._clock()
            wait_s = max(self._clock() - w.t_submit, 0.0)
            st.admitted += 1
            st.admitted_cost += cost
            st.inflight += 1
            st.wait_s_total += wait_s
            st.wait_s_max = max(st.wait_s_max, wait_s)
            _queue_inflight.inc(cls=priority, chip=self.label)
            _queue_admitted.inc(cls=priority, chip=self.label)
            _queue_admitted_cost.inc(cost, cls=priority, chip=self.label)
            _queue_wait_seconds.inc(wait_s, cls=priority, chip=self.label)
            # Another slot may still be free for the next waiter.
            self._cond.notify_all()
        return Ticket(priority, cost, wait_s)

    def _release(self, ticket: Ticket) -> None:
        with self._cond:
            if ticket.released:
                return
            ticket.released = True
            self._inflight -= 1
            self._pending_cost -= ticket.cost
            self._last_progress = self._clock()
            st = self._stats[ticket.priority]
            st.inflight -= 1
            st.drained += 1
            st.drained_cost += ticket.cost
            _queue_inflight.dec(cls=ticket.priority, chip=self.label)
            self._cond.notify_all()


# --------------------------------------------------------------------------
# Scopes: one scheduler/placement config domain + its queue registry.
# The process-wide default scope backs the module-level functions; a
# Store may carry a private scope (multi-tenant embedding) so one
# tenant's configure() stops clobbering another's.
# --------------------------------------------------------------------------


_label_lock = threading.Lock()
_label_seq: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_label_next = itertools.count()


def _queue_label(backend) -> str:
    """Chip identity for stats/metrics: the pool chip's device id when
    the backend is (or wraps) a pinned ChipBackend, else the backend
    class name qualified by its shard ratio and an instance tag (one
    single-device/mesh backend = one chip) — two same-class backends
    (e.g. volumes at 10+4 and 5+2) must not merge into one label set.
    The tag is a process-wide monotonic sequence number (id() bits can
    collide after allocator reuse, silently summing two backends'
    gauges into one series)."""
    label = getattr(backend, "chip_label", "")
    if not label:
        label = getattr(getattr(backend, "primary", None), "chip_label", "")
    if label:
        return label
    ctx = getattr(backend, "ctx", None)
    ratio = (
        f":{ctx.data_shards}+{ctx.parity_shards}"
        if ctx is not None
        else ""
    )
    with _label_lock:
        seq = _label_seq.get(backend)
        if seq is None:
            seq = _label_seq[backend] = next(_label_next)
    return f"{type(backend).__name__}{ratio}@{seq}"


class QueueScope:
    """One scheduler/placement configuration domain.

    Holds the enable flag, window, per-class shares, and the stream
    placement mode (`auto|mesh|chip`, consumed by ec/chip_pool.py),
    plus the registry of live DeviceQueues created under this scope.
    Queues are per (scope, backend): two scopes sharing a chip each get
    their own admission policy — the multi-tenant contract is isolation
    of CONFIG, while the physical chip pool (ec/chip_pool.py) stays
    process-wide so placement still sees total chip load."""

    def __init__(
        self,
        enabled: bool = True,
        window: int = DEFAULT_WINDOW,
        shares: dict[str, float] | None = None,
        placement: str = DEFAULT_PLACEMENT,
    ):
        self._lock = threading.Lock()
        self._queues: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
        self._config: dict = {
            "enabled": True,
            "window": DEFAULT_WINDOW,
            "shares": dict(DEFAULT_SHARES),
            "placement": DEFAULT_PLACEMENT,
        }
        self.configure(
            enabled=enabled, window=window, shares=shares or {},
            placement=placement,
        )

    def configure(
        self,
        enabled: bool | None = None,
        window: int | None = None,
        shares: dict[str, float] | None = None,
        placement: str | None = None,
    ) -> dict:
        """Update this scope's scheduler knobs; the LAST caller wins
        WITHIN the scope. A `shares` dict (even empty) REPLACES the
        whole share map — classes it omits return to DEFAULT_SHARES, so
        one caller's override can never stick invisibly to the next
        caller's config; None leaves the current map untouched.
        `placement` selects the chip-pool routing mode (auto|mesh|chip).
        Live queues pick the new values up immediately; `enabled=False`
        makes `for_backend` return None so every producer falls back to
        its private PR 3 window. Returns the effective config.

        Multi-tenant embedders should configure a per-Store scope
        (`Store(ec_queue_window=...)`) instead of the process-wide
        default this module's bare `configure()` mutates."""
        # Validate EVERY input before mutating anything: a rejected
        # call must not leave the scope half-configured (live queues on
        # the old window while later-created queues get the new one).
        merged = None
        if shares is not None:
            merged = dict(DEFAULT_SHARES)
            for cls, s in shares.items():
                if cls not in PRIORITIES:
                    raise ECError(f"unknown priority class {cls!r}")
                merged[cls] = min(max(float(s), 0.0), 0.9)
        if placement is not None and placement not in PLACEMENT_MODES:
            raise ECError(
                f"unknown ec_placement {placement!r} "
                f"(want one of {PLACEMENT_MODES})"
            )
        if window is not None:
            window = max(1, int(window))
        with self._lock:
            if enabled is not None:
                self._config["enabled"] = bool(enabled)
            if window is not None:
                self._config["window"] = window
            if merged is not None:
                self._config["shares"] = merged
            if placement is not None:
                self._config["placement"] = placement
            live = list(self._queues.values())
            cfg = {
                "enabled": self._config["enabled"],
                "window": self._config["window"],
                "shares": dict(self._config["shares"]),
                "placement": self._config["placement"],
            }
        for q in live:
            with q._cond:
                q.window = cfg["window"]
                q.shares = dict(cfg["shares"])
                q._cond.notify_all()
        return cfg

    @property
    def enabled(self) -> bool:
        with self._lock:
            return self._config["enabled"]

    @property
    def placement(self) -> str:
        with self._lock:
            return self._config["placement"]

    def for_backend(self, backend) -> DeviceQueue | None:
        """The shared queue for `backend`'s chip under this scope, or
        None when the scheduler is disabled (or there is no backend —
        the pass-through pipeline)."""
        if backend is None:
            return None
        with self._lock:
            if not self._config["enabled"]:
                return None
            q = self._queues.get(backend)
            if q is None:
                q = DeviceQueue(
                    window=self._config["window"],
                    shares=self._config["shares"],
                    label=_queue_label(backend),
                )
                self._queues[backend] = q
            return q

    def stats_snapshot(self) -> list[dict]:
        """Per-queue per-class counters for /status and ops tooling,
        keyed per chip (`chip` = device id for pool chips). `breaker`
        carries the chip's fallback-breaker state ("open" = this chip's
        streams are failing over to CPU; "" = the backend has no
        breaker) so the server can surface pod health."""
        with self._lock:
            items = [
                (type(b).__name__, getattr(b, "breaker", None), q)
                for b, q in self._queues.items()
            ]
        return [
            {
                "backend": name,
                "chip": q.label,
                "window": q.window,
                "breaker": brk.state if brk is not None else "",
                "load": q.load(),
                "classes": q.stats(),
            }
            for name, brk, q in items
        ]

    def queue_loads(self) -> dict[str, dict]:
        """Read-only per-chip load view: {chip_label: {"load": cost
        units queued+in-flight, "breaker": state}} — the cheap form of
        stats_snapshot for routing hints and heartbeat telemetry."""
        with self._lock:
            items = [
                (getattr(b, "breaker", None), q)
                for b, q in self._queues.items()
            ]
        return {
            q.label: {
                "load": q.load(),
                "breaker": brk.state if brk is not None else "",
            }
            for brk, q in items
        }


_DEFAULT_SCOPE = QueueScope()


def default_scope() -> QueueScope:
    """The process-wide scope backing the module-level functions."""
    return _DEFAULT_SCOPE


def resolve_scope(scope: QueueScope | None) -> QueueScope:
    return scope if scope is not None else _DEFAULT_SCOPE


def configure(
    enabled: bool | None = None,
    window: int | None = None,
    shares: dict[str, float] | None = None,
    placement: str | None = None,
) -> dict:
    """Process-wide DEFAULT-scope scheduler knobs; the LAST caller wins
    wholesale within that scope. See QueueScope.configure for the
    semantics; per-chip stats surface through `stats_snapshot` keyed by
    the queue's `chip` label (device id once a chip pool exists).
    Multi-tenant embedders should thread a per-Store scope through
    `Store(...)` instead of calling this."""
    return _DEFAULT_SCOPE.configure(
        enabled=enabled, window=window, shares=shares, placement=placement
    )


def for_backend(backend, scope: QueueScope | None = None) -> DeviceQueue | None:
    """The shared queue for `backend`'s chip (in `scope`, default the
    process-wide scope), or None when the scheduler is disabled."""
    return resolve_scope(scope).for_backend(backend)


def stats_snapshot(scope: QueueScope | None = None) -> list[dict]:
    """Per-queue per-class counters for /status and ops tooling."""
    return resolve_scope(scope).stats_snapshot()
