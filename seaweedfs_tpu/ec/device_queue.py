"""Shared per-chip device-queue scheduler for the EC compute pipeline.

Before this module every staged-apply call site (encode, rebuild,
decode self-heal, wide degraded reads) drove its own private in-flight
window against the device, so a background rebuild and a foreground
encode on the same chip serialized at the JAX runtime's mercy — or
fought for HBM with two uncoordinated windows. Haystack-style stores
avoid exactly this by prioritizing serving traffic over repair; the
ROADMAP named the shared scheduler as the open perf item from PR 3.

Model
-----

One `DeviceQueue` per backend instance ("per chip": backends are
lru_cached singletons per (name, k, m)). Producers open a
`DeviceStream` tagged with a priority class and submit batches through
it; the queue admits batch dispatches (the H2D + device-dispatch step)
one at a time under a policy, and bounds the TOTAL number of in-flight
device batches across all streams (`window` — the device-memory
residency bound that used to be per call site).

Priority classes, highest first:

- ``foreground`` — encode, degraded reads (serving traffic);
- ``recovery``  — rebuild, decode self-heal (restore redundancy);
- ``scrub``     — scrub-initiated repair (background hygiene).

Admission is strict-priority with a weighted-deficit minimum share for
the background classes: every byte admitted for a higher class banks
``share/(1-share)`` bytes of credit for each LOWER class that has work
waiting; a lower class whose credit covers its head batch is admitted
ahead of the higher class. Under saturation each background class
therefore gets ~``share`` of admitted bytes (no starvation), while an
arriving foreground batch goes ahead of every queued background batch
that is not yet "due" (batch-granularity preemption: a long rebuild
window can no longer head-of-line-block an encode — the rebuild yields
the H2D slot at its next batch boundary). ``share=0`` degrades to
strict priority for that class.

Fault semantics are unchanged and PER STREAM: the queue never touches
batch payloads or results, so a FallbackBackend device death between
dispatch and drain replays only the dying stream's in-flight batches on
CPU (the carried host copies), other streams keep the device until the
shared breaker trips, and bit-identity of every stream's output to the
synchronous apply holds by construction. A stream that dies releases
its window slots (``DeviceStream.close`` is leak-proof), so one
aborted producer can never wedge the chip for everyone else.

Knobs ride in through :func:`configure` (server wiring:
``ec_device_queue``, per-class shares, window) and per-class
depth/wait/throughput counters surface through :func:`stats_snapshot`
and the Prometheus registry (``sw_ec_queue_*``).
"""

from __future__ import annotations

import threading
import time
import weakref
from collections import deque

from ..utils import metrics as _M
from .context import ECError

# Highest priority first; admission prefers earlier classes.
PRIORITIES = ("foreground", "recovery", "scrub")

# Minimum admitted-byte share per background class under saturation.
# Small on purpose: this is a SERVING store — repair proceeds, but
# foreground keeps ~90% of the chip when it wants it (the bench
# acceptance bar is foreground >= 85% of isolated throughput with a
# concurrent rebuild stream still making progress).
DEFAULT_SHARES = {"recovery": 0.10, "scrub": 0.02}

# Default bound on in-flight device batches across ALL streams of one
# chip. PR 3's per-call-site windows allowed ~2*queue_size = 4 staged
# batches each; the shared window keeps the same residency for the chip
# as one saturated call site used to claim.
DEFAULT_WINDOW = 4

# Credit never banks more than this many bytes per class: a background
# class idle through a long foreground burst must not repay itself with
# an equally long background burst afterwards.
CREDIT_CAP_BYTES = 256 << 20

# Admission liveness bound. Window slots are freed by OTHER streams'
# drain threads; a stream wedged in to_host against a hung device holds
# its slots and (unlike the pre-scheduler private windows) would freeze
# every other stream's dispatch on the chip, silently and forever —
# run_pipeline's join_timeout can never fire for a thread stuck INSIDE
# the transform stage. Past this deadline admission raises instead:
# a loud per-stream ECError (callers fail/retry/fall back) beats a
# chip-wide freeze with no error. Generous on purpose — only a truly
# wedged chip waits minutes for a slot.
DEFAULT_ADMIT_TIMEOUT = 300.0

_queue_depth = _M.REGISTRY.gauge(
    "sw_ec_queue_depth", "EC device-queue waiting batches", ("cls",)
)
_queue_inflight = _M.REGISTRY.gauge(
    "sw_ec_queue_inflight", "EC device-queue in-flight batches", ("cls",)
)
_queue_admitted = _M.REGISTRY.counter(
    "sw_ec_queue_admitted_total", "EC device-queue admitted batches", ("cls",)
)
_queue_admitted_bytes = _M.REGISTRY.counter(
    "sw_ec_queue_admitted_bytes_total",
    "EC device-queue admitted bytes", ("cls",),
)
_queue_wait_seconds = _M.REGISTRY.counter(
    "sw_ec_queue_wait_seconds_total",
    "EC device-queue admission wait", ("cls",),
)


class _Waiter:
    __slots__ = ("priority", "nbytes", "t_submit")

    def __init__(self, priority: str, nbytes: int, t_submit: float):
        self.priority = priority
        self.nbytes = nbytes
        self.t_submit = t_submit


class Ticket:
    """One admitted (in-flight) batch; released after to_host drains it
    (or the stream dies). Idempotent release — close() may race a drain
    thread's finally."""

    __slots__ = ("priority", "nbytes", "released")

    def __init__(self, priority: str, nbytes: int):
        self.priority = priority
        self.nbytes = nbytes
        self.released = False


class ClassStats:
    __slots__ = (
        "submitted", "admitted", "admitted_bytes", "drained",
        "drained_bytes", "wait_s_total", "wait_s_max", "inflight",
    )

    def __init__(self):
        self.submitted = 0
        self.admitted = 0
        self.admitted_bytes = 0
        self.drained = 0
        self.drained_bytes = 0
        self.wait_s_total = 0.0
        self.wait_s_max = 0.0
        self.inflight = 0

    def as_dict(self, depth: int) -> dict:
        return {
            "depth": depth,
            "inflight": self.inflight,
            "submitted": self.submitted,
            "admitted": self.admitted,
            "admitted_bytes": self.admitted_bytes,
            "drained": self.drained,
            "drained_bytes": self.drained_bytes,
            "wait_s_total": round(self.wait_s_total, 6),
            "wait_s_max": round(self.wait_s_max, 6),
        }


class DeviceStream:
    """One producer's tagged batch stream into a DeviceQueue. Not
    thread-safe for concurrent dispatch (each pipeline dispatches from
    one thread), but release/close may run from the drain thread."""

    def __init__(self, queue: "DeviceQueue", priority: str, label: str = ""):
        self.queue = queue
        self.priority = priority
        self.label = label
        self._outstanding: set[Ticket] = set()
        self._lock = threading.Lock()

    def dispatch(self, fn, nbytes: int):
        """Block until this stream's batch is admitted under the queue
        policy, then run `fn()` (the caller's H2D upload + non-blocking
        device dispatch) and return ``(ticket, handle)``. The window
        slot is held until :meth:`release` — call it after `to_host`
        completes (success OR failure). If `fn` itself raises (device
        refused the dispatch; FallbackBackend turns that into a CPU
        handle instead, so this is the raw-backend path), the slot is
        released before the exception propagates."""
        ticket = self.queue._admit(self.priority, nbytes)
        with self._lock:
            self._outstanding.add(ticket)
        ok = False
        try:
            handle = fn()
            ok = True
        finally:
            if not ok:
                self.release(ticket)
        return ticket, handle

    def release(self, ticket: Ticket) -> None:
        with self._lock:
            self._outstanding.discard(ticket)
        self.queue._release(ticket)

    def close(self) -> None:
        """Release any slots this stream still holds — the leak-proofing
        for a pipeline that aborted with batches parked in its write
        queue (whose drain stage will never run)."""
        with self._lock:
            leftover = list(self._outstanding)
            self._outstanding.clear()
        for t in leftover:
            self.queue._release(t)

    def __enter__(self) -> "DeviceStream":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class DeviceQueue:
    """Priority-multiplexed admission scheduler for one chip (one
    backend instance). See the module docstring for the policy."""

    def __init__(
        self,
        window: int = DEFAULT_WINDOW,
        shares: dict[str, float] | None = None,
        clock=time.monotonic,
        admit_timeout: float = DEFAULT_ADMIT_TIMEOUT,
    ):
        self.window = max(1, int(window))
        self.admit_timeout = float(admit_timeout)
        self.shares = dict(DEFAULT_SHARES)
        if shares:
            for cls, s in shares.items():
                if cls not in PRIORITIES:
                    raise ECError(f"unknown priority class {cls!r}")
                self.shares[cls] = min(max(float(s), 0.0), 0.9)
        self._cond = threading.Condition()
        self._waiters: dict[str, deque[_Waiter]] = {
            c: deque() for c in PRIORITIES
        }
        self._credit: dict[str, float] = {c: 0.0 for c in PRIORITIES}
        self._inflight = 0
        self._stats: dict[str, ClassStats] = {c: ClassStats() for c in PRIORITIES}
        self._clock = clock
        # Liveness signal for the admission deadline: bumped on every
        # admit AND release. A waiter past its deadline while this keeps
        # moving is merely bypassed (e.g. share=0 strict priority under
        # sustained foreground) — that is the configured behavior, not a
        # wedge; only a chip with NO progress for the whole window
        # raises.
        self._last_progress = clock()

    # ------------------------------------------------------------ public

    def stream(self, priority: str, label: str = "") -> DeviceStream:
        if priority not in PRIORITIES:
            raise ECError(
                f"unknown priority class {priority!r} (want one of {PRIORITIES})"
            )
        return DeviceStream(self, priority, label)

    def stats(self) -> dict:
        with self._cond:
            return {
                c: self._stats[c].as_dict(len(self._waiters[c]))
                for c in PRIORITIES
            }

    @property
    def inflight(self) -> int:
        with self._cond:
            return self._inflight

    # ------------------------------------------------------------ policy

    def _pick(self) -> _Waiter | None:
        """Next admissible waiter (under self._cond). Only head-of-class
        waiters are eligible, so per-stream FIFO order is preserved by
        construction."""
        if self._inflight >= self.window:
            return None
        nonempty = [c for c in PRIORITIES if self._waiters[c]]
        if not nonempty:
            return None
        # A lower class whose banked credit covers its head batch is due
        # ahead of the best class — the minimum-share guarantee. Among
        # due classes, the higher-priority one wins (recovery > scrub).
        for c in nonempty[1:]:
            if self._credit[c] >= self._waiters[c][0].nbytes:
                return self._waiters[c][0]
        return self._waiters[nonempty[0]][0]

    def _admit(self, priority: str, nbytes: int) -> Ticket:
        nbytes = max(int(nbytes), 1)
        w = _Waiter(priority, nbytes, self._clock())
        with self._cond:
            self._waiters[priority].append(w)
            st = self._stats[priority]
            st.submitted += 1
            _queue_depth.inc(cls=priority)
            while self._pick() is not w:
                deadline = (
                    max(w.t_submit, self._last_progress) + self.admit_timeout
                )
                left = deadline - self._clock()
                if left <= 0 or not self._cond.wait(timeout=left):
                    if self._pick() is w:  # admitted at the wire
                        break
                    if self._clock() - self._last_progress < self.admit_timeout:
                        continue  # bypassed, not wedged: keep waiting
                    # Liveness escape: window slots are freed by other
                    # streams' drains; a full deadline with NO admit or
                    # release anywhere means the chip is wedged (e.g. a
                    # stream stuck in to_host against a hung device
                    # holding every slot). Fail THIS stream loudly
                    # instead of freezing the whole chip's dispatch
                    # silently forever.
                    self._waiters[priority].remove(w)
                    _queue_depth.dec(cls=priority)
                    self._cond.notify_all()
                    raise ECError(
                        f"device queue admission timed out after "
                        f"{self.admit_timeout:.0f}s without progress "
                        f"({priority}, inflight="
                        f"{self._inflight}/{self.window}): chip wedged?"
                    )
            popped = self._waiters[priority].popleft()
            assert popped is w  # only heads are ever picked
            _queue_depth.dec(cls=priority)
            # Bank minimum-share credit for every lower class with work
            # waiting; spend this class's own credit (floored at 0 so a
            # work-conserving free ride never becomes debt).
            idx = PRIORITIES.index(priority)
            for lower in PRIORITIES[idx + 1 :]:
                if self._waiters[lower]:
                    s = self.shares.get(lower, 0.0)
                    if s > 0.0:
                        self._credit[lower] = min(
                            self._credit[lower] + nbytes * s / (1.0 - s),
                            float(CREDIT_CAP_BYTES),
                        )
            self._credit[priority] = max(self._credit[priority] - nbytes, 0.0)
            self._inflight += 1
            self._last_progress = self._clock()
            wait_s = max(self._clock() - w.t_submit, 0.0)
            st.admitted += 1
            st.admitted_bytes += nbytes
            st.inflight += 1
            st.wait_s_total += wait_s
            st.wait_s_max = max(st.wait_s_max, wait_s)
            _queue_inflight.inc(cls=priority)
            _queue_admitted.inc(cls=priority)
            _queue_admitted_bytes.inc(nbytes, cls=priority)
            _queue_wait_seconds.inc(wait_s, cls=priority)
            # Another slot may still be free for the next waiter.
            self._cond.notify_all()
        return Ticket(priority, nbytes)

    def _release(self, ticket: Ticket) -> None:
        with self._cond:
            if ticket.released:
                return
            ticket.released = True
            self._inflight -= 1
            self._last_progress = self._clock()
            st = self._stats[ticket.priority]
            st.inflight -= 1
            st.drained += 1
            st.drained_bytes += ticket.nbytes
            _queue_inflight.dec(cls=ticket.priority)
            self._cond.notify_all()


# --------------------------------------------------------------------------
# Registry: one queue per backend instance ("per chip" — backends are
# lru_cached singletons per (name, k, m)), plus the process-wide knobs
# the server wiring sets.
# --------------------------------------------------------------------------

_registry_lock = threading.Lock()
_queues: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_config: dict = {
    "enabled": True,
    "window": DEFAULT_WINDOW,
    "shares": dict(DEFAULT_SHARES),
}


def configure(
    enabled: bool | None = None,
    window: int | None = None,
    shares: dict[str, float] | None = None,
) -> dict:
    """Process-wide scheduler knobs (server wiring: `ec_device_queue`,
    per-class shares, window); the LAST caller wins wholesale. A
    `shares` dict (even empty) REPLACES the whole share map — classes
    it omits return to DEFAULT_SHARES, so one caller's override can
    never stick invisibly to the next caller's config; None leaves the
    current map untouched. Live queues pick the new values up
    immediately; `enabled=False` makes `for_backend` return None so
    every producer falls back to its private PR 3 window. Returns the
    effective config."""
    with _registry_lock:
        if enabled is not None:
            _config["enabled"] = bool(enabled)
        if window is not None:
            _config["window"] = max(1, int(window))
        if shares is not None:
            merged = dict(DEFAULT_SHARES)
            for cls, s in shares.items():
                if cls not in PRIORITIES:
                    raise ECError(f"unknown priority class {cls!r}")
                merged[cls] = min(max(float(s), 0.0), 0.9)
            _config["shares"] = merged
        live = list(_queues.values())
        cfg = {
            "enabled": _config["enabled"],
            "window": _config["window"],
            "shares": dict(_config["shares"]),
        }
    for q in live:
        with q._cond:
            q.window = cfg["window"]
            q.shares = dict(cfg["shares"])
            q._cond.notify_all()
    return cfg


def for_backend(backend) -> DeviceQueue | None:
    """The shared queue for `backend`'s chip, or None when the scheduler
    is disabled (or there is no backend — the pass-through pipeline)."""
    if backend is None:
        return None
    with _registry_lock:
        if not _config["enabled"]:
            return None
        q = _queues.get(backend)
        if q is None:
            q = DeviceQueue(
                window=_config["window"], shares=_config["shares"]
            )
            _queues[backend] = q
        return q


def stats_snapshot() -> list[dict]:
    """Per-queue per-class counters for /status and ops tooling."""
    with _registry_lock:
        items = [(type(b).__name__, q) for b, q in _queues.items()]
    return [
        {"backend": name, "window": q.window, "classes": q.stats()}
        for name, q in items
    ]
