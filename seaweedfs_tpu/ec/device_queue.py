"""Shared per-chip device-queue scheduler for the EC compute pipeline.

Before this module every staged-apply call site (encode, rebuild,
decode self-heal, wide degraded reads) drove its own private in-flight
window against the device, so a background rebuild and a foreground
encode on the same chip serialized at the JAX runtime's mercy — or
fought for HBM with two uncoordinated windows. Haystack-style stores
avoid exactly this by prioritizing serving traffic over repair; the
ROADMAP named the shared scheduler as the open perf item from PR 3.

Model
-----

One `DeviceQueue` per chip. A single-device backend is one chip; a
column-mesh backend spans several chips but dispatches as a unit, so it
still gets ONE queue — the pod-level answer is `ec/chip_pool.py`, which
places whole streams onto per-chip backends (each with its own queue
from this module) instead of slicing every stream across the mesh.
Producers open a `DeviceStream` tagged with a priority class and submit
batches through it; the queue admits batch dispatches (the H2D +
device-dispatch step) one at a time under a policy, and bounds the
TOTAL number of in-flight device batches across all streams (`window` —
the device-memory residency bound that used to be per call site).

Priority classes, highest first:

- ``foreground`` — encode, degraded reads (serving traffic);
- ``recovery``  — rebuild, decode self-heal (restore redundancy);
- ``scrub``     — scrub-initiated repair (background hygiene).

Cost model
----------

Admission is denominated in COST UNITS, not payload bytes: one unit is
one output row-byte (``out_rows x batch_width``, see
:func:`batch_cost`). Device time for a GF(256) apply scales with the
output rows it computes, so a 1-row degraded reconstruction of a 64 KiB
leaf (cost 64Ki) no longer counts like a full parity encode of the same
width (cost m x width = 4 x width at 10+4): under the minimum-share
policy a recovery stream of single-row repairs gets proportionally MORE
batches admitted per unit of banked credit than a byte-denominated
accounting would allow — the heterogeneous-batch fairness the ROADMAP
recorded after PR 4.

Admission is strict-priority with a weighted-deficit minimum share for
the background classes: every cost unit admitted for a higher class
banks ``share/(1-share)`` units of credit for each LOWER class that has
work waiting; a lower class whose credit covers its head batch is
admitted ahead of the higher class. Under saturation each background
class therefore gets ~``share`` of admitted cost (no starvation), while
an arriving foreground batch goes ahead of every queued background
batch that is not yet "due" (batch-granularity preemption: a long
rebuild window can no longer head-of-line-block an encode — the rebuild
yields the H2D slot at its next batch boundary). ``share=0`` degrades
to strict priority for that class.

Fault semantics are unchanged and PER STREAM: the queue never touches
batch payloads or results, so a FallbackBackend device death between
dispatch and drain replays only the dying stream's in-flight batches on
CPU (the carried host copies), other streams keep the device until the
shared breaker trips, and bit-identity of every stream's output to the
synchronous apply holds by construction. A stream that dies releases
its window slots (``DeviceStream.close`` is leak-proof), so one
aborted producer can never wedge the chip for everyone else.

Scopes
------

Knobs live in a :class:`QueueScope` — one config domain with its own
queue registry. The module-level :func:`configure` / :func:`for_backend`
/ :func:`stats_snapshot` operate on the process-wide DEFAULT scope
(kept for embedders and tests; still last-caller-wins there), while a
`Store` may carry its own scope so two tenants in one process stop
clobbering each other's shares/window/placement (`storage/store.py`
threads it exactly like the shared interval cache). Per-class
depth/wait/throughput counters surface through ``stats_snapshot`` and
the Prometheus registry (``sw_ec_queue_*``), keyed per chip: each queue
carries a ``chip`` label (the device id for pool chips, the backend
class name otherwise), so a second chip's counters land in their own
gauge set instead of silently aliasing into the first's.

Residency: the physical layer under the scopes
----------------------------------------------

Scopes isolate CONFIG, not HARDWARE: two scopes sharing one chip each
used to get a full in-flight window, and a wide mesh stream admitted
through the mesh backend's own queue beside every per-chip queue — a
pod could be driven to ~2x physical oversubscription with nothing
stopping it. The :class:`ResidencyLedger` is the process-wide answer:
ONE ledger, one slot budget per PHYSICAL chip, charged by every
scope's queue in a second admission phase after the scope's own
window. Per-scope windows are thereby sub-budgets — N scopes on one
chip can never hold more in-flight batches than the chip's bound, and
a mesh-wide stream charges a slot on EVERY chip it spans
(`_residency_keys`). The ledger is also where cross-scope behavior
lives:

- **Tenant fairness** — each scope carries a ``tenant`` name; grants
  under contention order by (starvation bound, priority class, the
  tenant's windowed admitted cost). A storm tenant's backlog cannot
  push a quiet tenant's foreground wait unbounded, and any waiter
  older than ``SEAWEED_EC_TENANT_STARVE_S`` goes first regardless.
- **Graceful shedding** — sustained saturation raises a per-chip
  pressure level (an open chip breaker raises it further): level 1
  defers scrub grants, level 2 defers recovery too, level 3 makes
  :func:`shed_advice` tell front ends to 503/SlowDown the tenants
  whose windowed share exceeds their fair share (per-tenant, never
  per-server). Background classes throttle first; foreground last.

``sw_ec_residency_*`` metrics, :func:`residency_snapshot` (heartbeat
telemetry + /status + /cluster/status) and per-tenant shed counters
surface the whole state. ``SEAWEED_EC_RESIDENCY_WINDOW=0`` disables
the global ledger (each scope back to its private window only);
tests/bench inject private ledgers via ``QueueScope(residency=...)``.
"""

from __future__ import annotations

import contextlib
import itertools
import os
import threading
import time
import weakref
from collections import deque

from .. import faults
from ..utils import metrics as _M
from .context import ECError

# Highest priority first; admission prefers earlier classes.
PRIORITIES = ("foreground", "recovery", "scrub")

# Minimum admitted-cost share per background class under saturation.
# Small on purpose: this is a SERVING store — repair proceeds, but
# foreground keeps ~90% of the chip when it wants it (the bench
# acceptance bar is foreground >= 85% of isolated throughput with a
# concurrent rebuild stream still making progress).
DEFAULT_SHARES = {"recovery": 0.10, "scrub": 0.02}

# Default bound on in-flight device batches across ALL streams of one
# chip. PR 3's per-call-site windows allowed ~2*queue_size = 4 staged
# batches each; the shared window keeps the same residency for the chip
# as one saturated call site used to claim.
DEFAULT_WINDOW = 4

# Stream placement policy for multi-chip (mesh-capable) backends — see
# ec/chip_pool.py. "auto" routes each new stream to the least-loaded
# chip unless the stream is explicitly wide and the pod is idle;
# "chip" always routes; "mesh" always column-slices (the PR 4 shape).
PLACEMENT_MODES = ("auto", "mesh", "chip")
DEFAULT_PLACEMENT = "auto"

# Credit never banks more than this many cost units per class: a
# background class idle through a long foreground burst must not repay
# itself with an equally long background burst afterwards.
CREDIT_CAP_COST = 1 << 30

# Admission liveness bound. Window slots are freed by OTHER streams'
# drain threads; a stream wedged in to_host against a hung device holds
# its slots and (unlike the pre-scheduler private windows) would freeze
# every other stream's dispatch on the chip, silently and forever —
# run_pipeline's join_timeout can never fire for a thread stuck INSIDE
# the transform stage. Past this deadline admission raises instead:
# a loud per-stream ECError (callers fail/retry/fall back) beats a
# chip-wide freeze with no error. Generous on purpose — only a truly
# wedged chip waits minutes for a slot.
DEFAULT_ADMIT_TIMEOUT = 300.0

_queue_depth = _M.REGISTRY.gauge(
    "sw_ec_queue_depth", "EC device-queue waiting batches", ("cls", "chip")
)
_queue_inflight = _M.REGISTRY.gauge(
    "sw_ec_queue_inflight", "EC device-queue in-flight batches", ("cls", "chip")
)
_queue_admitted = _M.REGISTRY.counter(
    "sw_ec_queue_admitted_total",
    "EC device-queue admitted batches", ("cls", "chip"),
)
_queue_admitted_cost = _M.REGISTRY.counter(
    "sw_ec_queue_admitted_cost_total",
    "EC device-queue admitted cost units (output rows x batch width)",
    ("cls", "chip"),
)
_queue_wait_seconds = _M.REGISTRY.counter(
    "sw_ec_queue_wait_seconds_total",
    "EC device-queue admission wait", ("cls", "chip"),
)

# ---- residency defaults (env-tunable; see README env-knob registry) ----

# Per-physical-chip in-flight slot budget of the process-wide ledger.
# Defaults to DEFAULT_WINDOW so a single scope per chip behaves exactly
# as before — the ledger only binds once a SECOND scope (or a mesh-wide
# stream) shows up on the chip. 0 disables the global ledger.
DEFAULT_RESIDENCY_BUDGET = DEFAULT_WINDOW

# Starvation bound: a waiter older than this goes ahead of every
# fairness/shed consideration — the hard ceiling on how long tenant
# weighting or background deferral may hold anyone back.
DEFAULT_STARVE_S = 30.0

# Sustained-saturation threshold: a chip full with waiters queued for
# this long enters shed level 1 (scrub deferred); 3x = level 2
# (recovery deferred too); 6x = level 3 (over-share tenants shed at
# the front ends).
DEFAULT_SHED_AFTER_S = 5.0

# Base Retry-After (seconds) handed to shed tenants.
DEFAULT_SHED_RETRY_S = 2.0

# Tenant fairness accounting window: admitted cost is summed over a
# sliding ~2x this span (two rotating buckets) — recent behavior, not
# lifetime totals, decides who the storm tenant is.
DEFAULT_TENANT_WINDOW_S = 10.0


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


_res_budget_g = _M.REGISTRY.gauge(
    "sw_ec_residency_budget",
    "EC residency-ledger in-flight slot budget per physical chip",
    ("chip",),
)
_res_inflight_g = _M.REGISTRY.gauge(
    "sw_ec_residency_inflight",
    "EC residency-ledger in-flight batches per physical chip "
    "(all scopes + mesh streams combined)",
    ("chip",),
)
_res_pressure_g = _M.REGISTRY.gauge(
    "sw_ec_residency_pressure",
    "EC residency shed level per chip (0 ok, 1 scrub deferred, "
    "2 recovery deferred, 3 over-share tenants shed)",
    ("chip",),
)
_res_admitted = _M.REGISTRY.counter(
    "sw_ec_residency_admitted_total",
    "EC residency-ledger admitted batches", ("tenant", "chip"),
)
_res_admitted_cost = _M.REGISTRY.counter(
    "sw_ec_residency_admitted_cost_total",
    "EC residency-ledger admitted cost units", ("tenant", "chip"),
)
_res_wait_seconds = _M.REGISTRY.counter(
    "sw_ec_residency_wait_seconds_total",
    "EC residency-ledger acquire wait (the second admission phase, "
    "charged on top of the scope queue's own wait)",
    ("tenant", "chip"),
)
_res_shed = _M.REGISTRY.counter(
    "sw_ec_residency_shed_total",
    "front-end requests shed (503 SlowDown) per tenant by the "
    "residency pressure policy",
    ("tenant",),
)


def batch_cost(out_rows: int, width: int) -> int:
    """Admission cost of one batch: output rows x batch width (bytes per
    row). Tracks device time — a GF(256) apply computes out_rows x k x
    width byte-products, and k is fixed per volume — so a 1-row
    reconstruction is ~1/m the cost of a parity encode at equal width."""
    return max(int(out_rows), 1) * max(int(width), 1)


class _Waiter:
    __slots__ = ("priority", "cost", "t_submit")

    def __init__(self, priority: str, cost: int, t_submit: float):
        self.priority = priority
        self.cost = cost
        self.t_submit = t_submit


class Ticket:
    """One admitted (in-flight) batch; released after to_host drains it
    (or the stream dies). Idempotent release — close() may race a drain
    thread's finally. `wait_s` is the admission wait this batch paid
    (the flight recorder's "admission_wait" stage)."""

    __slots__ = ("priority", "cost", "released", "wait_s", "res")

    def __init__(self, priority: str, cost: int, wait_s: float = 0.0):
        self.priority = priority
        self.cost = cost
        self.released = False
        self.wait_s = wait_s
        # (ledger, _ResTicket) once the residency phase charged the
        # physical chip; None for ledger-less queues
        self.res = None


class ClassStats:
    __slots__ = (
        "submitted", "admitted", "admitted_cost", "drained",
        "drained_cost", "wait_s_total", "wait_s_max", "inflight",
    )

    def __init__(self):
        self.submitted = 0
        self.admitted = 0
        self.admitted_cost = 0
        self.drained = 0
        self.drained_cost = 0
        self.wait_s_total = 0.0
        self.wait_s_max = 0.0
        self.inflight = 0

    def as_dict(self, depth: int) -> dict:
        return {
            "depth": depth,
            "inflight": self.inflight,
            "submitted": self.submitted,
            "admitted": self.admitted,
            "admitted_cost": self.admitted_cost,
            "drained": self.drained,
            "drained_cost": self.drained_cost,
            "wait_s_total": round(self.wait_s_total, 6),
            "wait_s_max": round(self.wait_s_max, 6),
        }


class DeviceStream:
    """One producer's tagged batch stream into a DeviceQueue. Not
    thread-safe for concurrent dispatch (each pipeline dispatches from
    one thread), but release/close may run from the drain thread.
    `span` (utils/trace.py, None = tracer disarmed) gets per-batch
    "admission_wait" and "h2d_dispatch" stages labeled with this
    queue's chip."""

    def __init__(
        self,
        queue: "DeviceQueue",
        priority: str,
        label: str = "",
        span=None,
    ):
        self.queue = queue
        self.priority = priority
        self.label = label
        self.span = span
        self._outstanding: set[Ticket] = set()
        self._lock = threading.Lock()

    def dispatch(self, fn, cost: int):
        """Block until this stream's batch is admitted under the queue
        policy, then run `fn()` (the caller's H2D upload + non-blocking
        device dispatch) and return ``(ticket, handle)``. `cost` is the
        batch's admission weight in cost units (see :func:`batch_cost`).
        The window slot is held until :meth:`release` — call it after
        `to_host` completes (success OR failure). If `fn` itself raises
        (device refused the dispatch; FallbackBackend turns that into a
        CPU handle instead, so this is the raw-backend path), the slot
        is released before the exception propagates."""
        ticket = self.queue._admit(self.priority, cost)
        span = self.span
        if span is not None:
            span.add_stage(
                "admission_wait", ticket.wait_s, self.queue.label
            )
        with self._lock:
            self._outstanding.add(ticket)
        ok = False
        t0 = time.perf_counter() if span is not None else 0.0
        try:
            handle = fn()
            ok = True
        finally:
            if span is not None:
                span.add_stage(
                    "h2d_dispatch",
                    time.perf_counter() - t0,
                    self.queue.label,
                )
            if not ok:
                self.release(ticket)
        return ticket, handle

    def release(self, ticket: Ticket) -> None:
        with self._lock:
            self._outstanding.discard(ticket)
        self.queue._release(ticket)

    def close(self) -> None:
        """Release any slots this stream still holds — the leak-proofing
        for a pipeline that aborted with batches parked in its write
        queue (whose drain stage will never run)."""
        with self._lock:
            leftover = list(self._outstanding)
            self._outstanding.clear()
        for t in leftover:
            self.queue._release(t)

    def __enter__(self) -> "DeviceStream":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class DeviceQueue:
    """Priority-multiplexed admission scheduler for one chip. See the
    module docstring for the policy. `label` identifies the chip in
    stats and metrics (device id for pool chips)."""

    def __init__(
        self,
        window: int = DEFAULT_WINDOW,
        shares: dict[str, float] | None = None,
        clock=time.monotonic,
        admit_timeout: float = DEFAULT_ADMIT_TIMEOUT,
        label: str = "",
        residency: "ResidencyLedger | None" = None,
        res_keys: tuple[str, ...] = (),
        tenant: str = "default",
    ):
        self.window = max(1, int(window))
        self.admit_timeout = float(admit_timeout)
        self.label = label
        # Second admission phase: the process-wide physical ledger this
        # queue charges per batch (None = logical window only), the
        # chip keys one batch occupies, and the tenant the charge is
        # accounted to (QueueScope wiring).
        self.residency = residency
        self.res_keys = tuple(res_keys) or (label or "unlabeled",)
        self.tenant = tenant
        self.shares = dict(DEFAULT_SHARES)
        if shares:
            for cls, s in shares.items():
                if cls not in PRIORITIES:
                    raise ECError(f"unknown priority class {cls!r}")
                self.shares[cls] = min(max(float(s), 0.0), 0.9)
        self._cond = threading.Condition()
        self._waiters: dict[str, deque[_Waiter]] = {
            c: deque() for c in PRIORITIES
        }
        self._credit: dict[str, float] = {c: 0.0 for c in PRIORITIES}
        self._inflight = 0
        # Total un-drained cost (waiting + in-flight): live-load
        # introspection (accounting asserts, ops tooling). NOTE:
        # chip_pool routing does NOT read this — it charges each
        # stream's static cost hint at placement time and drains it at
        # stream close; wiring routing to live queue load is a recorded
        # ROADMAP item.
        self._pending_cost = 0
        # In-flight cost alone (no queued waiters): lets chip_pool
        # subtract THIS scope's share from the shared ledger's per-chip
        # cost so cross-scope load is added exactly once.
        self._inflight_cost = 0
        self._stats: dict[str, ClassStats] = {c: ClassStats() for c in PRIORITIES}
        self._clock = clock
        # Liveness signal for the admission deadline: bumped on every
        # admit AND release. A waiter past its deadline while this keeps
        # moving is merely bypassed (e.g. share=0 strict priority under
        # sustained foreground) — that is the configured behavior, not a
        # wedge; only a chip with NO progress for the whole window
        # raises.
        self._last_progress = clock()

    # ------------------------------------------------------------ public

    def stream(
        self, priority: str, label: str = "", span=None
    ) -> DeviceStream:
        if priority not in PRIORITIES:
            raise ECError(
                f"unknown priority class {priority!r} (want one of {PRIORITIES})"
            )
        return DeviceStream(self, priority, label, span=span)

    @contextlib.contextmanager
    def admission(self, priority: str, cost: int, span=None):
        """One-shot admission for work that is not a staged batch
        stream — e.g. a single-shot degraded-read reconstruction on the
        gateway serving path. Blocks until this queue admits `cost`
        units in `priority`'s class, holds ONE window slot for the body
        of the ``with``, and releases it on exit (success or raise).
        The admission wait is recorded on `span` as the
        "admission_wait" stage labeled with this queue's chip, exactly
        like the staged path's, so per-stage attribution shows where a
        scheduled read waited."""
        if priority not in PRIORITIES:
            raise ECError(
                f"unknown priority class {priority!r} (want one of {PRIORITIES})"
            )
        ticket = self._admit(priority, cost)
        if span is not None:
            span.add_stage("admission_wait", ticket.wait_s, self.label)
        try:
            yield ticket
        finally:
            self._release(ticket)

    def stats(self) -> dict:
        with self._cond:
            return {
                c: self._stats[c].as_dict(len(self._waiters[c]))
                for c in PRIORITIES
            }

    @property
    def inflight(self) -> int:
        with self._cond:
            return self._inflight

    def load(self) -> int:
        """Queued + in-flight cost units not yet drained."""
        with self._cond:
            return self._pending_cost

    # ------------------------------------------------------------ policy

    def _pick(self) -> _Waiter | None:
        """Next admissible waiter (under self._cond). Only head-of-class
        waiters are eligible, so per-stream FIFO order is preserved by
        construction."""
        if self._inflight >= self.window:
            return None
        nonempty = [c for c in PRIORITIES if self._waiters[c]]
        if not nonempty:
            return None
        # A lower class whose banked credit covers its head batch is due
        # ahead of the best class — the minimum-share guarantee. Among
        # due classes, the higher-priority one wins (recovery > scrub).
        for c in nonempty[1:]:
            if self._credit[c] >= self._waiters[c][0].cost:
                return self._waiters[c][0]
        return self._waiters[nonempty[0]][0]

    def _admit(self, priority: str, cost: int) -> Ticket:
        cost = max(int(cost), 1)
        w = _Waiter(priority, cost, self._clock())
        with self._cond:
            self._waiters[priority].append(w)
            self._pending_cost += cost
            st = self._stats[priority]
            st.submitted += 1
            _queue_depth.inc(cls=priority, chip=self.label)
            while self._pick() is not w:
                deadline = (
                    max(w.t_submit, self._last_progress) + self.admit_timeout
                )
                left = deadline - self._clock()
                if left <= 0 or not self._cond.wait(timeout=left):
                    if self._pick() is w:  # admitted at the wire
                        break
                    if self._clock() - self._last_progress < self.admit_timeout:
                        continue  # bypassed, not wedged: keep waiting
                    # Liveness escape: window slots are freed by other
                    # streams' drains; a full deadline with NO admit or
                    # release anywhere means the chip is wedged (e.g. a
                    # stream stuck in to_host against a hung device
                    # holding every slot). Fail THIS stream loudly
                    # instead of freezing the whole chip's dispatch
                    # silently forever.
                    self._waiters[priority].remove(w)
                    self._pending_cost -= cost
                    _queue_depth.dec(cls=priority, chip=self.label)
                    self._cond.notify_all()
                    raise ECError(
                        f"device queue admission timed out after "
                        f"{self.admit_timeout:.0f}s without progress "
                        f"({priority}, inflight="
                        f"{self._inflight}/{self.window}): chip wedged?"
                    )
            popped = self._waiters[priority].popleft()
            assert popped is w  # only heads are ever picked
            _queue_depth.dec(cls=priority, chip=self.label)
            # Bank minimum-share credit for every lower class with work
            # waiting; spend this class's own credit (floored at 0 so a
            # work-conserving free ride never becomes debt).
            idx = PRIORITIES.index(priority)
            for lower in PRIORITIES[idx + 1 :]:
                if self._waiters[lower]:
                    s = self.shares.get(lower, 0.0)
                    if s > 0.0:
                        self._credit[lower] = min(
                            self._credit[lower] + cost * s / (1.0 - s),
                            float(CREDIT_CAP_COST),
                        )
            self._credit[priority] = max(self._credit[priority] - cost, 0.0)
            self._inflight += 1
            self._last_progress = self._clock()
            wait_s = max(self._clock() - w.t_submit, 0.0)
            st.admitted += 1
            st.admitted_cost += cost
            st.inflight += 1
            st.wait_s_total += wait_s
            st.wait_s_max = max(st.wait_s_max, wait_s)
            _queue_inflight.inc(cls=priority, chip=self.label)
            _queue_admitted.inc(cls=priority, chip=self.label)
            _queue_admitted_cost.inc(cost, cls=priority, chip=self.label)
            _queue_wait_seconds.inc(wait_s, cls=priority, chip=self.label)
            self._inflight_cost += cost
            # Another slot may still be free for the next waiter.
            self._cond.notify_all()
        ticket = Ticket(priority, cost, wait_s)
        # Phase 2, OUTSIDE self._cond (the ledger has its own lock —
        # never nested): charge the physical chip(s). The local slot is
        # held while we wait here, which is exactly the sub-budget
        # semantics — this scope's window counts against the chip's
        # physical bound, it does not add to it. On failure the local
        # slot is returned before the error propagates.
        if self.residency is not None:
            t0 = self._clock()
            try:
                res = self.residency.acquire(
                    self.res_keys, self.tenant, priority, cost,
                    timeout=self.admit_timeout,
                )
            except BaseException:
                self._release(ticket)
                raise
            ticket.res = (self.residency, res)
            rwait = max(self._clock() - t0, 0.0)
            if rwait > 0.0:
                # the residency wait is part of this batch's admission
                # wait: fold it into the ticket (span stage) and stats
                ticket.wait_s += rwait
                with self._cond:
                    st = self._stats[priority]
                    st.wait_s_total += rwait
                    st.wait_s_max = max(st.wait_s_max, ticket.wait_s)
                _queue_wait_seconds.inc(rwait, cls=priority, chip=self.label)
        return ticket

    def _release(self, ticket: Ticket) -> None:
        res = None
        with self._cond:
            if ticket.released:
                return
            ticket.released = True
            res, ticket.res = ticket.res, None
            self._inflight -= 1
            self._pending_cost -= ticket.cost
            self._inflight_cost -= ticket.cost
            self._last_progress = self._clock()
            st = self._stats[ticket.priority]
            st.inflight -= 1
            st.drained += 1
            st.drained_cost += ticket.cost
            _queue_inflight.dec(cls=ticket.priority, chip=self.label)
            self._cond.notify_all()
        if res is not None:
            ledger, rt = res
            ledger.release(rt)


# --------------------------------------------------------------------------
# Residency: the physical admission layer under the scopes. ONE ledger
# per process (or one injected per test/bench), ONE lock for all chips
# — a mesh-wide stream acquires every chip it spans atomically, with no
# per-chip lock ordering to deadlock on.
# --------------------------------------------------------------------------


class _ResTicket:
    """One granted residency charge: `keys` are the physical chips
    holding a slot each until release. Idempotent release."""

    __slots__ = ("keys", "tenant", "priority", "cost", "released", "wait_s")

    def __init__(self, keys, tenant, priority, cost, wait_s):
        self.keys = keys
        self.tenant = tenant
        self.priority = priority
        self.cost = cost
        self.released = False
        self.wait_s = wait_s


class _ResWaiter:
    __slots__ = ("keys", "tenant", "priority", "cost", "t_submit", "seq")

    def __init__(self, keys, tenant, priority, cost, t_submit, seq):
        self.keys = keys
        self.tenant = tenant
        self.priority = priority
        self.cost = cost
        self.t_submit = t_submit
        self.seq = seq


class _ChipState:
    __slots__ = (
        "key", "budget", "inflight", "inflight_cost", "max_inflight",
        "max_inflight_cost", "admitted", "admitted_cost", "over_since",
        "breakers",
    )

    def __init__(self, key: str, budget: int):
        self.key = key
        self.budget = budget
        self.inflight = 0
        self.inflight_cost = 0
        # Watermarks are the chaos tests' GROUND TRUTH for the
        # invariant "N scopes on one chip never exceed the budget":
        # they record the worst concurrency the ledger ever granted,
        # not a sample that a racing reader could miss.
        self.max_inflight = 0
        self.max_inflight_cost = 0
        self.admitted = 0
        self.admitted_cost = 0
        # Wall time when the chip went full WITH waiters queued; None
        # while it has headroom. Sustained over_since drives the shed
        # level.
        self.over_since = None
        # weakrefs to this chip's fallback breakers (chip_pool wires
        # one per chip): an OPEN breaker means the chip's streams run
        # on CPU — degraded capacity feeds the shed level directly.
        self.breakers: list = []

    def breaker_open(self) -> bool:
        alive = []
        opened = False
        for ref in self.breakers:
            brk = ref()
            if brk is None:
                continue
            alive.append(ref)
            if getattr(brk, "state", "") == "open":
                opened = True
        self.breakers = alive
        return opened


class ResidencyLedger:
    """Process-wide per-physical-chip slot budget + tenant fairness +
    shed policy. Every DeviceQueue charges it in a second admission
    phase (after its own scope window), so the per-scope windows become
    sub-budgets of the chip's physical bound. See the module docstring
    for the policy; `budget`/`clock` are injectable for tests/bench."""

    def __init__(
        self,
        budget: int | None = None,
        starve_s: float | None = None,
        shed_after_s: float | None = None,
        shed_retry_s: float | None = None,
        tenant_window_s: float | None = None,
        clock=time.monotonic,
    ):
        if budget is None:
            budget = int(_env_float(
                "SEAWEED_EC_RESIDENCY_WINDOW", DEFAULT_RESIDENCY_BUDGET
            ))
        self.budget = max(1, int(budget))
        self.starve_s = float(
            starve_s if starve_s is not None
            else _env_float("SEAWEED_EC_TENANT_STARVE_S", DEFAULT_STARVE_S)
        )
        self.shed_after_s = float(
            shed_after_s if shed_after_s is not None
            else _env_float("SEAWEED_EC_SHED_AFTER_S", DEFAULT_SHED_AFTER_S)
        )
        self.shed_retry_s = float(
            shed_retry_s if shed_retry_s is not None
            else _env_float("SEAWEED_EC_SHED_RETRY_S", DEFAULT_SHED_RETRY_S)
        )
        self.tenant_window_s = max(float(
            tenant_window_s if tenant_window_s is not None
            else _env_float(
                "SEAWEED_EC_TENANT_WINDOW_S", DEFAULT_TENANT_WINDOW_S
            )
        ), 0.001)
        self._clock = clock
        self._cond = threading.Condition()
        self._chips: dict[str, _ChipState] = {}
        self._waiters: list[_ResWaiter] = []
        self._seq = itertools.count()
        self._last_progress = clock()
        # Tenant fairness accounting: admitted cost in two rotating
        # buckets (~2x tenant_window_s of history) — the virtual-time
        # signal that ranks a storm tenant behind a quiet one.
        self._tcost_cur: dict[str, float] = {}
        self._tcost_prev: dict[str, float] = {}
        self._bucket_start = clock()
        self._shed_counts: dict[str, int] = {}

    # ------------------------------------------------------------ internals

    def _chip(self, key: str) -> _ChipState:
        ch = self._chips.get(key)
        if ch is None:
            ch = self._chips[key] = _ChipState(key, self.budget)
            _res_budget_g.set(ch.budget, chip=key)
        return ch

    def _rotate_buckets(self, now: float) -> None:
        if now - self._bucket_start >= self.tenant_window_s:
            if now - self._bucket_start >= 2 * self.tenant_window_s:
                self._tcost_prev = {}
            else:
                self._tcost_prev = self._tcost_cur
            self._tcost_cur = {}
            self._bucket_start = now

    def _tenant_cost(self, tenant: str) -> float:
        return self._tcost_cur.get(tenant, 0.0) + self._tcost_prev.get(
            tenant, 0.0
        )

    def _update_pressure(self, now: float) -> None:
        waiting = set()
        for w in self._waiters:
            waiting.update(w.keys)
        for key, ch in self._chips.items():
            if ch.inflight >= ch.budget and key in waiting:
                if ch.over_since is None:
                    ch.over_since = now
            else:
                ch.over_since = None

    def _level(self, ch: _ChipState, now: float) -> int:
        lvl = 0
        if ch.over_since is not None:
            dur = now - ch.over_since
            if dur >= self.shed_after_s:
                lvl = 1
            if dur >= 3 * self.shed_after_s:
                lvl = 2
            if dur >= 6 * self.shed_after_s:
                lvl = 3
        if ch.breakers and ch.breaker_open():
            # a breaker-open chip is already degraded to CPU fallback:
            # escalate one level so background work yields sooner
            lvl = min(lvl + 1, 3)
        return lvl

    def _deferred(self, w: _ResWaiter, now: float) -> bool:
        """Graceful shedding, background first: scrub yields at level
        1+, recovery at level 2+. Foreground is never deferred here —
        its relief valve is shed_advice at the front ends. The
        starvation bound trumps deferral so a background class is
        slowed, never starved."""
        if w.priority == "foreground":
            return False
        if now - w.t_submit > self.starve_s:
            return False
        threshold = 1 if w.priority == "scrub" else 2
        return any(
            self._level(self._chip(k), now) >= threshold for k in w.keys
        )

    def _rank(self, w: _ResWaiter, now: float):
        starving = 0 if (now - w.t_submit > self.starve_s) else 1
        return (
            starving,
            PRIORITIES.index(w.priority),
            self._tenant_cost(w.tenant),
            w.seq,
        )

    def _fits(self, w: _ResWaiter) -> bool:
        return all(
            self._chip(k).inflight < self._chip(k).budget for k in w.keys
        )

    def _grantable(self, w: _ResWaiter, now: float) -> bool:
        if not self._fits(w) or self._deferred(w, now):
            return False
        # No better-ranked live contender on any shared chip: a wide
        # mesh waiter spanning this chip blocks a chip-local grant (it
        # must win eventually — head-of-line by design, so wide streams
        # cannot be starved by a trickle of single-chip admits).
        mine = self._rank(w, now)
        keys = set(w.keys)
        for other in self._waiters:
            if other is w or not (keys & set(other.keys)):
                continue
            if self._deferred(other, now):
                continue
            if self._rank(other, now) < mine:
                return False
        return True

    # ------------------------------------------------------------ public

    def acquire(
        self,
        keys,
        tenant: str,
        priority: str,
        cost: int,
        timeout: float = DEFAULT_ADMIT_TIMEOUT,
    ) -> _ResTicket:
        """Block until every chip in `keys` has a free slot AND this
        waiter is first under the fairness policy, then charge one slot
        per chip. Multi-chip acquire is atomic (one lock). Raises
        ECError past `timeout` with NO ledger progress anywhere (the
        same liveness contract as DeviceQueue._admit: merely being
        bypassed by the policy keeps waiting)."""
        faults.fire(
            "ec.residency.acquire", tenant=tenant, priority=priority,
        )
        keys = tuple(dict.fromkeys(keys))
        if not keys:
            raise ECError("residency acquire with no chip keys")
        cost = max(int(cost), 1)
        with self._cond:
            now = self._clock()
            self._rotate_buckets(now)
            w = _ResWaiter(keys, tenant, priority, cost, now, next(self._seq))
            self._waiters.append(w)
            try:
                self._update_pressure(now)
                while not self._grantable(w, self._clock()):
                    now = self._clock()
                    self._update_pressure(now)
                    deadline = (
                        max(w.t_submit, self._last_progress) + timeout
                    )
                    left = deadline - now
                    if left <= 0 or not self._cond.wait(
                        timeout=min(left, 1.0)
                    ):
                        now = self._clock()
                        if self._grantable(w, now):
                            break
                        if now - self._last_progress < timeout:
                            # bypassed (fairness/deferral), not wedged:
                            # pressure levels and starvation age change
                            # with TIME, so re-check at least once a
                            # second even with no release to notify us
                            continue
                        raise ECError(
                            f"residency acquire timed out after "
                            f"{timeout:.0f}s without progress "
                            f"(tenant={tenant}, {priority}, "
                            f"chips={','.join(keys)}): pod wedged?"
                        )
            finally:
                self._waiters.remove(w)
                # grant or abort, the next waiter may now be eligible
                self._cond.notify_all()
            now = self._clock()
            self._rotate_buckets(now)
            for k in keys:
                ch = self._chip(k)
                ch.inflight += 1
                ch.inflight_cost += cost
                ch.max_inflight = max(ch.max_inflight, ch.inflight)
                ch.max_inflight_cost = max(
                    ch.max_inflight_cost, ch.inflight_cost
                )
                ch.admitted += 1
                ch.admitted_cost += cost
                _res_inflight_g.set(ch.inflight, chip=k)
                _res_admitted.inc(tenant=tenant, chip=k)
                _res_admitted_cost.inc(cost, tenant=tenant, chip=k)
            # fairness is denominated in WORK, charged once per batch
            # (a wide stream does one batch of work, not one per chip)
            self._tcost_cur[tenant] = (
                self._tcost_cur.get(tenant, 0.0) + cost
            )
            self._last_progress = now
            self._update_pressure(now)
            wait_s = max(now - w.t_submit, 0.0)
            _res_wait_seconds.inc(wait_s, tenant=tenant, chip=keys[0])
        return _ResTicket(keys, tenant, priority, cost, wait_s)

    def release(self, ticket: _ResTicket) -> None:
        with self._cond:
            if ticket.released:
                return
            ticket.released = True
            for k in ticket.keys:
                ch = self._chip(k)
                ch.inflight -= 1
                ch.inflight_cost -= ticket.cost
                _res_inflight_g.set(ch.inflight, chip=k)
            now = self._clock()
            self._last_progress = now
            self._update_pressure(now)
            self._cond.notify_all()

    def register_breaker(self, key: str, breaker) -> None:
        """Attach a chip's fallback breaker so its OPEN state feeds the
        shed level. Weakly held; duplicates are fine."""
        if breaker is None:
            return
        with self._cond:
            ch = self._chip(key)
            if not any(ref() is breaker for ref in ch.breakers):
                try:
                    ch.breakers.append(weakref.ref(breaker))
                except TypeError:
                    pass  # unweakrefable test double: skip the feed

    def loads(self) -> dict[str, int]:
        """Per-chip in-flight COST across every scope — the cross-scope
        live-load signal chip_pool routing adds to each scope's own
        queue view (the PR 14 carried item)."""
        with self._cond:
            return {
                k: ch.inflight_cost for k, ch in self._chips.items()
            }

    def shed_level(self) -> int:
        """Worst per-chip shed level right now (0 = no pressure)."""
        with self._cond:
            now = self._clock()
            self._update_pressure(now)
            return max(
                (self._level(ch, now) for ch in self._chips.values()),
                default=0,
            )

    def shed_advice(self, tenant: str) -> float | None:
        """Should the front ends 503 this tenant right now? Returns the
        Retry-After seconds to send, or None to serve. Only tenants
        whose windowed admitted-cost share EXCEEDS their fair share are
        shed (per-tenant, never per-server): the storm pays, the
        well-behaved tenant keeps serving through the overload."""
        with self._cond:
            now = self._clock()
            self._rotate_buckets(now)
            self._update_pressure(now)
            worst = max(
                (self._level(ch, now) for ch in self._chips.values()),
                default=0,
            )
            if worst < 3:
                return None
            mine = self._tenant_cost(tenant)
            if mine <= 0.0:
                return None  # no recent device work: not the storm
            # Fair share is over every tenant CONTENDING — admitted
            # cost or queued waiters. A storm tenant holding 100% while
            # the victim is still stuck waiting must read as over-share
            # even though the victim has no admitted cost yet.
            tenants = set(self._tcost_cur) | set(self._tcost_prev)
            tenants.update(w.tenant for w in self._waiters)
            total = sum(self._tenant_cost(t) for t in tenants)
            fair = total / max(len(tenants), 1)
            if mine <= fair * 1.05:  # hysteresis: at-share is served
                return None
            self._shed_counts[tenant] = self._shed_counts.get(tenant, 0) + 1
            _res_shed.inc(tenant=tenant)
            return self.shed_retry_s

    def snapshot(self) -> dict:
        """Full observable state: per-chip budget/inflight/watermarks/
        pressure and per-tenant windowed cost + shed counts. The chaos
        tests' ground truth and the telemetry/status payload."""
        with self._cond:
            now = self._clock()
            self._rotate_buckets(now)
            self._update_pressure(now)
            chips = {}
            for k, ch in self._chips.items():
                lvl = self._level(ch, now)
                _res_pressure_g.set(lvl, chip=k)
                chips[k] = {
                    "budget": ch.budget,
                    "inflight": ch.inflight,
                    "inflight_cost": ch.inflight_cost,
                    "max_inflight": ch.max_inflight,
                    "max_inflight_cost": ch.max_inflight_cost,
                    "admitted": ch.admitted,
                    "admitted_cost": ch.admitted_cost,
                    "pressure": lvl,
                    "over_s": (
                        round(now - ch.over_since, 3)
                        if ch.over_since is not None
                        else 0.0
                    ),
                    "breaker_open": (
                        ch.breaker_open() if ch.breakers else False
                    ),
                }
            tenants = {
                t: {
                    "windowed_cost": round(self._tenant_cost(t), 1),
                    "shed": self._shed_counts.get(t, 0),
                }
                for t in (
                    set(self._tcost_cur)
                    | set(self._tcost_prev)
                    | set(self._shed_counts)
                )
            }
            return {
                "budget": self.budget,
                "chips": chips,
                "tenants": tenants,
                "waiters": len(self._waiters),
            }


def _residency_keys(backend) -> tuple[str, ...]:
    """The physical chip identities one batch of `backend` occupies.
    A (possibly fallback-wrapped) pinned chip is one key; a MESH
    backend dispatches one batch across EVERY device it spans, so it
    charges them all — this is exactly how the wide-stream path stops
    admitting past the per-chip queues. Backends with no device
    identity (pure NumPy) get their synthetic queue label: a private
    chip nobody else can collide with."""
    label = getattr(backend, "chip_label", "") or getattr(
        getattr(backend, "primary", None), "chip_label", ""
    )
    if label:
        return (label,)
    for obj in (backend, getattr(backend, "primary", None)):
        mesh_rs = getattr(obj, "_mesh_rs", None)
        if mesh_rs is None:
            continue
        labels = getattr(mesh_rs, "device_labels", None)
        if callable(labels):
            try:
                keys = tuple(labels())
            except Exception:
                keys = ()
            if keys:
                return keys
    return (_queue_label(backend),)


_residency_lock = threading.Lock()
_residency_default: "ResidencyLedger | None" = None
_residency_init = False


def default_residency() -> ResidencyLedger | None:
    """The process-wide ledger (lazily built from the SEAWEED_EC_*
    knobs), or None when SEAWEED_EC_RESIDENCY_WINDOW=0 disabled it."""
    global _residency_default, _residency_init
    with _residency_lock:
        if not _residency_init:
            budget = int(_env_float(
                "SEAWEED_EC_RESIDENCY_WINDOW", DEFAULT_RESIDENCY_BUDGET
            ))
            _residency_default = (
                ResidencyLedger(budget=budget) if budget > 0 else None
            )
            _residency_init = True
        return _residency_default


def shed_advice(tenant: str) -> float | None:
    """Front-end hook: Retry-After seconds if `tenant` should be shed
    under current pod pressure, else None. Never raises."""
    try:
        led = default_residency()
        return led.shed_advice(tenant) if led is not None else None
    except Exception:
        return None


def shed_level() -> int:
    """Worst chip shed level of the process ledger (0 when off/idle) —
    background daemons (e.g. the MQ parity flusher) stretch their
    cadence by this."""
    try:
        led = default_residency()
        return led.shed_level() if led is not None else 0
    except Exception:
        return 0


def residency_snapshot() -> dict:
    """The process ledger's snapshot() for /status, heartbeats and
    /debug/gateway; {} when the ledger is disabled."""
    try:
        led = default_residency()
        return led.snapshot() if led is not None else {}
    except Exception:
        return {}


# --------------------------------------------------------------------------
# Scopes: one scheduler/placement config domain + its queue registry.
# The process-wide default scope backs the module-level functions; a
# Store may carry a private scope (multi-tenant embedding) so one
# tenant's configure() stops clobbering another's.
# --------------------------------------------------------------------------


_label_lock = threading.Lock()
_label_seq: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_label_next = itertools.count()


def _queue_label(backend) -> str:
    """Chip identity for stats/metrics: the pool chip's device id when
    the backend is (or wraps) a pinned ChipBackend, else the backend
    class name qualified by its shard ratio and an instance tag (one
    single-device/mesh backend = one chip) — two same-class backends
    (e.g. volumes at 10+4 and 5+2) must not merge into one label set.
    The tag is a process-wide monotonic sequence number (id() bits can
    collide after allocator reuse, silently summing two backends'
    gauges into one series)."""
    label = getattr(backend, "chip_label", "")
    if not label:
        label = getattr(getattr(backend, "primary", None), "chip_label", "")
    if label:
        return label
    ctx = getattr(backend, "ctx", None)
    ratio = (
        f":{ctx.data_shards}+{ctx.parity_shards}"
        if ctx is not None
        else ""
    )
    with _label_lock:
        seq = _label_seq.get(backend)
        if seq is None:
            seq = _label_seq[backend] = next(_label_next)
    return f"{type(backend).__name__}{ratio}@{seq}"


class QueueScope:
    """One scheduler/placement configuration domain.

    Holds the enable flag, window, per-class shares, and the stream
    placement mode (`auto|mesh|chip`, consumed by ec/chip_pool.py),
    plus the registry of live DeviceQueues created under this scope.
    Queues are per (scope, backend): two scopes sharing a chip each get
    their own admission policy — the multi-tenant contract is isolation
    of CONFIG, while the physical chip pool (ec/chip_pool.py) stays
    process-wide so placement still sees total chip load.

    `tenant` names this scope's fairness/shed accounting domain on the
    shared ResidencyLedger (default "default": unnamed scopes pool
    their accounting, named Stores get per-tenant QoS). `residency`
    selects the physical ledger the scope's queues charge: None = the
    process-wide default (env-gated), False = no physical ledger (the
    pre-PR 16 logical-window-only behavior), or an injected
    ResidencyLedger (tests/bench)."""

    def __init__(
        self,
        enabled: bool = True,
        window: int = DEFAULT_WINDOW,
        shares: dict[str, float] | None = None,
        placement: str = DEFAULT_PLACEMENT,
        tenant: str | None = None,
        residency: "ResidencyLedger | None | bool" = None,
    ):
        self.tenant = tenant or "default"
        self._residency_cfg = residency
        self._lock = threading.Lock()
        self._queues: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
        self._config: dict = {
            "enabled": True,
            "window": DEFAULT_WINDOW,
            "shares": dict(DEFAULT_SHARES),
            "placement": DEFAULT_PLACEMENT,
        }
        self.configure(
            enabled=enabled, window=window, shares=shares or {},
            placement=placement,
        )

    def configure(
        self,
        enabled: bool | None = None,
        window: int | None = None,
        shares: dict[str, float] | None = None,
        placement: str | None = None,
    ) -> dict:
        """Update this scope's scheduler knobs; the LAST caller wins
        WITHIN the scope. A `shares` dict (even empty) REPLACES the
        whole share map — classes it omits return to DEFAULT_SHARES, so
        one caller's override can never stick invisibly to the next
        caller's config; None leaves the current map untouched.
        `placement` selects the chip-pool routing mode (auto|mesh|chip).
        Live queues pick the new values up immediately; `enabled=False`
        makes `for_backend` return None so every producer falls back to
        its private PR 3 window. Returns the effective config.

        Multi-tenant embedders should configure a per-Store scope
        (`Store(ec_queue_window=...)`) instead of the process-wide
        default this module's bare `configure()` mutates."""
        # Validate EVERY input before mutating anything: a rejected
        # call must not leave the scope half-configured (live queues on
        # the old window while later-created queues get the new one).
        merged = None
        if shares is not None:
            merged = dict(DEFAULT_SHARES)
            for cls, s in shares.items():
                if cls not in PRIORITIES:
                    raise ECError(f"unknown priority class {cls!r}")
                merged[cls] = min(max(float(s), 0.0), 0.9)
        if placement is not None and placement not in PLACEMENT_MODES:
            raise ECError(
                f"unknown ec_placement {placement!r} "
                f"(want one of {PLACEMENT_MODES})"
            )
        if window is not None:
            window = max(1, int(window))
        with self._lock:
            if enabled is not None:
                self._config["enabled"] = bool(enabled)
            if window is not None:
                self._config["window"] = window
            if merged is not None:
                self._config["shares"] = merged
            if placement is not None:
                self._config["placement"] = placement
            live = list(self._queues.values())
            cfg = {
                "enabled": self._config["enabled"],
                "window": self._config["window"],
                "shares": dict(self._config["shares"]),
                "placement": self._config["placement"],
            }
        for q in live:
            with q._cond:
                q.window = cfg["window"]
                q.shares = dict(cfg["shares"])
                q._cond.notify_all()
        return cfg

    @property
    def enabled(self) -> bool:
        with self._lock:
            return self._config["enabled"]

    @property
    def placement(self) -> str:
        with self._lock:
            return self._config["placement"]

    def residency(self) -> "ResidencyLedger | None":
        """This scope's physical ledger (None = logical windows only)."""
        cfg = self._residency_cfg
        if cfg is False:
            return None
        if cfg is None:
            return default_residency()
        return cfg

    def for_backend(self, backend) -> DeviceQueue | None:
        """The shared queue for `backend`'s chip under this scope, or
        None when the scheduler is disabled (or there is no backend —
        the pass-through pipeline)."""
        if backend is None:
            return None
        with self._lock:
            if not self._config["enabled"]:
                return None
            q = self._queues.get(backend)
            if q is None:
                ledger = self.residency()
                keys = _residency_keys(backend)
                q = DeviceQueue(
                    window=self._config["window"],
                    shares=self._config["shares"],
                    label=_queue_label(backend),
                    residency=ledger,
                    res_keys=keys,
                    tenant=self.tenant,
                )
                self._queues[backend] = q
                if ledger is not None:
                    # breaker-state feed for the shed policy: a pinned
                    # chip's fallback breaker flapping open escalates
                    # that chip's pressure level
                    brk = getattr(backend, "breaker", None)
                    if brk is not None and len(keys) == 1:
                        ledger.register_breaker(keys[0], brk)
            return q

    def stats_snapshot(self) -> list[dict]:
        """Per-queue per-class counters for /status and ops tooling,
        keyed per chip (`chip` = device id for pool chips). `breaker`
        carries the chip's fallback-breaker state ("open" = this chip's
        streams are failing over to CPU; "" = the backend has no
        breaker) so the server can surface pod health."""
        with self._lock:
            items = [
                (type(b).__name__, getattr(b, "breaker", None), q)
                for b, q in self._queues.items()
            ]
        return [
            {
                "backend": name,
                "chip": q.label,
                "window": q.window,
                "breaker": brk.state if brk is not None else "",
                "load": q.load(),
                "classes": q.stats(),
            }
            for name, brk, q in items
        ]

    def queue_loads(self) -> dict[str, dict]:
        """Read-only per-chip load view: {chip_label: {"load": cost
        units queued+in-flight, "breaker": state}} — the cheap form of
        stats_snapshot for routing hints and heartbeat telemetry."""
        with self._lock:
            items = [
                (getattr(b, "breaker", None), q)
                for b, q in self._queues.items()
            ]
        out = {}
        for brk, q in items:
            with q._cond:
                load, infl = q._pending_cost, q._inflight_cost
            out[q.label] = {
                "load": load,
                "inflight_cost": infl,
                "breaker": brk.state if brk is not None else "",
            }
        return out

    def residency_loads(self) -> dict[str, int]:
        """Per-chip in-flight cost on this scope's PHYSICAL ledger —
        all scopes combined ({} when the ledger is off). chip_pool adds
        the cross-scope share of this on top of queue_loads()."""
        ledger = self.residency()
        return ledger.loads() if ledger is not None else {}


_DEFAULT_SCOPE = QueueScope()


def default_scope() -> QueueScope:
    """The process-wide scope backing the module-level functions."""
    return _DEFAULT_SCOPE


def resolve_scope(scope: QueueScope | None) -> QueueScope:
    return scope if scope is not None else _DEFAULT_SCOPE


def configure(
    enabled: bool | None = None,
    window: int | None = None,
    shares: dict[str, float] | None = None,
    placement: str | None = None,
) -> dict:
    """Process-wide DEFAULT-scope scheduler knobs; the LAST caller wins
    wholesale within that scope. See QueueScope.configure for the
    semantics; per-chip stats surface through `stats_snapshot` keyed by
    the queue's `chip` label (device id once a chip pool exists).
    Multi-tenant embedders should thread a per-Store scope through
    `Store(...)` instead of calling this."""
    return _DEFAULT_SCOPE.configure(
        enabled=enabled, window=window, shares=shares, placement=placement
    )


def for_backend(backend, scope: QueueScope | None = None) -> DeviceQueue | None:
    """The shared queue for `backend`'s chip (in `scope`, default the
    process-wide scope), or None when the scheduler is disabled."""
    return resolve_scope(scope).for_backend(backend)


def stats_snapshot(scope: QueueScope | None = None) -> list[dict]:
    """Per-queue per-class counters for /status and ops tooling."""
    return resolve_scope(scope).stats_snapshot()
