"""Hot-volume rebalancing: move whole EC shard sets toward compute.

PR 14 closed the routing loop for NEW bytes (placement reads heartbeat
telemetry), but bytes that already landed stay wherever disk headroom
put them — a hot EC volume whose shards sit on a chip-poor (or
breaker-open, or queue-saturated) node reconstructs at CPU-fallback
speed forever while chip-rich nodes idle. This module is the data-
gravity layer for EXISTING bytes:

- **heat**: per-EC-volume ``read_bytes``/``reconstructed_bytes``
  counters ride the heartbeat telemetry blob
  (``VolumeServer._ec_telemetry_json`` -> ``ec_volumes``); the
  master-side scanner diffs them per sweep so heat is a rate, not a
  lifetime total.
- **planner** (:func:`plan_hot_migrations`): rank (volume heat x holder
  chip-deficit), pick a strictly-better-gravity destination honoring
  every placement invariant (slot capacity, byte headroom, per-volume
  spread, across-rack ceiling), move the holder's WHOLE shard set —
  the unit a migration task executes.
- **driver** (:func:`drive_migration`): the worker-task executor —
  copy (net-plane sendfile preferred) -> verify against the sidecar ->
  unmount source -> mount destination -> delete source. Generation-
  fenced, idempotent on crash-rerun, and NEVER two mounted holders: the
  source unmounts before the destination mounts, so the worst crash
  window leaves the shard set durable on both disks but served by at
  most one node, and a re-run converges to exactly one mounted holder.

The planner is pure (NodeViews + heat dicts in, Migrations out) so it
is testable against synthetic skew the way ``plan_ec_balance`` is; the
driver takes gRPC stubs through a resolver so tests/bench drive real
in-process servers.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

from .. import faults
from ..utils import metrics as _M
from ..utils import trace
from ..utils.glog import logger
from .placement import NodeView, gravity_key

log = logger("ec.rebalance")

_migrations_total = _M.REGISTRY.counter(
    "sw_ec_migrations_total",
    "hot-volume shard-set migrations driven, by outcome",
    ("outcome",),
)


def min_heat_bytes() -> int:
    """SEAWEED_EC_REBALANCE_MIN_HEAT_MB: a volume must serve at least
    this many read/reconstruction bytes per scan window on one holder
    before the scanner considers migrating it (default 1 MiB)."""
    try:
        return int(
            float(os.environ.get("SEAWEED_EC_REBALANCE_MIN_HEAT_MB", "1"))
            * (1 << 20)
        )
    except ValueError:
        return 1 << 20


def max_migrations_per_sweep() -> int:
    """SEAWEED_EC_REBALANCE_MAX_MOVES: migrations dispatched per scan
    sweep (default 1 — one bounded move per tick keeps the plane
    convergent, the scan_for_ec_scrub discipline)."""
    try:
        return max(int(os.environ.get("SEAWEED_EC_REBALANCE_MAX_MOVES", "1")), 1)
    except ValueError:
        return 1


def min_gravity_gain() -> float:
    """SEAWEED_EC_REBALANCE_MIN_GAIN: destination gravity_score must
    exceed the holder's by this factor before a migration is worth its
    wire bytes (default 1.5)."""
    try:
        return float(os.environ.get("SEAWEED_EC_REBALANCE_MIN_GAIN", "1.5"))
    except ValueError:
        return 1.5


def volume_heat(telemetry: dict | None) -> dict[int, int]:
    """Extract {vid: heat_bytes} from one node's telemetry blob's
    ``ec_volumes`` map (read + reconstructed bytes — reconstruction
    weighs double: it is the work gravity exists to move toward
    chips). Malformed blobs read as no heat."""
    if not telemetry:
        return {}
    vols = telemetry.get("ec_volumes")
    if not isinstance(vols, dict):
        return {}
    out: dict[int, int] = {}
    for vid, c in vols.items():
        try:
            out[int(vid)] = int(c.get("read_bytes", 0)) + 2 * int(
                c.get("reconstructed_bytes", 0)
            )
        except (TypeError, ValueError, AttributeError):
            continue
    return out


@dataclass(frozen=True)
class Migration:
    """Move the holder `src`'s whole shard set of `vid` to `dst`."""

    vid: int
    src: str
    dst: str
    shard_ids: tuple[int, ...]
    heat: int
    src_gravity: float
    dst_gravity: float

    def rank(self) -> float:
        """heat x chip-deficit: what the scanner sorts on."""
        return self.heat * max(self.dst_gravity - self.src_gravity, 0.0)


def plan_hot_migrations(
    nodes: list[NodeView],
    heat: dict[str, dict[int, int]],
    *,
    shard_bytes: dict[int, int] | None = None,
    min_heat: int | None = None,
    max_migrations: int | None = None,
    min_gain: float | None = None,
) -> list[Migration]:
    """Rank hot (volume, holder) pairs by heat x holder chip-deficit
    and plan bounded whole-shard-set migrations toward strictly
    better-gravity nodes.

    `heat` is {node_id: {vid: bytes served this window}} (see
    :func:`volume_heat`); `shard_bytes` ({vid: bytes per shard}) gates
    destinations on known disk headroom. Deterministic under a fixed
    input (ties break on vid then node id); mutates nothing — planned
    moves are reflected in LOCAL copies of the capacity counters so a
    sweep never plans two migrations onto headroom that only exists
    once.

    Invariants (a migration is never planned that would violate them):

    - destination holds NO shard of the volume (per-node spread can
      only improve or stay equal — the whole set moves);
    - destination has >= len(shard_ids) free slots and, when byte
      headroom is known, fits len(shard_ids) x shard_bytes;
    - with >= 2 racks, the destination rack stays within the
      ceil(total/racks) across-rack ceiling for the volume;
    - destination gravity_score >= min_gain x holder gravity_score
      (and strictly better by `gravity_key`).
    """
    if min_heat is None:
        min_heat = min_heat_bytes()
    if max_migrations is None:
        max_migrations = max_migrations_per_sweep()
    if min_gain is None:
        min_gain = min_gravity_gain()
    by_id = {n.id: n for n in nodes}
    racks: dict[tuple[str, str], list[NodeView]] = {}
    for n in nodes:
        racks.setdefault(n.rack_key(), []).append(n)
    multi_rack = len(racks) >= 2

    # candidate (heat x deficit) ranking over every hot holder
    scored: list[tuple[float, int, str]] = []
    for node_id, vols in heat.items():
        holder = by_id.get(node_id)
        if holder is None:
            continue
        h_score = holder.gravity_score()
        best = max(
            (
                n.gravity_score()
                for n in nodes
                if n is not holder and n.free_slots > 0
            ),
            default=0.0,
        )
        deficit = max(best - h_score, 0.0)
        if deficit <= 0.0:
            continue
        for vid, heat_bytes in vols.items():
            if heat_bytes < min_heat or not holder.shards.get(vid):
                continue
            scored.append((heat_bytes * deficit, vid, node_id))
    scored.sort(key=lambda t: (-t[0], t[1], t[2]))

    plans: list[Migration] = []
    # local capacity mutation so one sweep's plans don't stack
    free_slots = {n.id: n.free_slots for n in nodes}
    free_bytes = {n.id: n.free_bytes for n in nodes}
    moved_vids: set[int] = set()
    for _rank, vid, src_id in scored:
        if len(plans) >= max_migrations:
            break
        if vid in moved_vids:
            continue  # one migration per volume per sweep
        src = by_id[src_id]
        sids = tuple(sorted(src.shards.get(vid, ())))
        if not sids:
            continue
        per_shard = (shard_bytes or {}).get(vid, 0)
        need_bytes = per_shard * len(sids)
        total = sum(len(n.shards.get(vid, ())) for n in nodes)
        ceiling = -(-total // len(racks)) if multi_rack else total

        def rack_count(rk: tuple[str, str]) -> int:
            return sum(len(n.shards.get(vid, ())) for n in racks[rk])

        candidates = [
            d
            for d in nodes
            if d is not src
            and not d.shards.get(vid)
            and free_slots[d.id] >= len(sids)
            and not (need_bytes > 0 and 0 <= free_bytes[d.id] < need_bytes)
            and gravity_key(d) < gravity_key(src)
            and d.gravity_score() >= min_gain * max(src.gravity_score(), 1e-9)
            and (
                not multi_rack
                or d.rack_key() == src.rack_key()
                or rack_count(d.rack_key()) + len(sids) <= ceiling
            )
        ]
        if not candidates:
            continue
        dst = min(
            candidates,
            key=lambda d: (*gravity_key(d), -free_slots[d.id], d.id),
        )
        plans.append(
            Migration(
                vid=vid,
                src=src.id,
                dst=dst.id,
                shard_ids=sids,
                heat=int((heat.get(src_id) or {}).get(vid, 0)),
                src_gravity=src.gravity_score(),
                dst_gravity=dst.gravity_score(),
            )
        )
        moved_vids.add(vid)
        free_slots[dst.id] -= len(sids)
        if free_bytes[dst.id] >= 0:
            free_bytes[dst.id] = max(free_bytes[dst.id] - need_bytes, 0)
    return plans


# ---------------------------------------------------------------------------
# Driver — the ec_migrate worker task body (also driven by the bench
# and the crash-rerun tests).
# ---------------------------------------------------------------------------


def drive_migration(
    vid: int,
    collection: str,
    src_grpc: str,
    dst_grpc: str,
    shard_ids,
    *,
    stub_for,
    lookup_ec=None,
    timeout: float = 3600.0,
) -> dict:
    """Execute one whole-shard-set migration: copy -> (sidecar-verified
    inside ``VolumeEcShardsCopy``) -> unmount source -> mount
    destination -> delete source files.

    ``stub_for(grpc_addr)`` returns a volume-service stub;
    ``lookup_ec()`` (optional) returns the live ``{sid: [urls]}``
    holder map used for idempotent re-runs.

    Ordering is the NEVER-TWO-MOUNTED-HOLDERS protocol:

    1. copy lands the shard files (+ index/sidecar on first contact)
       at the destination, atomically per file, UNMOUNTED — the source
       keeps serving; a crash here changed nothing visible.
    2. source unmounts the set (files stay on its disk): reads degrade
       to reconstruction for at most the mount gap; at no instant do
       two holders advertise the same shard.
    3. destination mounts (its heartbeat advertises the set).
    4. source deletes its now-redundant files.

    A re-run after ANY crash window converges: the copy is idempotent
    (atomic per-file replace, bit-verified against the sidecar),
    unmount/mount/delete are no-ops where already done, and the final
    state is exactly one mounted holder. Fault points
    ``ec.migrate.{before_copy,after_copy,after_unmount,after_mount}``
    enumerate the windows for the chaos tests."""
    sids = sorted(int(s) for s in shard_ids)
    if not sids:
        return {"migrated": [], "skipped": "empty shard set"}
    sp = trace.start(
        "ec.migrate", volume=vid, src=src_grpc, dst=dst_grpc, shards=sids
    )
    try:
        with trace.activate(sp):
            return _drive_migration(
                vid, collection, src_grpc, dst_grpc, sids,
                stub_for=stub_for, lookup_ec=lookup_ec, timeout=timeout,
                span=sp,
            )
    except BaseException:
        _migrations_total.inc(outcome="failed")
        raise
    finally:
        trace.finish(sp)


def _drive_migration(
    vid, collection, src_grpc, dst_grpc, sids, *, stub_for, lookup_ec,
    timeout, span
):
    from ..pb import cluster_pb2 as pb

    src = stub_for(src_grpc)
    dst = stub_for(dst_grpc)
    md = trace.grpc_metadata()

    # Idempotence scouting: which of the set does the destination
    # already SERVE (mounted + advertised)? A prior run that crashed
    # after its mount only needs the source cleanup.
    dst_has: set[int] = set()
    src_has: set[int] = set()
    if lookup_ec is not None:
        try:
            located = lookup_ec()
        except Exception as e:  # noqa: BLE001 — scouting is best-effort
            log.warning("migrate ec %d: holder lookup failed: %s", vid, e)
            located = {}
        for sid, urls in located.items():
            if int(sid) not in sids:
                continue
            for u in urls:
                if u == dst_grpc:
                    dst_has.add(int(sid))
                if u == src_grpc:
                    src_has.add(int(sid))
    need_copy = [s for s in sids if s not in dst_has]
    trace.event(
        span, "migrate_scout", dst_has=sorted(dst_has),
        src_has=sorted(src_has), need_copy=need_copy,
    )

    faults.fire("ec.migrate.before_copy", volume=vid)
    if need_copy:
        # index/sidecar files ride along when the destination has no
        # shard of this volume yet (the ec.balance first_on_dst rule)
        first_on_dst = not dst_has
        dst.VolumeEcShardsCopy(
            pb.EcShardsCopyRequest(
                volume_id=vid,
                collection=collection,
                shard_ids=need_copy,
                source_url=src_grpc,
                copy_ecx=first_on_dst,
                copy_ecj=first_on_dst,
                copy_vif=first_on_dst,
                copy_ecsum=first_on_dst,
            ),
            timeout=timeout,
            metadata=md,
        )
    faults.fire("ec.migrate.after_copy", volume=vid)

    # Source stops serving BEFORE the destination starts: never two
    # mounted holders. Unmount of an already-unmounted set is a no-op.
    src.VolumeEcShardsUnmount(
        pb.EcShardsUnmountRequest(volume_id=vid, shard_ids=sids),
        timeout=60,
        metadata=md,
    )
    faults.fire("ec.migrate.after_unmount", volume=vid)

    dst.VolumeEcShardsMount(
        pb.EcShardsMountRequest(volume_id=vid, collection=collection),
        timeout=60,
        metadata=md,
    )
    faults.fire("ec.migrate.after_mount", volume=vid)

    # Source cleanup: the destination serves the set now; the source
    # files are redundant bytes (and a future dedupe target).
    src.VolumeEcShardsDelete(
        pb.EcShardsDeleteRequest(
            volume_id=vid, collection=collection, shard_ids=sids
        ),
        timeout=60,
        metadata=md,
    )
    _migrations_total.inc(outcome="done")
    return {"migrated": sids, "copied": need_copy, "src": src_grpc,
            "dst": dst_grpc}
