"""EC encode: volume (.dat + .idx) -> .ec00.. shards, .ecx, .ecsum, .vif.

Reference pipeline: weed/storage/erasure_coding/ec_encoder.go
(WriteEcFiles / encodeDatFile / encodeDataOneBatch) and the server RPC
VolumeEcShardsGenerate (volume_grpc_erasure_coding.go:45), which writes
the .ecx BEFORE the shards to close a write race, then persists .ecsum
and .vif.

TPU-first divergence: the reference feeds its SIMD encoder 256KB
buffers; a device wants batches in the tens of MB. Because parity is
columnwise-independent, any batch split of a stripe row produces
bit-identical shards, so the backend is fed `batch_size` columns at a
time (default 16 MiB per shard => 160 MiB device input at 10+4) and the
shard files/CRC builders are appended chunk by chunk in offset order.
"""

from __future__ import annotations

import os
import time

import numpy as np

from .. import faults
from ..storage.needle_map import MemDb
from ..utils import trace
from .backend import RSBackend, get_backend
from .bitrot import BitrotProtection
from .context import (
    BITROT_LEAF_SIZE,
    LARGE_BLOCK_SIZE,
    SMALL_BLOCK_SIZE,
    DEFAULT_EC_CONTEXT,
    ECContext,
    ECError,
)
from .pipeline import make_shard_sink, run_pipeline
from .volume_info import VolumeInfo

DEFAULT_BATCH = 16 * 1024 * 1024

# A stream at least this large (source bytes: the .dat for encode,
# k x shard extent for rebuild) counts as "wide" for placement: a lone
# wide stream on an idle pod keeps the column-mesh slicing (all chips
# on one stream); anything smaller — or any stream with competitors —
# is placed whole onto the least-loaded chip (ec/chip_pool.py,
# `ec_placement=auto`).
WIDE_STREAM_BYTES = 1 << 30


def _pread_padded(fd: int, buf: np.ndarray, offset: int) -> None:
    """Fill `buf` from fd at `offset` IN PLACE (no intermediate bytes
    object), zero-padding past EOF."""
    mv = memoryview(buf)
    filled = 0
    want = len(buf)
    while filled < want:
        got = os.preadv(fd, [mv[filled:]], offset + filled)
        if got == 0:
            break
        filled += got
    if filled < want:
        buf[filled:] = 0


def write_sorted_file_from_idx(base: str, ext: str = ".ecx") -> None:
    """Convert write-ordered .idx -> sorted sealed index (reference
    WriteSortedFileFromIdx, ec_encoder.go:32-59)."""
    db = MemDb()
    db.load_idx(base + ".idx")
    db.write_sorted_file(base + ext)


def write_ec_files(
    base: str,
    ctx: ECContext = DEFAULT_EC_CONTEXT,
    backend: RSBackend | None = None,
    batch_size: int = DEFAULT_BATCH,
    large_block_size: int = LARGE_BLOCK_SIZE,
    small_block_size: int = SMALL_BLOCK_SIZE,
    leaf_size: int = BITROT_LEAF_SIZE,
    scheduler=None,
) -> BitrotProtection:
    """Stripe+encode base.dat into base.ec00..; returns bitrot CRCs
    accumulated during the same pass. `leaf_size` > 0 additionally rolls
    the v2 sidecar's per-leaf CRCs (same pass, same bytes); 0 emits a
    v1 (block-level only) sidecar. `scheduler` is the QueueScope whose
    placement/admission config this encode stream runs under (None =
    the process-wide default)."""
    if backend is None:
        backend = get_backend("auto", ctx.data_shards, ctx.parity_shards)
    k, total = ctx.data_shards, ctx.total

    dat_fd = os.open(base + ".dat", os.O_RDONLY)
    outputs: list = []
    # Flight-recorder span for the encode pipeline (a child when called
    # under ec_encode_volume's root; its own root for direct callers).
    sp = trace.start(
        "ec.encode", name=os.path.basename(base), base=base,
        batch_size=batch_size,
    )
    try:
        for i in range(total):
            # buffering=0: the fused native sink writes via raw fds; the
            # Python fallback writes whole >=1MiB batches, where a
            # userspace buffer adds a copy and saves nothing.
            outputs.append(open(base + ctx.to_ext(i), "wb", buffering=0))
        sink = make_shard_sink(outputs, leaf_size=leaf_size)
        dat_size = os.fstat(dat_fd).st_size
        large_row = large_block_size * k
        small_row = small_block_size * k

        # Row/chunk schedule: the hot loop is disk-bound (SURVEY.md hard
        # part (b)), so reads, H2D staging, device encode, and shard
        # writes run as the shared 4-stage pipeline (ec/pipeline.py) —
        # the device computes batch N while batch N+1 is read/transferred
        # and batch N-1 drains to host and disk.
        def chunk_plan():
            processed = 0
            remaining = dat_size
            while remaining >= large_row:
                yield processed, large_block_size
                processed += large_row
                remaining -= large_row
            while remaining > 0:
                yield processed, small_block_size
                processed += small_row
                remaining -= small_row

        def batch_plan():
            """(row_offset, block_size, chunk_off, width) per batch."""
            for row_offset, block_size in chunk_plan():
                batch = min(batch_size, block_size)
                for chunk_off in range(0, block_size, batch):
                    yield (
                        row_offset, block_size, chunk_off,
                        min(batch, block_size - chunk_off),
                    )

        # Native read source (ec/native_io.py): one GIL-releasing
        # batched pread per batch straight into a pooled aligned matrix
        # that flows read -> device -> sink untouched (the zero-copy
        # plane), with the NEXT batch's extents readahead-hinted before
        # this one reads. An armed fault registry or SEAWEED_EC_NATIVE=0
        # keeps the bit-identical Python preadv loop.
        from . import native_io

        use_native = native_io.enabled() and not faults.active()
        pool = native_io.BufferPool(k) if use_native else None

        def produce():
            plan = list(batch_plan())
            for n_batch, (row_offset, block_size, chunk_off, width) in (
                enumerate(plan)
            ):
                with trace.stage(sp, "disk_read"):
                    offsets = [
                        row_offset + i * block_size + chunk_off
                        for i in range(k)
                    ]
                    if use_native:
                        if n_batch + 1 < len(plan):
                            nro, nbs, nco, nw = plan[n_batch + 1]
                            for i in range(k):
                                native_io.prefetch(
                                    dat_fd, nro + i * nbs + nco, nw
                                )
                        data = pool.get(width)
                        native_io.read_batch(
                            [dat_fd] * k, offsets, data, pad_eof=True
                        )
                    else:
                        data = np.empty((k, width), dtype=np.uint8)
                        for i in range(k):
                            _pread_padded(dat_fd, data[i], offsets[i])
                yield data

        # Encode is SERVING traffic: it dispatches as a foreground
        # stream of the shared per-chip scheduler (ec/device_queue.py),
        # so a colocated background rebuild yields the H2D slot at
        # every batch boundary instead of head-of-line-blocking the
        # encode. On a multi-chip backend the WHOLE stream is placed
        # onto the least-loaded chip (ec/chip_pool.py) — only a huge
        # lone encode on an idle pod keeps the column-mesh slicing.
        # Scheduler disabled -> the PR 3 private window on the original
        # backend.
        from .chip_pool import place_stream
        from .device_queue import batch_cost

        m = ctx.parity_shards
        placement = place_stream(
            backend, "foreground",
            scope=scheduler,
            # total admission cost this stream will dispatch: m output
            # rows per column of the per-shard extent
            cost_hint=batch_cost(m, -(-dat_size // k)),
            wide=dat_size >= WIDE_STREAM_BYTES,
            span=sp,
        )
        enc_backend = placement.backend
        dq = placement.queue
        stream = (
            dq.stream("foreground", label="ec encode", span=sp)
            if dq is not None
            else None
        )
        chip = getattr(enc_backend, "chip_label", "")

        def transform(data):
            # H2D stage + device encode dispatch, both async: device
            # residency bound is ~4 batches alive at once (one draining
            # in to_host, two queued, one being dispatched), so peak
            # device memory is ~4x batch_size of input (+ m/k of that
            # in outputs); callers raising batch_size must budget
            # accordingly. With the shared scheduler the chip-wide
            # bound is the queue's window instead.
            if stream is None:
                with trace.stage(sp, "h2d_dispatch", chip):
                    handle = enc_backend.encode_staged(
                        enc_backend.to_device(data)
                    )
                return data, None, handle
            ticket, handle = stream.dispatch(
                lambda: enc_backend.encode_staged(enc_backend.to_device(data)),
                batch_cost(m, data.shape[1]),
            )
            return data, ticket, handle

        def consume(item):
            data, ticket, parity_handle = item
            # Blocks until the device result is ready — while it does,
            # the main thread keeps dispatching H2D+encode for the
            # batches queued behind this one.
            try:
                with trace.stage(sp, "device_drain", chip):
                    parity = np.ascontiguousarray(
                        enc_backend.to_host(parity_handle), dtype=np.uint8
                    )
            finally:
                if ticket is not None:
                    stream.release(ticket)
            with trace.stage(sp, "write_sink"):
                sink.append_rows([*data, *parity])
            if pool is not None:
                # the batch's bytes are on disk (or in the sink's write
                # path) — its pooled matrix is free to carry batch N+2
                pool.put(data)

        try:
            run_pipeline(
                produce,
                transform,
                consume,
                # Join bound: up to ~4 batches can still be draining (one
                # in to_host, two queued, one dispatched); allow each
                # 16 MiB/s of slow-disk write plus a fixed device-fetch
                # allowance.
                join_timeout=60.0 + 4.0 * batch_size / (16 << 20),
                describe="ec encode pipeline",
                span=sp,
            )
        finally:
            if stream is not None:
                stream.close()
            placement.close()

        # Crash window: shards fully written but not yet durable — a
        # power cut here may leave any suffix of any shard missing.
        faults.fire("ec.encode.before_fsync", base=base)
        # Durability barrier. Flushes are issued in parallel: on a real
        # disk array the 14 shard files' dirty pages drain concurrently
        # instead of serializing 14 round-trips.
        from concurrent.futures import ThreadPoolExecutor as _TPE

        with trace.stage(sp, "fsync_publish"):
            for f in outputs:
                f.flush()
            with _TPE(max_workers=len(outputs)) as ex:
                list(ex.map(lambda f: os.fsync(f.fileno()), outputs))
    finally:
        os.close(dat_fd)
        for f in outputs:
            f.close()
        trace.finish(sp)
    from ..utils.fs import fsync_dir

    fsync_dir(base + ".dat")
    return sink.to_protection(ctx)


def ec_encode_volume(
    base: str,
    ctx: ECContext = DEFAULT_EC_CONTEXT,
    backend: RSBackend | None = None,
    batch_size: int = DEFAULT_BATCH,
    version: int = 3,
    leaf_size: int = BITROT_LEAF_SIZE,
    scheduler=None,
) -> VolumeInfo:
    """Full encode of one volume's files (the server-side work of
    VolumeEcShardsGenerate). Order matters: .ecx first (write-race
    close, volume_grpc_erasure_coding.go:107-116), then shards, then
    .ecsum + .vif."""
    if not os.path.exists(base + ".dat"):
        raise ECError(f"{base}.dat not found")
    if not os.path.exists(base + ".idx"):
        raise ECError(f"{base}.idx not found")

    encode_ts_ns = time.time_ns()
    # Root span for the whole volume encode: the pipeline (ec.encode)
    # nests under it along with index sort and sidecar publication.
    sp = trace.start(
        "ec.encode_volume", name=os.path.basename(base), base=base,
    )
    try:
        with trace.activate(sp):
            with trace.stage(sp, "index_sort"):
                write_sorted_file_from_idx(base)
            # Crash window the ecx-first ordering closes: .ecx exists,
            # no shards.
            faults.fire("ec.encode.after_ecx", base=base)
            prot = write_ec_files(
                base, ctx, backend, batch_size, leaf_size=leaf_size,
                scheduler=scheduler,
            )
            prot.generation = encode_ts_ns
            # Crash window: shards durable, sidecar absent — readers
            # must serve, scrub must refuse (no ground truth), rebuild
            # must still work.
            faults.fire("ec.encode.before_ecsum", base=base)
            with trace.stage(sp, "fsync_publish"):
                prot.save(base + ".ecsum")

                vi = VolumeInfo(
                    version=version,
                    ec_ctx=ctx,
                    dat_file_size=os.path.getsize(base + ".dat"),
                    encode_ts_ns=encode_ts_ns,
                )
                vi.save(base + ".vif")
            return vi
    finally:
        trace.finish(sp)
