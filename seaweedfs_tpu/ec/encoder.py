"""EC encode: volume (.dat + .idx) -> .ec00.. shards, .ecx, .ecsum, .vif.

Reference pipeline: weed/storage/erasure_coding/ec_encoder.go
(WriteEcFiles / encodeDatFile / encodeDataOneBatch) and the server RPC
VolumeEcShardsGenerate (volume_grpc_erasure_coding.go:45), which writes
the .ecx BEFORE the shards to close a write race, then persists .ecsum
and .vif.

TPU-first divergence: the reference feeds its SIMD encoder 256KB
buffers; a device wants batches in the tens of MB. Because parity is
columnwise-independent, any batch split of a stripe row produces
bit-identical shards, so the backend is fed `batch_size` columns at a
time (default 16 MiB per shard => 160 MiB device input at 10+4) and the
shard files/CRC builders are appended chunk by chunk in offset order.
"""

from __future__ import annotations

import os
import time

import numpy as np

from .. import faults
from ..storage.needle_map import MemDb
from .backend import RSBackend, get_backend
from .bitrot import BitrotProtection, ShardChecksumBuilder
from .context import (
    BITROT_BLOCK_SIZE,
    LARGE_BLOCK_SIZE,
    SMALL_BLOCK_SIZE,
    DEFAULT_EC_CONTEXT,
    ECContext,
    ECError,
)
from .volume_info import VolumeInfo

DEFAULT_BATCH = 16 * 1024 * 1024


def _pread_padded(fd: int, buf: np.ndarray, offset: int) -> None:
    """Fill `buf` from fd at `offset` IN PLACE (no intermediate bytes
    object), zero-padding past EOF."""
    mv = memoryview(buf)
    filled = 0
    want = len(buf)
    while filled < want:
        got = os.preadv(fd, [mv[filled:]], offset + filled)
        if got == 0:
            break
        filled += got
    if filled < want:
        buf[filled:] = 0


class _FusedShardSink:
    """Write stage backed by the native fused append+CRC
    (sn_shard_append): one GIL-releasing C++ call per batch, a worker
    thread per shard, CRC32C rolled while the bytes are cache-hot,
    write(2) straight from the source buffers — no tobytes()/slice
    copies. This is what closes the BENCH_r03 finding that 87% of e2e
    wall time was host-side overhead (reference equivalent: the single
    fused encode+CRC loop in weed/storage/erasure_coding/ec_encoder.go)."""

    def __init__(self, files: list, block_size: int = BITROT_BLOCK_SIZE):
        from ..utils import native

        self._native = native
        self.fds = [f.fileno() for f in files]
        n = len(files)
        self.block_size = block_size
        self.crc_state = np.zeros(n, np.uint32)
        self.filled = np.zeros(n, np.uint64)
        self.crcs: list[list[int]] = [[] for _ in range(n)]
        self.sizes = [0] * n
        self._out_counts = np.empty(n, np.int32)
        self._out_crcs: np.ndarray | None = None

    def append(self, data: np.ndarray, parity: np.ndarray) -> None:
        # Row-pointer math below requires C-contiguous uint8 (no-op when
        # already so, which the reader/backends guarantee).
        data = np.ascontiguousarray(data, dtype=np.uint8)
        parity = np.ascontiguousarray(parity, dtype=np.uint8)
        width = data.shape[1]
        if parity.shape[1] != width:
            raise ECError(
                f"parity width {parity.shape[1]} != data width {width}"
            )
        max_out = width // self.block_size + 2
        if self._out_crcs is None or self._out_crcs.shape[1] < max_out:
            self._out_crcs = np.empty((len(self.fds), max_out), np.uint32)
        rows = [data.ctypes.data + i * width for i in range(data.shape[0])]
        rows += [parity.ctypes.data + j * width for j in range(parity.shape[0])]
        self._native.shard_append(
            self.fds,
            rows,
            width,
            self.block_size,
            self.crc_state,
            self.filled,
            self._out_crcs,
            self._out_counts,
        )
        for i in range(len(self.fds)):
            c = int(self._out_counts[i])
            if c:
                self.crcs[i].extend(int(x) for x in self._out_crcs[i, :c])
            self.sizes[i] += width

    def finish(self, ctx: ECContext) -> BitrotProtection:
        import uuid as _uuid

        for i in range(len(self.fds)):
            if self.filled[i]:
                self.crcs[i].append(int(self.crc_state[i]))
                self.filled[i] = 0
                self.crc_state[i] = 0
        return BitrotProtection(
            ctx=ctx,
            block_size=self.block_size,
            uuid=_uuid.uuid4().bytes,
            shard_sizes=list(self.sizes),
            shard_crcs=[list(c) for c in self.crcs],
        )


class _PyShardSink:
    """Pure-Python fallback write stage (native .so unavailable)."""

    def __init__(self, files: list, block_size: int = BITROT_BLOCK_SIZE):
        self.files = files
        self.builders = [ShardChecksumBuilder(block_size) for _ in files]

    def append(self, data: np.ndarray, parity: np.ndarray) -> None:
        k = data.shape[0]
        for i, f in enumerate(self.files):
            b = (data[i] if i < k else parity[i - k]).tobytes()
            mv = memoryview(b)
            while mv:  # raw FileIO may short-write
                mv = mv[f.write(mv) :]
            self.builders[i].write(b)

    def finish(self, ctx: ECContext) -> BitrotProtection:
        return BitrotProtection.from_builders(ctx, self.builders)


def write_sorted_file_from_idx(base: str, ext: str = ".ecx") -> None:
    """Convert write-ordered .idx -> sorted sealed index (reference
    WriteSortedFileFromIdx, ec_encoder.go:32-59)."""
    db = MemDb()
    db.load_idx(base + ".idx")
    db.write_sorted_file(base + ext)


def write_ec_files(
    base: str,
    ctx: ECContext = DEFAULT_EC_CONTEXT,
    backend: RSBackend | None = None,
    batch_size: int = DEFAULT_BATCH,
    large_block_size: int = LARGE_BLOCK_SIZE,
    small_block_size: int = SMALL_BLOCK_SIZE,
) -> BitrotProtection:
    """Stripe+encode base.dat into base.ec00..; returns bitrot CRCs
    accumulated during the same pass."""
    if backend is None:
        backend = get_backend("auto", ctx.data_shards, ctx.parity_shards)
    k, total = ctx.data_shards, ctx.total

    dat_fd = os.open(base + ".dat", os.O_RDONLY)
    outputs: list = []
    try:
        for i in range(total):
            # buffering=0: the fused native sink writes via raw fds; the
            # Python fallback writes whole >=1MiB batches, where a
            # userspace buffer adds a copy and saves nothing.
            outputs.append(open(base + ctx.to_ext(i), "wb", buffering=0))
        try:
            sink: _FusedShardSink | _PyShardSink = _FusedShardSink(outputs)
        except Exception:
            sink = _PyShardSink(outputs)
        dat_size = os.fstat(dat_fd).st_size
        large_row = large_block_size * k
        small_row = small_block_size * k

        # Row/chunk schedule: the hot loop is disk-bound (SURVEY.md hard
        # part (b)), so reads, H2D staging, device encode, and shard
        # writes run as a 4-stage pipeline with bounded queues — the
        # device computes batch N while batch N+1 is read/transferred
        # and batch N-1 drains to host and disk.
        def chunk_plan():
            processed = 0
            remaining = dat_size
            while remaining >= large_row:
                yield processed, large_block_size
                processed += large_row
                remaining -= large_row
            while remaining > 0:
                yield processed, small_block_size
                processed += small_row
                remaining -= small_row

        import queue as _queue
        import threading as _threading

        read_q: "_queue.Queue" = _queue.Queue(maxsize=2)
        write_q: "_queue.Queue" = _queue.Queue(maxsize=2)
        abort = _threading.Event()
        errors: list[BaseException] = []

        def _put(q, item) -> bool:
            """Abort-aware put: never blocks forever on a full queue
            whose consumer has stopped."""
            while True:
                try:
                    q.put(item, timeout=0.2)
                    return True
                except _queue.Full:
                    if abort.is_set():
                        return False

        def reader():
            try:
                for row_offset, block_size in chunk_plan():
                    batch = min(batch_size, block_size)
                    for chunk_off in range(0, block_size, batch):
                        if abort.is_set():
                            return
                        width = min(batch, block_size - chunk_off)
                        data = np.empty((k, width), dtype=np.uint8)
                        for i in range(k):
                            _pread_padded(
                                dat_fd,
                                data[i],
                                row_offset + i * block_size + chunk_off,
                            )
                        if not _put(read_q, data):
                            return
            except BaseException as e:  # pragma: no cover - disk errors
                errors.append(e)
                abort.set()
            finally:
                _put(read_q, None)

        def writer():
            try:
                while True:
                    item = write_q.get()
                    if item is None:
                        return
                    data, parity_handle = item
                    # Blocks until the device result is ready — while it
                    # does, the main thread keeps dispatching H2D+encode
                    # for the batches queued behind this one.
                    parity = np.ascontiguousarray(
                        backend.to_host(parity_handle), dtype=np.uint8
                    )
                    sink.append(data, parity)
            except BaseException as e:  # pragma: no cover - disk errors
                errors.append(e)
                abort.set()
                while write_q.get() is not None:
                    pass

        rt = _threading.Thread(target=reader, daemon=True)
        wt = _threading.Thread(target=writer, daemon=True)
        rt.start()
        wt.start()
        try:
            # 4 overlapped stages: disk read (reader thread) / H2D stage /
            # device encode dispatch (both async, this thread) / D2H +
            # shard write (writer thread, blocks in to_host). Device
            # residency bound: up to 4 batches alive at once — one
            # draining in to_host, two queued in write_q, one being
            # dispatched here — so peak device memory is ~4x batch_size
            # of input (+ m/k of that in outputs); callers raising
            # batch_size must budget accordingly.
            while True:
                data = read_q.get()
                if data is None or abort.is_set():
                    break
                parity_handle = backend.encode_staged(backend.to_device(data))
                if not _put(write_q, (data, parity_handle)):
                    break
        except BaseException as e:
            errors.append(e)
        finally:
            # Shutdown discipline: JOIN both threads before any fd is
            # closed — a reader mid-pread on a closed (possibly reused)
            # fd would read someone else's file. On error, abort stops
            # the reader (its _put is abort-aware) and draining read_q
            # unblocks an in-flight put. The writer always drains
            # write_q until the None sentinel (its error path keeps
            # consuming), so a BLOCKING put(None) never deadlocks and
            # never drops queued batches on the happy path.
            if errors:
                abort.set()
                try:
                    while True:
                        read_q.get_nowait()
                except _queue.Empty:
                    pass
            write_q.put(None)
            # Join bound: up to ~4 batches can still be draining (one in
            # to_host, two queued, one dispatched); allow each 16 MiB/s
            # of slow-disk write plus a fixed device-fetch allowance.
            join_timeout = 60.0 + 4.0 * batch_size / (16 << 20)
            rt.join(timeout=join_timeout)
            wt.join(timeout=join_timeout)
            if rt.is_alive() or wt.is_alive():  # pragma: no cover
                # A stuck thread (e.g. the writer wedged in a device
                # to_host against a hung TPU relay) means the shard
                # files are TRUNCATED but the CRC builders are
                # self-consistent with the truncation — returning
                # success here would publish undetectable data loss.
                # Chain the root cause so it isn't masked.
                abort.set()
                raise ECError(
                    "ec encode pipeline thread did not finish "
                    f"(reader alive={rt.is_alive()}, writer alive="
                    f"{wt.is_alive()}); shards are incomplete"
                ) from (errors[0] if errors else None)
        if errors:
            raise errors[0]

        # Crash window: shards fully written but not yet durable — a
        # power cut here may leave any suffix of any shard missing.
        faults.fire("ec.encode.before_fsync", base=base)
        # Durability barrier. Flushes are issued in parallel: on a real
        # disk array the 14 shard files' dirty pages drain concurrently
        # instead of serializing 14 round-trips.
        from concurrent.futures import ThreadPoolExecutor as _TPE

        for f in outputs:
            f.flush()
        with _TPE(max_workers=len(outputs)) as ex:
            list(ex.map(lambda f: os.fsync(f.fileno()), outputs))
    finally:
        os.close(dat_fd)
        for f in outputs:
            f.close()
    from ..utils.fs import fsync_dir

    fsync_dir(base + ".dat")
    return sink.finish(ctx)


def ec_encode_volume(
    base: str,
    ctx: ECContext = DEFAULT_EC_CONTEXT,
    backend: RSBackend | None = None,
    batch_size: int = DEFAULT_BATCH,
    version: int = 3,
) -> VolumeInfo:
    """Full encode of one volume's files (the server-side work of
    VolumeEcShardsGenerate). Order matters: .ecx first (write-race
    close, volume_grpc_erasure_coding.go:107-116), then shards, then
    .ecsum + .vif."""
    if not os.path.exists(base + ".dat"):
        raise ECError(f"{base}.dat not found")
    if not os.path.exists(base + ".idx"):
        raise ECError(f"{base}.idx not found")

    encode_ts_ns = time.time_ns()
    write_sorted_file_from_idx(base)
    # Crash window the ecx-first ordering closes: .ecx exists, no shards.
    faults.fire("ec.encode.after_ecx", base=base)
    prot = write_ec_files(base, ctx, backend, batch_size)
    prot.generation = encode_ts_ns
    # Crash window: shards durable, sidecar absent — readers must serve,
    # scrub must refuse (no ground truth), rebuild must still work.
    faults.fire("ec.encode.before_ecsum", base=base)
    prot.save(base + ".ecsum")

    vi = VolumeInfo(
        version=version,
        ec_ctx=ctx,
        dat_file_size=os.path.getsize(base + ".dat"),
        encode_ts_ns=encode_ts_ns,
    )
    vi.save(base + ".vif")
    return vi
