"""EC encode: volume (.dat + .idx) -> .ec00.. shards, .ecx, .ecsum, .vif.

Reference pipeline: weed/storage/erasure_coding/ec_encoder.go
(WriteEcFiles / encodeDatFile / encodeDataOneBatch) and the server RPC
VolumeEcShardsGenerate (volume_grpc_erasure_coding.go:45), which writes
the .ecx BEFORE the shards to close a write race, then persists .ecsum
and .vif.

TPU-first divergence: the reference feeds its SIMD encoder 256KB
buffers; a device wants batches in the tens of MB. Because parity is
columnwise-independent, any batch split of a stripe row produces
bit-identical shards, so the backend is fed `batch_size` columns at a
time (default 16 MiB per shard => 160 MiB device input at 10+4) and the
shard files/CRC builders are appended chunk by chunk in offset order.
"""

from __future__ import annotations

import os
import time

import numpy as np

from ..storage.needle_map import MemDb
from .backend import RSBackend, get_backend
from .bitrot import BitrotProtection, ShardChecksumBuilder
from .context import (
    LARGE_BLOCK_SIZE,
    SMALL_BLOCK_SIZE,
    DEFAULT_EC_CONTEXT,
    ECContext,
    ECError,
)
from .volume_info import VolumeInfo

DEFAULT_BATCH = 16 * 1024 * 1024


def _pread_padded(fd: int, buf: np.ndarray, offset: int) -> None:
    """Fill `buf` from fd at `offset`, zero-padding past EOF."""
    got = os.pread(fd, len(buf), offset)
    n = len(got)
    buf[:n] = np.frombuffer(got, dtype=np.uint8)
    if n < len(buf):
        buf[n:] = 0


def write_sorted_file_from_idx(base: str, ext: str = ".ecx") -> None:
    """Convert write-ordered .idx -> sorted sealed index (reference
    WriteSortedFileFromIdx, ec_encoder.go:32-59)."""
    db = MemDb()
    db.load_idx(base + ".idx")
    db.write_sorted_file(base + ext)


def write_ec_files(
    base: str,
    ctx: ECContext = DEFAULT_EC_CONTEXT,
    backend: RSBackend | None = None,
    batch_size: int = DEFAULT_BATCH,
    large_block_size: int = LARGE_BLOCK_SIZE,
    small_block_size: int = SMALL_BLOCK_SIZE,
) -> BitrotProtection:
    """Stripe+encode base.dat into base.ec00..; returns bitrot CRCs
    accumulated during the same pass."""
    if backend is None:
        backend = get_backend("auto", ctx.data_shards, ctx.parity_shards)
    k, total = ctx.data_shards, ctx.total

    dat_fd = os.open(base + ".dat", os.O_RDONLY)
    builders = [ShardChecksumBuilder() for _ in range(total)]
    outputs: list = []
    try:
        for i in range(total):
            outputs.append(open(base + ctx.to_ext(i), "wb"))
        dat_size = os.fstat(dat_fd).st_size
        large_row = large_block_size * k
        small_row = small_block_size * k

        # Row/chunk schedule: the hot loop is disk-bound (SURVEY.md hard
        # part (b)), so reads, H2D staging, device encode, and shard
        # writes run as a 4-stage pipeline with bounded queues — the
        # device computes batch N while batch N+1 is read/transferred
        # and batch N-1 drains to host and disk.
        def chunk_plan():
            processed = 0
            remaining = dat_size
            while remaining >= large_row:
                yield processed, large_block_size
                processed += large_row
                remaining -= large_row
            while remaining > 0:
                yield processed, small_block_size
                processed += small_row
                remaining -= small_row

        import queue as _queue
        import threading as _threading

        read_q: "_queue.Queue" = _queue.Queue(maxsize=2)
        write_q: "_queue.Queue" = _queue.Queue(maxsize=2)
        abort = _threading.Event()
        errors: list[BaseException] = []

        def _put(q, item) -> bool:
            """Abort-aware put: never blocks forever on a full queue
            whose consumer has stopped."""
            while True:
                try:
                    q.put(item, timeout=0.2)
                    return True
                except _queue.Full:
                    if abort.is_set():
                        return False

        def reader():
            try:
                for row_offset, block_size in chunk_plan():
                    batch = min(batch_size, block_size)
                    for chunk_off in range(0, block_size, batch):
                        if abort.is_set():
                            return
                        width = min(batch, block_size - chunk_off)
                        data = np.empty((k, width), dtype=np.uint8)
                        for i in range(k):
                            _pread_padded(
                                dat_fd,
                                data[i],
                                row_offset + i * block_size + chunk_off,
                            )
                        if not _put(read_q, data):
                            return
            except BaseException as e:  # pragma: no cover - disk errors
                errors.append(e)
                abort.set()
            finally:
                _put(read_q, None)

        def writer():
            try:
                while True:
                    item = write_q.get()
                    if item is None:
                        return
                    data, parity_handle = item
                    # Blocks until the device result is ready — while it
                    # does, the main thread keeps dispatching H2D+encode
                    # for the batches queued behind this one.
                    parity = backend.to_host(parity_handle)
                    for i in range(total):
                        b = (data[i] if i < k else parity[i - k]).tobytes()
                        outputs[i].write(b)
                        builders[i].write(b)
            except BaseException as e:  # pragma: no cover - disk errors
                errors.append(e)
                abort.set()
                while write_q.get() is not None:
                    pass

        rt = _threading.Thread(target=reader, daemon=True)
        wt = _threading.Thread(target=writer, daemon=True)
        rt.start()
        wt.start()
        try:
            # 4 overlapped stages: disk read (reader thread) / H2D stage /
            # device encode dispatch (both async, this thread) / D2H +
            # shard write (writer thread, blocks in to_host). Device
            # residency bound: up to 4 batches alive at once — one
            # draining in to_host, two queued in write_q, one being
            # dispatched here — so peak device memory is ~4x batch_size
            # of input (+ m/k of that in outputs); callers raising
            # batch_size must budget accordingly.
            while True:
                data = read_q.get()
                if data is None or abort.is_set():
                    break
                parity_handle = backend.encode_staged(backend.to_device(data))
                if not _put(write_q, (data, parity_handle)):
                    break
        except BaseException as e:
            errors.append(e)
        finally:
            # Shutdown discipline: JOIN both threads before any fd is
            # closed — a reader mid-pread on a closed (possibly reused)
            # fd would read someone else's file. On error, abort stops
            # the reader (its _put is abort-aware) and draining read_q
            # unblocks an in-flight put. The writer always drains
            # write_q until the None sentinel (its error path keeps
            # consuming), so a BLOCKING put(None) never deadlocks and
            # never drops queued batches on the happy path.
            if errors:
                abort.set()
                try:
                    while True:
                        read_q.get_nowait()
                except _queue.Empty:
                    pass
            write_q.put(None)
            rt.join(timeout=60)
            wt.join(timeout=60)
            if rt.is_alive() or wt.is_alive():  # pragma: no cover
                # A stuck thread (e.g. the writer wedged in a device
                # to_host against a hung TPU relay) means the shard
                # files are TRUNCATED but the CRC builders are
                # self-consistent with the truncation — returning
                # success here would publish undetectable data loss.
                abort.set()
                raise ECError(
                    "ec encode pipeline thread did not finish "
                    f"(reader alive={rt.is_alive()}, writer alive="
                    f"{wt.is_alive()}); shards are incomplete"
                )
        if errors:
            raise errors[0]

        for f in outputs:
            f.flush()
            os.fsync(f.fileno())
    finally:
        os.close(dat_fd)
        for f in outputs:
            f.close()
    from ..utils.fs import fsync_dir

    fsync_dir(base + ".dat")
    return BitrotProtection.from_builders(ctx, builders)


def ec_encode_volume(
    base: str,
    ctx: ECContext = DEFAULT_EC_CONTEXT,
    backend: RSBackend | None = None,
    batch_size: int = DEFAULT_BATCH,
    version: int = 3,
) -> VolumeInfo:
    """Full encode of one volume's files (the server-side work of
    VolumeEcShardsGenerate). Order matters: .ecx first (write-race
    close, volume_grpc_erasure_coding.go:107-116), then shards, then
    .ecsum + .vif."""
    if not os.path.exists(base + ".dat"):
        raise ECError(f"{base}.dat not found")
    if not os.path.exists(base + ".idx"):
        raise ECError(f"{base}.idx not found")

    encode_ts_ns = time.time_ns()
    write_sorted_file_from_idx(base)
    prot = write_ec_files(base, ctx, backend, batch_size)
    prot.generation = encode_ts_ns
    prot.save(base + ".ecsum")

    vi = VolumeInfo(
        version=version,
        ec_ctx=ctx,
        dat_file_size=os.path.getsize(base + ".dat"),
        encode_ts_ns=encode_ts_ns,
    )
    vi.save(base + ".vif")
    return vi
