"""Volume info sidecar: <base>.vif.

Carries what the reference's protobuf VolumeInfo carries (reference
weed/storage/volume_info/volume_info.go, written by
volume_grpc_erasure_coding.go:62-79): needle version, the EC shard
config (for custom ratios), the .dat size at encode time (authoritative
for the striping layout), and the EncodeTsNs generation stamp. Stored as
JSON — human-debuggable, schema-stable.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Optional

from .context import ECContext


@dataclass
class VolumeInfo:
    version: int = 3
    ec_ctx: Optional[ECContext] = None
    dat_file_size: int = 0
    encode_ts_ns: int = 0
    # cold-tier placement (reference VolumeInfo.files tier info,
    # volume_tier.go): where the .dat lives when not on local disk
    tier_url: str = ""
    tier_size: int = 0

    def to_json(self) -> str:
        d: dict = {"version": self.version}
        if self.ec_ctx is not None:
            d["ecShardConfig"] = {
                "dataShards": self.ec_ctx.data_shards,
                "parityShards": self.ec_ctx.parity_shards,
            }
        if self.dat_file_size:
            d["datFileSize"] = self.dat_file_size
        if self.encode_ts_ns:
            d["encodeTsNs"] = self.encode_ts_ns
        if self.tier_url:
            d["tierUrl"] = self.tier_url
            d["tierSize"] = self.tier_size
        return json.dumps(d, indent=2, sort_keys=True)

    @classmethod
    def from_json(cls, text: str) -> "VolumeInfo":
        d = json.loads(text)
        ec = d.get("ecShardConfig")
        return cls(
            version=int(d.get("version", 3)),
            ec_ctx=ECContext(int(ec["dataShards"]), int(ec["parityShards"]))
            if ec
            else None,
            dat_file_size=int(d.get("datFileSize", 0)),
            encode_ts_ns=int(d.get("encodeTsNs", 0)),
            tier_url=d.get("tierUrl", ""),
            tier_size=int(d.get("tierSize", 0)),
        )

    def save(self, path: str) -> None:
        from ..utils.fs import atomic_write

        atomic_write(path, self.to_json().encode())

    @classmethod
    def load(cls, path: str) -> "VolumeInfo":
        with open(path) as f:
            return cls.from_json(f.read())

    @classmethod
    def maybe_load(cls, path: str) -> Optional["VolumeInfo"]:
        if not os.path.exists(path):
            return None
        return cls.load(path)
