"""Zero-copy native read plane for the EC byte path (ISSUE 10).

The Python layer ORCHESTRATES buffers here instead of copying them:
batches land via one GIL-releasing `sn_batch_pread` call per batch into
caller-owned aligned numpy matrices that flow produce -> transform ->
consume untouched (numpy views over one allocation — no `bytes`
objects, no per-batch malloc/page-fault churn), then return to a small
pool. The write half is the stateful native sink (utils/native.py
NativeSink, used by pipeline.FusedShardSink). The NETWORK half lives
in ec/net_plane.py (ISSUE 12): the same BufferPool class backs the
peer-fetch ingress landings and the fastread client, and `enabled()`
below is the single gate every plane (local, wire, HTTP egress)
checks.

Buffer-ownership rules (README "Native data plane" has the long form):

- A pooled matrix belongs to exactly one in-flight batch from the
  moment `BufferPool.get` returns it until its release callback runs in
  the consume stage. The pipeline's bounded queues cap in-flight
  batches, and the pool is sized to that cap, so `get` never blocks on
  the happy path.
- Rows handed to the native sink must stay alive until the append call
  returns (the C side pwrite(2)s straight from them; it stores no
  pointers).
- Pool matrices are 4096-aligned so the same buffers satisfy O_DIRECT
  alignment when a caller opens shard fds with it (offsets and widths
  must then also be 512/4096-multiples; the ragged tail batch is not,
  which is why O_DIRECT stays an opt-in for aligned workloads).

Fallback semantics: `enabled()` is False when the native core failed to
import (no C++ toolchain — utils/native.py raises ImportError by
contract) or when SEAWEED_EC_NATIVE=0 forces the pure-Python plane;
callers must keep their Python source/sink paths as the bit-identical
fallback. An ARMED fault registry also routes callers to the Python
plane: byte-mutating fault points need materialized bytes at the
read/write seams (see ec/rebuild.py).
"""

from __future__ import annotations

import os
from typing import Sequence

import numpy as np

_ALIGN = 4096


def _native_mod():
    try:
        from ..utils import native

        return native
    except ImportError:
        return None


def enabled() -> bool:
    """True when the native data plane should carry reads/writes:
    the .so loaded and SEAWEED_EC_NATIVE != 0 (checked live so tests
    and benches can flip the env per call)."""
    if os.environ.get("SEAWEED_EC_NATIVE", "1") == "0":
        return False
    return _native_mod() is not None


def aligned_matrix(rows: int, width: int, align: int = _ALIGN) -> np.ndarray:
    """(rows, width) C-contiguous uint8 matrix whose base address is
    `align`-aligned (over-allocate + offset; plain numpy, no custom
    allocator to keep GC ownership trivial)."""
    raw = np.empty(rows * width + align, dtype=np.uint8)
    off = (-raw.ctypes.data) % align
    return raw[off : off + rows * width].reshape(rows, width)


_landing_pool_singleton = None
_landing_pool_lock = None


def landing_pool() -> "BufferPool":
    """Process-wide width-keyed pool of 1-row aligned landing buffers,
    shared by every single-stream ingress (peer-fetch net-plane
    landings, the fastread client) so steady state allocates once per
    width and reuses forever."""
    global _landing_pool_singleton, _landing_pool_lock
    if _landing_pool_lock is None:
        import threading as _t

        _landing_pool_lock = _t.Lock()
    with _landing_pool_lock:
        if _landing_pool_singleton is None:
            _landing_pool_singleton = BufferPool(rows=1)
        return _landing_pool_singleton


class BufferPool:
    """Reusable aligned (rows, width) matrices cycling through the
    pipeline, free-listed by exact width (the encode plan yields at
    most a few width classes: full batches, the small-block phase, and
    ragged tails). Allocation happens on demand; the population is
    naturally bounded by the pipeline's in-flight batch cap
    (~2*queue_size + one per stage), so steady state is allocate-once,
    reuse-forever — no per-batch malloc or page-fault churn. Release is
    cooperative: the consume stage calls `put` when the batch's bytes
    have been written; a batch dropped by an aborting pipeline simply
    strands its matrix for the GC (the pool holds no global list)."""

    def __init__(self, rows: int):
        import threading as _t

        self.rows = rows
        self._free: dict[int, list[np.ndarray]] = {}
        self._lock = _t.Lock()

    def get(self, width: int) -> np.ndarray:
        with self._lock:
            lst = self._free.get(width)
            if lst:
                return lst.pop()
        return aligned_matrix(self.rows, width)

    def put(self, buf: np.ndarray) -> None:
        with self._lock:
            self._free.setdefault(buf.shape[1], []).append(buf)


def read_batch(
    fds: Sequence[int],
    offsets: Sequence[int],
    dst: np.ndarray,
    *,
    width: int | None = None,
    pad_eof: bool = True,
    granule: int = 0,
    crc_state: np.ndarray | None = None,
    filled_state: np.ndarray | None = None,
    out_crcs: np.ndarray | None = None,
    out_counts: np.ndarray | None = None,
) -> None:
    """One native batched positioned read into `dst` rows (see
    utils/native.batch_pread for the contract). Caller must have
    checked `enabled()`."""
    native = _native_mod()
    native.batch_pread(
        list(fds),
        list(offsets),
        dst,
        width=width,
        pad_eof=pad_eof,
        granule=granule,
        crc_state=crc_state,
        filled_state=filled_state,
        out_crcs=out_crcs,
        out_counts=out_counts,
    )


def read_exact_into(fd: int, buf: np.ndarray, offset: int) -> None:
    """Fill 1-D `buf` from fd at offset; short read raises. Native
    single-row read when available, preadv loop otherwise — same
    in-place no-bytes contract either way."""
    if enabled():
        read_batch([fd], [offset], buf.reshape(1, -1), pad_eof=False)
        return
    mv = memoryview(buf)
    filled = 0
    want = len(buf)
    while filled < want:
        got = os.preadv(fd, [mv[filled:]], offset + filled)
        if got == 0:
            raise OSError(f"short read at offset {offset + filled}")
        filled += got


def prefetch(fd: int, offset: int, length: int) -> None:
    """Best-effort readahead for the NEXT batch window: issued before
    reading the current batch so the kernel pages in batch N+1 while
    batch N computes and N-1 drains."""
    native = _native_mod()
    if native is not None and length > 0:
        native.fadvise_willneed(fd, offset, length)
