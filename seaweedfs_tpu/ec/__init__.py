"""Erasure-coding pipeline: the framework's north-star component.

Encode/rebuild/decode/read with pluggable CPU (C++ SIMD) and TPU
(JAX/Pallas bit-matmul) Reed-Solomon backends, bit-identical outputs.
"""

from .backend import CpuBackend, FallbackBackend, JaxBackend, get_backend
from .bitrot import (
    BitrotError,
    BitrotProtection,
    ShardChecksumBuilder,
    fold_leaf_crcs,
)
from .context import (
    BITROT_BLOCK_SIZE,
    BITROT_LEAF_SIZE,
    DATA_SHARDS,
    DEFAULT_EC_CONTEXT,
    LARGE_BLOCK_SIZE,
    MAX_SHARD_COUNT,
    PARITY_SHARDS,
    SMALL_BLOCK_SIZE,
    TOTAL_SHARDS,
    ECContext,
    ECError,
)
from .chip_pool import (
    ChipBackend,
    ChipPool,
    Placement,
    place_stream,
    pool_for,
)
from .device_queue import (
    DeviceQueue,
    DeviceStream,
    QueueScope,
    batch_cost,
    configure as configure_device_queue,
    default_scope as default_device_queue_scope,
    for_backend as device_queue_for_backend,
)
from .decoder import (
    ec_decode_volume,
    find_dat_file_size,
    has_live_needles,
    rebuild_ecx_file,
    write_dat_file,
    write_idx_from_ecx,
)
from .ec_volume import EcCookieMismatch, EcNotFoundError, EcVolume
from .encoder import ec_encode_volume, write_ec_files, write_sorted_file_from_idx
from .locate import Interval, locate_data
from .pipeline import FusedShardSink, PyShardSink, make_shard_sink, run_pipeline
from .stream_encode import (
    EcStreamEncoder,
    StreamJournal,
    load_stream_journal,
    recover_stream,
    stream_summary,
)
from .peer_rebuild import (
    PeerCorruptError,
    PeerFetchTransient,
    PeerRebuildReport,
    rebuild_from_peers,
)
from .rebuild import rebuild_ec_files
from .repair_journal import (
    JOURNAL_SUFFIX,
    JournalError,
    LeafPatch,
    RepairJournal,
    apply_leaf_repair,
    leaf_verdict,
    reconstruct_leaves,
    recover_volume_journals,
    sweep_stale_journals,
)
from .scrub import (
    QUARANTINE_SUFFIX,
    RateLimiter,
    ScrubCursor,
    ScrubDaemon,
    ScrubReport,
    scrub_ec_volume,
)
from .volume_info import VolumeInfo
