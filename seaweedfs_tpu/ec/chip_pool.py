"""Pod-level stream placement: route EC streams to chips, not slices.

PR 4's scheduler treats one backend instance as one chip, so on the
column-mesh backend EVERY stream is sliced across all local devices and
the whole pod serializes behind a single admission queue. The reference
gets its throughput from many independent volume workers
(weed/storage/erasure_coding), not one wide one; the TPU-native
analogue is stream-level data parallelism — when concurrent EC streams
outnumber chips, place WHOLE streams on single chips and reserve
column-mesh slicing for the lone-wide-stream case. Outputs are
bit-identical either way: the mesh path is bit-exact vs the
single-device path by construction (parity is columnwise-independent),
so placement is purely a scheduling decision.

Pieces
------

- :class:`ChipBackend` — a single-device JaxBackend pinned to one local
  device (`jax.device_put(…, device)`; jit follows the committed input,
  so every staged dispatch runs on that chip).
- :class:`ChipPool` — one per mesh-capable backend, built lazily from
  the mesh's own device list (never calls `jax.devices()` itself — a
  mesh backend existing proves device init already succeeded, the
  dead-relay hang rule from `get_backend`). Each chip's backend is
  constructed on first use; when the pooled backend is a
  FallbackBackend, every chip gets its OWN FallbackBackend + breaker,
  so one chip dying fails over only ITS streams to CPU while siblings
  keep their chips (the shared CpuBackend is stateless).
- :func:`place_stream` — the policy: route each new DeviceStream to the
  chip with the least outstanding placed cost (deterministic: ties go
  to the lowest chip index), falling back to the column-mesh backend
  only when the stream is explicitly wide AND no other stream is placed
  (mode "auto"), or always ("mesh"), or never ("chip") — the
  `ec_placement` knob, per QueueScope.

The wide/mesh path a stream keeps here is the POD-SHARDED encode since
the data-gravity PR: `parallel.MeshRS` lowers the XLA impl through one
explicit `NamedSharding`/pjit computation over the full device mesh
with the stripe (column) axis constrained (`SEAWEED_EC_POD_PJIT`),
which on multi-process TPU pods spans every process's devices — the
per-process shard_map wrapper remains for the Pallas impls. Placement
span events record which lowering the mesh decision landed on
(`pod_sharded`).

The pool itself is process-wide (chips are physical; two tenant scopes
sharing a host should see each other's load), while each scope gets its
own per-chip DeviceQueues (config isolation, `device_queue.QueueScope`).

The residency nuance recorded here since PR 5 — a chip serving a wide
MESH stream beside chip-placed streams could transiently hold two
windows of in-flight batches — is closed by the process-wide
ResidencyLedger (ec/device_queue.py): every queue charges the physical
chip(s) in a second admission phase, and a mesh-wide batch charges a
slot on EVERY chip it spans, so the per-chip budget holds across
queues and scopes. Routing reads the ledger too: `_live_loads_for`
adds each chip's CROSS-SCOPE in-flight cost on top of the scope's own
queue view, so another tenant's load repels placement (the PR 14
carried item).
"""

from __future__ import annotations

import threading
import weakref

import numpy as np

from .device_queue import QueueScope, resolve_scope
from .backend import CpuBackend, FallbackBackend, JaxBackend
from ..utils import metrics as _M
from ..utils.retry import CircuitBreaker

# An open breaker means this chip's streams are failing over to CPU:
# routing treats it as carrying this much extra outstanding cost, so a
# healthy sibling wins any remotely close call while a dead pod (all
# breakers open) still degrades gracefully instead of refusing.
BREAKER_OPEN_PENALTY = 1 << 40

_placement_decisions = _M.REGISTRY.counter(
    "sw_ec_placement_decisions_total",
    "EC stream placement decisions by the load signal that drove them "
    "(live = per-chip DeviceQueue.load() moved the pick, ledger = "
    "static stream cost hints alone, mesh = column-sliced)",
    ("signal",),
)


class ChipBackend(JaxBackend):
    """Single-device JaxBackend pinned to one local device.

    Staged H2D goes through `jax.device_put(data, device)`; computation
    follows the committed input, so encode_staged/apply_staged run on
    exactly this chip. The synchronous surface (encode/apply without
    staging) is only used by CPU fallback replays and inherits the
    default-device behavior — streams always take the staged path.

    Construction bypasses JaxBackend.__init__: the chips of one pool
    SHARE one RSJax codec (`rs` — jit dispatch follows the committed
    input's device, and the coeff/bit-matrix caches are lock-protected
    since PR 4), so an 8-chip pool does not pay 8 identical bit-matrix
    constructions, and no jax device probing happens here at all (the
    dead-relay hang rule)."""

    def __init__(self, ctx, device, rs=None, impl: str = "xla",
                 interpret: bool = False):
        from .backend import _BackendBase

        _BackendBase.__init__(self, ctx)
        if rs is None:
            from ..ops.rs_jax import RSJax

            rs = RSJax(
                ctx.data_shards, ctx.parity_shards,
                impl=impl, interpret=interpret,
            )
        self._rs = rs
        self._mesh_rs = None  # this backend IS one chip
        self.device = device
        self.chip_label = f"{device.platform}:{device.id}"

    def to_device(self, data: np.ndarray):
        import jax

        return jax.device_put(
            np.ascontiguousarray(data, dtype=np.uint8), self.device
        )


class _PodLedger:
    """Shared load/stream accounting for one PHYSICAL pod.

    Pools are per backend instance (their chip backends are ctx- and
    wrapper-specific), but the chips are physical: two backends over
    the same devices (e.g. 10+4 and 5+2 volumes — get_backend caches
    them separately) must see each OTHER's placed streams, or both
    would route their heavy streams to "idle" chip 0 while the rest of
    the pod sits empty. `pool_for` shares one ledger per device set."""

    def __init__(self, n: int):
        self.lock = threading.Lock()
        self.load: list[int] = [0] * n
        self.streams: list[int] = [0] * n


class ChipPool:
    """Per-chip backends + least-loaded stream routing for one pod.

    `devices` is any sequence of placement targets and `make_chip(dev)`
    builds the backend for one of them — the routing/load core is
    plain Python (bench --self-check exercises it without jax).

    Load accounting is per placed STREAM: `acquire(cost_hint)` charges
    the stream's estimated total cost (rows x bytes it will dispatch)
    to the chosen chip until the returned release fires. Routing is
    deterministic given the arrival order: least outstanding cost,
    ties to the lowest chip index. The accounting lives in a
    `_PodLedger` that `pool_for` SHARES between pools over the same
    physical devices."""

    def __init__(self, devices, make_chip, labels=None, ledger=None):
        self.devices = list(devices)
        self._make_chip = make_chip
        self.labels = (
            list(labels)
            if labels is not None
            else [str(d) for d in self.devices]
        )
        self._ledger = ledger if ledger is not None else _PodLedger(
            len(self.devices)
        )
        self._lock = self._ledger.lock
        self._chips: list = [None] * len(self.devices)

    @property
    def n_chips(self) -> int:
        return len(self.devices)

    def chip_backend(self, i: int):
        """The backend for chip `i`, constructed lazily OUTSIDE the
        pod lock (RSJax construction is host-side numpy work, but it
        must never serialize concurrent placements or stream-close
        releases). Two racers may both build; the insert keeps one."""
        with self._lock:
            be = self._chips[i]
        if be is None:
            built = self._make_chip(self.devices[i])
            with self._lock:
                be = self._chips[i]
                if be is None:
                    be = self._chips[i] = built
        return be

    def loads(self) -> list[int]:
        with self._lock:
            return list(self._ledger.load)

    def idle(self) -> bool:
        """True when no stream is placed on any chip of the POD (any
        pool sharing this ledger counts)."""
        with self._lock:
            return not any(self._ledger.streams)

    def _release_fn(self, indices, hint):
        done = [False]
        led = self._ledger

        def release() -> None:
            with led.lock:
                if done[0]:
                    return
                done[0] = True
                for j in indices:
                    led.load[j] -= hint
                    led.streams[j] -= 1

        return release

    def acquire(
        self,
        cost_hint: int = 0,
        prefer_mesh: bool = False,
        force_mesh: bool = False,
        live_loads: "list[int] | None" = None,
    ):
        """Place one stream: returns (chip_index, backend, release).
        `release()` is idempotent and must fire when the stream closes
        (success or death) so the chip's load drains.

        `live_loads` (per chip index, same order as `devices`) is the
        LIVE routing signal: each chip's DeviceQueue cost units
        queued+in-flight right now (plus breaker penalties), ADDED to
        the ledger's static placed-cost charges when ranking chips —
        the ROADMAP "routing reads live load" loop. The sum is
        deliberately conservative: a chip busy with work the ledger
        never saw (one-shot gateway admissions, another scope's
        dispatches) now repels new streams, while a placed stream
        keeps its ledger charge until it closes, so its own in-flight
        batches count twice while it is actively dispatching — routing
        prefers a chip that is merely RESERVED over one that is
        reserved AND busy, which is the right bias even though it
        overstates absolute load.

        `prefer_mesh` takes the whole-pod mesh IFF the pod is idle,
        decided under the SAME lock as the charge (no
        check-then-acquire window for a racing placement to slip
        through): chip_index and backend come back None and EVERY chip
        is charged the hint — a column-sliced stream occupies the whole
        pod, so pool.idle() reads False and a second stream (wide or
        not) routes to a chip instead of stacking behind the mesh
        queue. `force_mesh` charges the whole pod unconditionally (a
        pinned `ec_placement=mesh` stream runs column-sliced regardless
        of load, but must still be VISIBLE to every other scope's
        routing and idle checks)."""
        hint = max(int(cost_hint), 1)
        led = self._ledger
        live = live_loads if live_loads is not None else [0] * len(
            self.devices
        )
        with self._lock:
            if force_mesh or (prefer_mesh and not any(led.streams)):
                indices = range(len(led.load))
                i = None
            else:
                i = min(
                    range(len(led.load)),
                    key=lambda j: (led.load[j] + live[j], j),
                )
                indices = (i,)
            for j in indices:
                led.load[j] += hint
                led.streams[j] += 1
            release = self._release_fn(indices, hint)
        if i is None:
            return None, None, release
        try:
            be = self.chip_backend(i)
        except BaseException:
            # The charge landed before lazy construction; a failed
            # build must not leave phantom load on the pod ledger.
            release()
            raise
        return i, be, release


# --------------------------------------------------------------------------
# Pool registry: one pool per mesh-capable backend instance (its chips
# are ctx-specific), with the load LEDGER shared per physical device
# set — pools over the same chips route against one load state.
# --------------------------------------------------------------------------

_pools_lock = threading.Lock()
_pools: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
# device-identity -> _PodLedger; device sets are process-stable, so a
# plain dict (bounded by distinct pod topologies, in practice 1) is fine
_ledgers: dict = {}


def pool_for(backend) -> ChipPool | None:
    """The chip pool behind `backend`, or None when it is not a
    multi-device (column-mesh) backend. Safe on dead relays: devices
    come from the backend's OWN mesh, never a fresh jax.devices()."""
    if backend is None:
        return None
    primary = getattr(backend, "primary", backend)
    mesh_rs = getattr(primary, "_mesh_rs", None)
    if mesh_rs is None or mesh_rs.n_devices < 2:
        return None
    with _pools_lock:
        pool = _pools.get(backend)
        if pool is None:
            devices = list(np.ravel(mesh_rs.mesh.devices))
            ctx = backend.ctx
            rs = primary._rs
            wrap = isinstance(backend, FallbackBackend)
            cpu = CpuBackend(ctx) if wrap else None
            # Plain values only: capturing `backend` itself would pin
            # the WeakKeyDictionary key via its own pool value, leaking
            # every mesh backend (+ chips/queues) for process lifetime.
            brk_threshold = backend.breaker.failure_threshold if wrap else 0
            brk_timeout = backend.breaker.reset_timeout if wrap else 0.0

            def make_chip(dev):
                chip = ChipBackend(ctx, dev, rs=rs)
                if not wrap:
                    return chip
                # Per-chip breaker: one chip's repeated deaths demote
                # only ITS streams to CPU; siblings keep their chips.
                # A fresh instance per chip, but with the POOLED
                # backend's thresholds — an embedder's tolerance config
                # must survive the reroute onto chips.
                # (FallbackBackend copies chip_label from its primary.)
                return FallbackBackend(chip, cpu, breaker=CircuitBreaker(
                    failure_threshold=brk_threshold,
                    reset_timeout=brk_timeout,
                ))

            labels = [f"{d.platform}:{d.id}" for d in devices]
            # one load ledger per PHYSICAL device set: a second backend
            # over the same chips (another shard ratio) routes against
            # the same load state instead of a blind private copy
            led_key = tuple(labels)
            ledger = _ledgers.get(led_key)
            if ledger is None:
                ledger = _ledgers[led_key] = _PodLedger(len(devices))
            pool = ChipPool(devices, make_chip, labels=labels, ledger=ledger)
            _pools[backend] = pool
    return pool


class Placement:
    """One stream's resolved (backend, queue) pair. `chip` is the chip
    index (None = the original backend: mesh slicing, or no pool).
    close() releases the chip-load charge; idempotent."""

    __slots__ = ("backend", "queue", "chip", "_release")

    def __init__(self, backend, queue, chip=None, release=None):
        self.backend = backend
        self.queue = queue
        self.chip = chip
        self._release = release

    def close(self) -> None:
        if self._release is not None:
            rel, self._release = self._release, None
            rel()

    def __enter__(self) -> "Placement":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def chip_load_hint(scope: QueueScope | None = None) -> dict[str, dict]:
    """Read-only per-chip load/breaker hint: {chip_label: {"load":
    outstanding cost units queued+in-flight, "breaker":
    ""|"closed"|"open"|...}}.

    This is the LIVE routing signal: `place_stream` ranks chips by
    ledger charge PLUS this load (and ships it to the master via
    heartbeats for cluster-wide placement — /cluster/status,
    sw_ec_queue_load, `placement.NodeView.ec_load`). Reads only the
    scope's existing DeviceQueues — no queue is created and no jax/
    device state is touched (dead-relay safe)."""
    return resolve_scope(scope).queue_loads()


def _pod_sharded(backend) -> bool:
    """True when a mesh-kept stream's encode runs the explicit
    NamedSharding/pjit pod lowering (parallel.MeshRS.pod_sharded)."""
    primary = getattr(backend, "primary", backend)
    return bool(
        getattr(getattr(primary, "_mesh_rs", None), "pod_sharded", False)
    )


def _live_loads_for(pool: ChipPool, scope: QueueScope) -> list[int]:
    """Per-chip-index live load aligned with `pool.labels`: the scope's
    own DeviceQueue.load() (queued + in-flight) plus the residency
    ledger's CROSS-SCOPE share (every other scope's — and the mesh
    path's — in-flight cost on the chip) plus the breaker penalty.
    The scope's own in-flight cost is subtracted from the ledger view
    so it is never counted twice. Chips with no state anywhere read
    0 — never create a queue just to ask its load."""
    hint = scope.queue_loads()
    shared = scope.residency_loads()
    out = []
    for label in pool.labels:
        h = hint.get(label)
        load = 0
        own_inflight = 0
        if h is not None:
            load = int(h.get("load", 0))
            own_inflight = int(h.get("inflight_cost", 0))
        load += max(int(shared.get(label, 0)) - own_inflight, 0)
        if h is not None and h.get("breaker") == "open":
            load += BREAKER_OPEN_PENALTY
        out.append(load)
    return out


def place_stream(
    backend,
    priority: str,
    *,
    scope: QueueScope | None = None,
    cost_hint: int = 0,
    wide: bool = False,
    span=None,
) -> Placement:
    """Resolve where one new EC stream runs.

    Returns a Placement whose `.backend` the producer must use for
    to_device/…_staged/to_host and whose `.queue` its DeviceStream
    opens on (None = scheduler disabled: the PR 3 private window).
    The caller MUST close() the placement when the stream ends.

    Policy (scope's `ec_placement`):

    - "mesh": always the original backend (PR 4 behavior — every
      stream column-sliced across the pod behind one queue).
    - "chip": always route to the least-loaded chip of the pool.
    - "auto" (default): route to a chip, EXCEPT an explicitly `wide`
      stream arriving at an idle pod, which keeps the whole mesh
      (lone huge encode: slicing wins when nothing competes).

    No pool (single device, CPU backend, scheduler disabled) degrades
    to the original backend + its scope queue — exactly PR 4.
    `priority` does not influence routing (the per-chip queue enforces
    class policy); it is accepted so call sites read naturally and for
    future affinity policies.

    `span` (utils/trace.py; None = tracer disarmed) records the routing
    decision as a "placement" event carrying the pod load ledger the
    decision saw — the evidence for "why did this stream land on chip
    3" when reading a trace."""
    scope = resolve_scope(scope)
    if backend is None or not scope.enabled:
        # Scheduler disabled (or no backend): no pool routing either —
        # placement is a layer ON TOP of the per-chip queues. The mesh
        # queue itself is resolved lazily on the paths that USE it: a
        # chip-routed stream must not register a phantom mesh queue in
        # stats/metrics.
        return Placement(backend, None)
    mode = scope.placement
    pool = pool_for(backend)
    if mode == "mesh":
        if pool is None:
            return Placement(backend, scope.for_backend(backend))
        # Pinned mesh still charges the whole pod: another scope's
        # auto-wide placement must see this pod as busy, not stack a
        # second column-sliced stream through an independent window.
        if span is not None:
            span.event(
                "placement", mode=mode, chip="mesh", signal="mesh",
                loads=pool.loads(), cost_hint=cost_hint, wide=wide,
                queue_load_hint=chip_load_hint(scope),
                pod_sharded=_pod_sharded(backend),
            )
        _placement_decisions.inc(signal="mesh")
        _, _, release = pool.acquire(cost_hint, force_mesh=True)
        return Placement(backend, scope.for_backend(backend), None, release)
    if pool is None or pool.n_chips < 2:
        return Placement(backend, scope.for_backend(backend))
    # Routing inputs, snapshotted BEFORE the charge: the pod ledger
    # (static per-stream cost hints) PLUS the live per-chip queue load
    # (cost units queued+in-flight right now, breaker-penalized) — the
    # decision follows their SUM, so a chip busy with work the ledger
    # never saw repels new streams and a hinted-but-drained stream
    # stops repelling them.
    live = _live_loads_for(pool, scope)
    signal = "live" if any(live) else "ledger"
    loads_seen = pool.loads() if span is not None else None
    idx, chip_be, release = pool.acquire(
        cost_hint, prefer_mesh=(wide and mode == "auto"),
        live_loads=live,
    )
    if span is not None:
        # the evidence for "why did this stream land on chip 3": the
        # ledger AND the live queue loads the decision read, and which
        # signal source was decisive
        span.event(
            "placement", mode=mode,
            chip=("mesh" if idx is None else pool.labels[idx]),
            signal=("mesh" if idx is None else signal),
            loads=loads_seen, live_loads=live,
            cost_hint=cost_hint, wide=wide,
            queue_load_hint=chip_load_hint(scope),
            pod_sharded=(idx is None and _pod_sharded(backend)),
        )
    _placement_decisions.inc(signal=("mesh" if idx is None else signal))
    if idx is None:
        # Lone wide stream on an idle pod: it keeps the whole mesh and
        # the charge on every chip makes the pod read busy, so a second
        # arrival (wide or not) routes to a chip instead of stacking a
        # second column-sliced stream behind the same mesh queue.
        return Placement(backend, scope.for_backend(backend), None, release)
    try:
        chip_queue = scope.for_backend(chip_be)
    except BaseException:
        release()
        raise
    return Placement(chip_be, chip_queue, idx, release)
