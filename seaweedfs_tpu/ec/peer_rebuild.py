"""Peer-fetch EC rebuild: recover when no single server holds k shards.

Per-server rebuild (ec/rebuild.py) refuses when fewer than k source
shards are on local disk — correct, but on a balanced cluster EVERY
holder is a subset holder, so a quarantined shard could never be
regenerated anywhere. The reference solves this at the maintenance
layer (ec.rebuild collects shards onto one node first); this module is
the streaming equivalent: fetch just enough sibling shards from peer
holders through the shard-read RPC, rebuild locally on the TPU through
the staged/scheduled path, and publish only the regenerated targets.

Present-but-corrupt local shards whose rot is pinned to specific 64 KiB
leaves (v2 sidecar) are repaired at LEAF granularity first: only the
rotten leaves' byte ranges are fetched from k range-verified sources
(local good shards from disk, the rest over the ranged shard-read RPC)
and patched in place under the crash-consistent repair journal
(ec/repair_journal.py) — ~k·64 KiB of wire per rotten leaf instead of
~k·shard. Only what leaf repair cannot fix takes the whole-shard
fetch/rebuild/publish path below.

Correctness envelope (the same verify-and-exclude rules as the local
rebuild, extended across the wire):

- every fetched stream is verified against the .ecsum sidecar at the
  sidecar's own granularity WHILE it streams — a peer serving corrupt
  bytes is excluded (after one immediate re-read to rule out transient
  wire corruption) and the plan re-routes to another holder or another
  shard; transient failures (RPC errors, torn/short streams) retry
  under utils/retry.py before the holder is abandoned;
- fewer than k verified sources reachable = clean refusal: staging is
  wiped, nothing is published, the canonical files are untouched;
- fetched sources live ONLY in a staging directory next to the volume
  (hard links for verified-good local shards, downloads for the rest)
  so the local server never holds publishable copies of shards the
  master placed on peers — no duplicate minting, even across crashes;
- regenerated targets publish with the local rebuild's own machinery
  (temp + fsync + sidecar re-verify + atomic rename inside staging,
  then one rename per target into the canonical directory), so a
  re-run after any crash window converges idempotently.

The actual byte transport is injected (`fetch`), so the core is
testable without servers; server/volume_server.py wires it to the
VolumeEcShardRead RPC with the generation fence, and distributes
regenerated shards the local server does not own to planned holders
(ec/placement.py).
"""

from __future__ import annotations

import os
import shutil
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field

import numpy as np

from .. import faults
from ..utils import metrics as M
from ..utils import trace
from ..utils.crc import crc32c
from ..utils.fs import fsync_dir
from ..utils.glog import logger
from ..utils.retry import RetryError, RetryPolicy, retry_call
from .bitrot import BitrotError, BitrotProtection
from .context import ECContext, ECError
from .rebuild import rebuild_ec_files
from .repair_journal import (
    apply_leaf_repair,
    leaf_verdict,
    patched_byte_ranges,
    reconstruct_leaves,
)
from .volume_info import VolumeInfo

log = logger("ec.peer")

# Transient fetch failures (RPC errors, torn/short streams) retry
# quickly and give up fast: with several candidate holders per shard, a
# dead peer should cost milliseconds, not a backoff tail. ECError is
# never retried — refusals are deterministic.
DEFAULT_FETCH_POLICY = RetryPolicy(
    max_attempts=3, base_delay=0.05, max_delay=0.5,
)

# Fetch request size: granule-aligned so the sidecar CRC verdict lands
# per chunk (bounded memory, early corrupt-peer detection).
FETCH_CHUNK = 1 << 20

# The native ingress lands in pooled reusable buffers, so it can afford
# a wider window per request: fewer header round trips and Python-level
# chunk turnarounds on the zero-copy path (memory cost is one pooled
# matrix per in-flight stream, reused forever).
NATIVE_FETCH_CHUNK = 4 << 20

STAGING_PREFIX = ".peerfetch-"


class PeerFetchTransient(Exception):
    """One fetch attempt failed in a retryable way (RPC error, short or
    torn stream). `fetch` implementations raise this for transport
    errors; persistent transients abandon the holder, not the plan."""


class PeerPlaneUnavailable(Exception):
    """The peer serves no native shard byte plane (ec/net_plane.py):
    `fetch_into` implementations raise this so the stream falls back to
    the Python `fetch` transport — a capability miss, not a failure,
    so it is never retried and never excludes the holder."""


class PeerCorruptError(Exception):
    """A peer served bytes that fail sidecar verification even on a
    re-read: the holder is serving rot and is excluded from the plan."""

    def __init__(self, peer: str, shard: int, granule: int):
        super().__init__(
            f"peer {peer} serves corrupt bytes for shard {shard} "
            f"(granule {granule})"
        )
        self.peer = peer
        self.shard = shard


@dataclass
class PeerRebuildReport:
    """What one peer-fetch rebuild attempt did."""

    rebuilt: list[int] = field(default_factory=list)
    fetched: dict[int, str] = field(default_factory=dict)  # sid -> peer
    # Which byte plane carried each fetched stream ("native" = zero-copy
    # net-plane ingress straight into pooled aligned buffers, "python" =
    # the bit-identical gRPC/bytes fallback).
    fetched_plane: dict[int, str] = field(default_factory=dict)
    local_sources: list[int] = field(default_factory=list)
    corrupt_local: list[int] = field(default_factory=list)
    excluded_peers: list[str] = field(default_factory=list)
    # Present-but-corrupt local shards whose rot was leaf-localized and
    # repaired IN PLACE under the repair journal, fetching only the
    # rotten leaves' byte ranges from peers (shard -> patched leaves).
    # These never enter the whole-shard rebuild.
    leaf_repaired: dict[int, list[int]] = field(default_factory=dict)
    # Bytes actually pulled over the wire for those ranged repairs
    # (including granule re-reads) — the ~k·64 KiB-per-leaf acceptance
    # number, vs ~k·shard for a full peer-fetch rebuild.
    repair_wire_bytes: int = 0
    # In-place patches applied this run (shard -> [(lo, hi), ...]): the
    # serving layer drops cached reconstructions over exactly these.
    patched_ranges: dict[int, list[tuple[int, int]]] = field(
        default_factory=dict
    )


def staging_dir(base: str) -> str:
    """Staging directory for one volume's peer-fetch rebuild (same
    filesystem as the volume, so hard links and renames work)."""
    d, name = os.path.split(base)
    return os.path.join(d, STAGING_PREFIX + name)


def _clear_staging(sdir: str) -> None:
    shutil.rmtree(sdir, ignore_errors=True)


def _verify_local(
    base: str, ctx: ECContext, prot: BitrotProtection, present: list[int]
) -> tuple[list[int], list[int]]:
    """(verified-good, corrupt) split of the local present shards. An
    unreadable or size-mismatched shard counts corrupt — it must never
    be fed to Reed-Solomon."""

    def check(i: int) -> bool:
        p = base + ctx.to_ext(i)
        try:
            if os.path.getsize(p) != prot.shard_sizes[i]:
                return True
            return bool(prot.verify_shard_file(p, i, stop_early=True))
        except OSError:
            return True

    if len(present) <= 1:
        flags = [check(i) for i in present]
    else:
        with ThreadPoolExecutor(max_workers=min(len(present), 8)) as ex:
            flags = list(ex.map(check, present))
    corrupt = [i for i, bad in zip(present, flags) if bad]
    return [i for i in present if i not in corrupt], corrupt


def _fetch_shard_verified(
    sbase: str,
    peer: str,
    sid: int,
    prot: BitrotProtection,
    ctx: ECContext,
    fetch,
    policy: RetryPolicy,
    fetch_into=None,
) -> str:
    """Stream one whole shard from `peer` into staging, verifying the
    sidecar CRC per granule as the bytes land, and return the plane
    that carried it ("native" | "python"). Raises PeerCorruptError when
    a granule mismatches even after one immediate re-read (the
    transient-wire-corruption escape), PeerFetchTransient/RetryError
    when the peer stays unreachable. Publishes atomically INSIDE
    staging; a partial download never looks like a shard.

    The native plane (`fetch_into` provided, native_io enabled, fault
    registry disarmed) lands each chunk DIRECTLY in a pooled aligned
    buffer with the granule CRC fused into the copy-in; the Python
    plane is the bit-identical `fetch`-based fallback, which also
    carries every stream whenever chaos is armed (byte-mutating fault
    points need materialized bytes)."""
    if fetch_into is not None:
        from . import native_io

        if native_io.enabled() and not faults.active():
            try:
                _fetch_shard_stream_native(
                    sbase, peer, sid, prot, ctx, fetch, fetch_into, policy
                )
                return "native"
            except PeerPlaneUnavailable as e:
                log.info(
                    "peer %s has no native shard plane (%s); falling back "
                    "to the python fetch", peer, e,
                )
    _fetch_shard_stream_python(sbase, peer, sid, prot, ctx, fetch, policy)
    return "python"


def _fetch_shard_stream_native(
    sbase: str,
    peer: str,
    sid: int,
    prot: BitrotProtection,
    ctx: ECContext,
    fetch,
    fetch_into,
    policy: RetryPolicy,
) -> None:
    """Native ingress: `fetch_into(peer, sid, off, size, dst, granule)`
    lands each granule-aligned chunk straight into a pooled 4096-aligned
    buffer and returns the granule CRCs rolled DURING the copy-in, so
    the verify-and-exclude pass below compares integers against the
    sidecar instead of re-reading bytes. A mismatched granule gets one
    immediate byte-level re-read through `fetch` (the transient-wire-
    corruption escape); a repeat mismatch excludes the holder. The
    staging file is written with raw unbuffered I/O straight from the
    landing buffer — socket to matrix to disk, one userspace copy
    total."""
    gsize, gcrcs = prot.verify_granularity(sid)
    size = prot.shard_sizes[sid]
    chunk = max(NATIVE_FETCH_CHUNK - NATIVE_FETCH_CHUNK % gsize, gsize)
    dest = sbase + ctx.to_ext(sid)
    tmp = dest + ".fetching"
    from .native_io import landing_pool

    pool = landing_pool()
    buf = pool.get(chunk)
    sp = trace.start(
        "ec.peer_fetch", name=f"shard {sid} <- {peer}",
        peer=peer, shard=sid, bytes=size, plane="native",
    )
    try:
        with open(tmp, "wb", buffering=0) as f:
            off = 0
            gi = 0
            while off < size:
                n = min(chunk, size - off)
                row = buf[0, :n]

                def attempt(off=off, n=n, row=row):
                    return fetch_into(peer, sid, off, n, row, gsize)

                with trace.stage(sp, "peer_fetch"):
                    crcs = retry_call(
                        attempt, policy, retry_on=(PeerFetchTransient,),
                        describe=f"peer fetch {peer} shard {sid}",
                    )
                with trace.stage(sp, "crc_verify"):
                    ngr = (n + gsize - 1) // gsize
                    if crcs is None or len(crcs) != ngr:
                        raise PeerFetchTransient(
                            f"native ingress returned {0 if crcs is None else len(crcs)} "
                            f"granule CRCs for {ngr} granules"
                        )
                    for j in range(ngr):
                        if gi + j < len(gcrcs) and int(crcs[j]) == gcrcs[gi + j]:
                            continue
                        # one immediate byte-level re-read of ONLY this
                        # granule rules out transient wire corruption; a
                        # repeat mismatch is the peer serving rot
                        lo = j * gsize
                        glen = min(gsize, n - lo)

                        def reread(off=off, lo=lo, glen=glen):
                            return fetch(peer, sid, off + lo, glen)

                        g2 = retry_call(
                            reread, policy, retry_on=(PeerFetchTransient,),
                            describe=f"peer fetch {peer} shard {sid}",
                        )
                        if gi + j >= len(gcrcs) or crc32c(g2) != gcrcs[gi + j]:
                            raise PeerCorruptError(peer, sid, gi + j)
                        row[lo : lo + glen] = np.frombuffer(g2, dtype=np.uint8)
                    gi += ngr
                with trace.stage(sp, "write_sink"):
                    mv = memoryview(row)
                    while mv:
                        mv = mv[f.write(mv):]
                off += n
            with trace.stage(sp, "fsync_publish"):
                os.fsync(f.fileno())
        os.replace(tmp, dest)
    finally:
        pool.put(buf)
        if os.path.exists(tmp):
            os.unlink(tmp)
        trace.finish(sp)


def _fetch_shard_stream_python(
    sbase: str,
    peer: str,
    sid: int,
    prot: BitrotProtection,
    ctx: ECContext,
    fetch,
    policy: RetryPolicy,
) -> None:
    """Python-plane whole-shard stream (the PR 6 byte path, unchanged):
    `fetch` materializes bytes, the granule CRC is rolled over them as
    they land, and byte-mutating chaos points apply at the seams."""
    gsize, gcrcs = prot.verify_granularity(sid)
    size = prot.shard_sizes[sid]
    chunk = max(FETCH_CHUNK - FETCH_CHUNK % gsize, gsize)
    dest = sbase + ctx.to_ext(sid)
    tmp = dest + ".fetching"
    # Child span per fetched shard stream (parent: the ec.peer_rebuild
    # root active in this thread) — wire time vs CRC time per peer.
    sp = trace.start(
        "ec.peer_fetch", name=f"shard {sid} <- {peer}",
        peer=peer, shard=sid, bytes=size,
    )

    def get(off: int, n: int) -> bytes:
        def attempt() -> bytes:
            try:
                # Named client-side chaos point: a raised IOError is a
                # transient fetch failure; a mutate corrupts the stream
                # the way a rotten peer (or a bad NIC) would, which the
                # granule CRC below must catch.
                faults.fire(
                    "ec.peer_fetch.read", peer=peer, shard=sid, offset=off
                )
                data = fetch(peer, sid, off, n)
            except (PeerFetchTransient, PeerCorruptError):
                raise
            except (IOError, OSError) as e:
                raise PeerFetchTransient(str(e)) from e
            data = faults.mutate(
                "ec.peer_fetch.read", data, peer=peer, shard=sid, offset=off
            )
            if len(data) != n:
                raise PeerFetchTransient(
                    f"short read from {peer} for shard {sid}: "
                    f"{len(data)}/{n} bytes at {off}"
                )
            return data

        with trace.stage(sp, "peer_fetch"):
            return retry_call(
                attempt, policy, retry_on=(PeerFetchTransient,),
                describe=f"peer fetch {peer} shard {sid}",
            )

    try:
        with open(tmp, "wb") as f:
            off = 0
            gi = 0
            while off < size:
                n = min(chunk, size - off)
                data = get(off, n)
                # granule-level sidecar verdict while the chunk is hot
                with trace.stage(sp, "crc_verify"):
                    for j in range(0, n, gsize):
                        g = data[j : j + gsize]
                        if gi >= len(gcrcs) or crc32c(g) != gcrcs[gi]:
                            # one immediate re-read rules out transient
                            # wire corruption; a repeat mismatch is the
                            # PEER serving rot. Re-read ONLY this
                            # granule's byte range: the rest of `data`
                            # already passed its CRCs, and re-pulling
                            # the whole chunk would cost up to
                            # chunk/gsize times the wire traffic to
                            # splice out one granule.
                            g2 = get(off + j, len(g))
                            if gi >= len(gcrcs) or crc32c(g2) != gcrcs[gi]:
                                raise PeerCorruptError(peer, sid, gi)
                            data = data[:j] + g2 + data[j + gsize :]
                        gi += 1
                with trace.stage(sp, "write_sink"):
                    f.write(data)
                off += n
            with trace.stage(sp, "fsync_publish"):
                f.flush()
                os.fsync(f.fileno())
        os.replace(tmp, dest)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
        trace.finish(sp)


def _fetch_range_verified(
    peer: str,
    sid: int,
    lo: int,
    size: int,
    prot: BitrotProtection,
    fetch,
    policy: RetryPolicy,
    counter: list,
    sp=None,
) -> bytes:
    """Fetch ONE leaf-aligned byte range [lo, lo+size) of a sibling
    shard from `peer`, verifying every granule against the sidecar as
    it lands — the ranged analog of `_fetch_shard_verified`. A granule
    that mismatches gets one immediate re-read (transient wire
    corruption); a repeat mismatch raises PeerCorruptError so the
    caller excludes the holder. `counter[0]` accumulates the bytes
    actually pulled over the wire (re-reads included)."""
    gsize, _ = prot.verify_granularity(sid)

    def get(off: int, n: int) -> bytes:
        def attempt() -> bytes:
            try:
                faults.fire(
                    "ec.peer_fetch.read", peer=peer, shard=sid, offset=off
                )
                data = fetch(peer, sid, off, n)
            except (PeerFetchTransient, PeerCorruptError):
                raise
            except (IOError, OSError) as e:
                raise PeerFetchTransient(str(e)) from e
            data = faults.mutate(
                "ec.peer_fetch.read", data, peer=peer, shard=sid, offset=off
            )
            if len(data) != n:
                raise PeerFetchTransient(
                    f"short read from {peer} for shard {sid}: "
                    f"{len(data)}/{n} bytes at {off}"
                )
            return data

        with trace.stage(sp, "repair_fetch"):
            got = retry_call(
                attempt, policy, retry_on=(PeerFetchTransient,),
                describe=f"peer range fetch {peer} shard {sid}",
            )
        counter[0] += len(got)
        return got

    data = get(lo, size)
    with trace.stage(sp, "crc_verify"):
        if not prot.verify_range(sid, lo, data):
            # pin the mismatch to its granule(s): one immediate re-read
            # each (transient wire corruption); a repeat mismatch is
            # the peer serving rot
            for j in range(0, size, gsize):
                g = data[j : j + gsize]
                if prot.verify_range(sid, lo + j, g):
                    continue
                g2 = get(lo + j, len(g))
                if not prot.verify_range(sid, lo + j, g2):
                    raise PeerCorruptError(peer, sid, (lo + j) // gsize)
                data = data[:j] + g2 + data[j + len(g) :]
    return data


def rebuild_from_peers(
    base: str,
    holders: dict[int, list[str]],
    fetch,
    *,
    ctx: ECContext | None = None,
    targets: list[int] | None = None,
    backend=None,
    scheduler=None,
    priority: str = "recovery",
    policy: RetryPolicy = DEFAULT_FETCH_POLICY,
    fetch_into=None,
) -> PeerRebuildReport:
    """Regenerate `targets` for the volume at `base`, fetching sibling
    source shards from peer holders when fewer than k verified-good
    shards are on local disk.

    `holders` maps shard id -> peer ids that serve it (the LOCAL server
    must already be excluded); `fetch(peer, shard_id, offset, size)`
    returns exactly `size` bytes or raises PeerFetchTransient.
    `fetch_into(peer, shard_id, offset, size, dst, granule)` is the
    OPTIONAL native-plane transport (ec/net_plane.make_fetch_into):
    lands the range directly in `dst` and returns the granule CRCs
    rolled during the copy-in, raises PeerPlaneUnavailable for peers
    without the plane — whole-shard streams then ride it whenever the
    native plane is enabled and the fault registry is disarmed, with
    the `fetch` path as the bit-identical fallback.
    `targets=None` regenerates every shard that is not locally
    verified-good; an explicit list restricts regeneration to those ids
    (the server passes its legitimate-set union cluster-lost, the same
    no-duplicate-minting contract as the local rebuild RPC) —
    present-but-corrupt local shards are always replaced regardless.

    Fail-closed: no (or malformed) .ecsum refuses — peer bytes cannot
    be trusted unverified; fewer than k reachable verified sources
    refuses with nothing published and staging wiped.
    """
    ecsum = base + ".ecsum"
    if not os.path.exists(ecsum):
        raise ECError(
            f"peer-fetch rebuild for {base} needs the .ecsum sidecar to "
            f"verify fetched streams; refusing"
        )
    try:
        prot = BitrotProtection.load(ecsum)
    except BitrotError as e:
        raise ECError(
            f"bitrot sidecar for {base} is malformed ({e}); refusing "
            f"peer-fetch rebuild"
        ) from e
    if ctx is None:
        vif = base + ".vif"
        if os.path.exists(vif):
            vi = VolumeInfo.load(vif)
            ctx = vi.ec_ctx
        if ctx is None:
            ctx = prot.ctx
    if prot.ctx != ctx:
        raise ECError(
            f"bitrot sidecar for {base} records ratio {prot.ctx} but the "
            f"volume config says {ctx}; refusing peer-fetch rebuild"
        )
    k = ctx.data_shards

    # Flight-recorder root for the whole peer-fetch rebuild (a child
    # when the holder's RPC span is active in this thread): per-peer
    # fetch child spans, the nested local rebuild, and the publish
    # renames all hang off it, so one cluster heal reads as one tree.
    sp = trace.start(
        "ec.peer_rebuild", name=os.path.basename(base), base=base,
        targets=("auto" if targets is None else sorted(targets)),
    )
    try:
        with trace.activate(sp):
            return _rebuild_from_peers_span(
                base, holders, fetch, ctx, targets, backend, scheduler,
                priority, policy, prot, ecsum, k, sp, fetch_into,
            )
    finally:
        trace.finish(sp)


def _rebuild_from_peers_span(
    base, holders, fetch, ctx, targets, backend, scheduler, priority,
    policy, prot, ecsum, k, sp, fetch_into=None,
) -> PeerRebuildReport:
    report = PeerRebuildReport()
    present = [
        i for i in range(ctx.total) if os.path.exists(base + ctx.to_ext(i))
    ]
    with trace.stage(sp, "verify"):
        good_local, corrupt_local = _verify_local(base, ctx, prot, present)
    report.local_sources = list(good_local)
    report.corrupt_local = list(corrupt_local)
    excluded: set[str] = set()

    # ---- leaf-granular ranged repair of present-but-corrupt locals ----
    # When the rot is pinned to specific leaves (v2 sidecar, full-length
    # file), fetch ONLY those leaves' byte ranges from k verified
    # sources — local good shards read from disk, the remainder pulled
    # from peers through the ranged shard-read RPC — and patch the
    # canonical file in place under the repair journal. Wire cost:
    # ~k·64 KiB per rotten leaf instead of ~k·shard. Anything this
    # cannot fix stays in corrupt_local and takes the whole-shard path.
    if prot.has_leaves and corrupt_local:
        for sid in list(corrupt_local):
            path = base + ctx.to_ext(sid)
            bad = leaf_verdict(path, sid, prot)
            if bad is None:
                continue  # size rot / unreadable: whole-shard replacement
            if not bad:
                # whole-shard verify failed but every leaf now verifies:
                # repaired between the two walks — treat as good
                corrupt_local.remove(sid)
                good_local.append(sid)
                report.corrupt_local.remove(sid)
                report.local_sources = sorted(
                    set(report.local_sources) | {sid}
                )
                continue
            wire = [0]

            def read_range(src: int, lo: int, size: int) -> bytes | None:
                if src in good_local:
                    try:
                        faults.fire(
                            "ec.repair.source_read", shard=src, offset=lo
                        )
                        with open(base + ctx.to_ext(src), "rb") as f:
                            f.seek(lo)
                            got = f.read(size)
                        if len(got) == size:
                            return faults.mutate(
                                "ec.repair.source_read", got,
                                shard=src, offset=lo,
                            )
                    except (OSError, IOError):
                        pass  # transient local I/O: the same shard may
                        # still be servable by a peer holder below —
                        # don't forfeit the cheap ranged path over it
                for peer in holders.get(src, []):
                    if peer in excluded:
                        continue
                    try:
                        return _fetch_range_verified(
                            peer, src, lo, size, prot, fetch, policy,
                            wire, sp,
                        )
                    except PeerCorruptError as e:
                        log.warning("excluding peer: %s", e)
                        trace.event(
                            sp, "peer_excluded", peer=peer, shard=src
                        )
                        excluded.add(peer)
                        continue
                    except (PeerFetchTransient, RetryError) as e:
                        log.warning(
                            "peer %s unreachable for shard %d range "
                            "[%d:+%d): %s", peer, src, lo, size, e,
                        )
                        continue
                return None

            candidates = sorted(good_local) + sorted(
                s for s in holders
                if s not in good_local and s != sid and 0 <= s < ctx.total
            )
            try:
                patches = reconstruct_leaves(
                    prot, ctx, sid, bad, read_range, candidates,
                    backend=backend, span=sp,
                )
                apply_leaf_repair(path, sid, prot, patches, span=sp)
            except (ECError, OSError) as e:
                M.ec_leaf_repairs_total.inc(outcome="failed")
                log.warning(
                    "ranged leaf repair of shard %d failed (%s); falling "
                    "back to whole-shard peer rebuild", sid, e,
                )
                continue
            corrupt_local.remove(sid)
            good_local.append(sid)
            report.corrupt_local.remove(sid)
            report.local_sources = sorted(set(report.local_sources) | {sid})
            report.leaf_repaired[sid] = sorted(bad)
            report.repair_wire_bytes += wire[0]
            report.patched_ranges[sid] = patched_byte_ranges(prot, sid, bad)
            M.ec_leaf_repairs_total.inc(outcome="repaired")
            log.warning(
                "leaf-repaired shard %d in place (leaves %s, %d wire "
                "bytes)", sid, sorted(bad), wire[0],
            )
        report.excluded_peers = sorted(excluded)

    if targets is None:
        want = sorted(set(range(ctx.total)) - set(good_local))
    else:
        # present-but-corrupt shards are always replaced, like the
        # local rebuild's verify-and-exclude contract
        want = sorted(set(targets) | set(corrupt_local))
        want = [i for i in want if i not in good_local]
    if not want:
        return report

    sdir = staging_dir(base)
    _clear_staging(sdir)  # leftovers from a crashed attempt
    os.makedirs(sdir, exist_ok=True)
    sbase = os.path.join(sdir, os.path.basename(base))

    # `excluded` carries over from the ranged-repair stage: a holder
    # that served rot for a 64 KiB range serves rot, full stop.
    try:
        # ---- assemble k verified sources: local links + peer streams --
        sources = set(good_local)
        candidates = sorted(
            sid
            for sid, peers in holders.items()
            if peers and sid not in sources and sid not in want
            and 0 <= sid < ctx.total
        )
        for sid in candidates:
            if len(sources) >= k:
                break
            for peer in holders[sid]:
                if peer in excluded:
                    continue
                try:
                    plane = _fetch_shard_verified(
                        sbase, peer, sid, prot, ctx, fetch, policy,
                        fetch_into=fetch_into,
                    )
                except PeerCorruptError as e:
                    # verify-and-exclude across the wire: this holder
                    # serves rot; nothing it sends is trustworthy
                    log.warning("excluding peer: %s", e)
                    trace.event(sp, "peer_excluded", peer=peer, shard=sid)
                    excluded.add(peer)
                    continue
                except (PeerFetchTransient, RetryError) as e:
                    log.warning(
                        "peer %s unreachable for shard %d: %s", peer, sid, e
                    )
                    continue
                sources.add(sid)
                report.fetched[sid] = peer
                report.fetched_plane[sid] = plane
                break
        report.excluded_peers = sorted(excluded)
        if len(sources) < k:
            raise ECError(
                f"peer-fetch rebuild for {base}: only {len(sources)} "
                f"verified source shards reachable (local "
                f"{sorted(good_local)}, fetched "
                f"{sorted(report.fetched)}, excluded peers "
                f"{sorted(excluded)}); need {k} — refusing, nothing "
                f"published"
            )

        # ---- stage local sources + sidecars, rebuild, publish ---------
        # exactly k staged inputs: linking surplus local shards would
        # only buy extra verification reads inside the rebuild
        for sid in sorted(good_local)[: k - len(report.fetched)]:
            os.link(base + ctx.to_ext(sid), sbase + ctx.to_ext(sid))
        os.link(ecsum, sbase + ".ecsum")
        if os.path.exists(base + ".vif"):
            os.link(base + ".vif", sbase + ".vif")

        rebuilt = rebuild_ec_files(
            sbase,
            ctx,
            backend=backend,
            only_shards=want,
            scheduler=scheduler,
            priority=priority,
        )

        # Crash window: regenerated targets are durable in staging but
        # not yet at the canonical paths. A crash here (or between the
        # per-target renames below) republishes idempotently on re-run:
        # already-renamed targets verify good and drop out of `want`.
        faults.fire("ec.peer_rebuild.before_publish", base=base)
        for sid in sorted(rebuilt):
            os.replace(sbase + ctx.to_ext(sid), base + ctx.to_ext(sid))
            faults.fire("ec.peer_rebuild.after_publish", base=base, shard=sid)
            report.rebuilt.append(sid)
        fsync_dir(base + ".ecsum")
    finally:
        _clear_staging(sdir)
    return report
