"""Crash-consistent in-place leaf repair: the write-ahead repair journal.

Scrub's v2 leaf-CRC sidecar (PR 2) pins rot to a 64 KiB leaf, but until
now the only cure was whole-shard quarantine + full rebuild + atomic
whole-file publish — ~k shards of I/O to fix 64 KiB. This module is the
missing publish story for PARTIAL repair: patch just the rotten leaves
of a shard file IN PLACE, with a write-ahead journal making the patch
atomic across power loss.

Protocol (one journal file `<shard>.repair` next to the shard):

  1. INTENT   — write the journal: shard id, sidecar generation + uuid
                fence, leaf ranges, the full NEW leaf bytes and their
                CRCs, all self-checksummed; fsync file + directory.
  2. PATCH    — pwrite the new leaf bytes into the shard file at their
                leaf offsets; fsync the shard.
  3. FLIP     — if the new leaf CRCs differ from the sidecar's current
                row, publish the updated sidecar (atomic_write; block
                CRCs re-folded from the leaf row via crc32c_combine).
  4. RETIRE   — unlink the journal; fsync the directory.

Crash windows and why recovery converges (enumerated by the fault
registry points, asserted in tests/test_ec_leaf_repair.py):

  window                      | on-disk evidence      | recovery
  ----------------------------+-----------------------+-----------------
  torn journal write (1)      | journal fails its own | ROLL BACK: delete
                              | checksum              | journal; patch
                              |                       | never started, the
                              |                       | shard is fully-OLD
  crash after intent (1->2)   | valid journal, shard  | REPLAY: re-patch
                              | untouched             | all leaves -> NEW
  torn patch (2)              | valid journal, shard  | REPLAY: pwrite is
                              | partially patched     | idempotent -> NEW
  crash patch->flip (2->3)    | valid journal, shard  | REPLAY + FLIP
                              | fully patched, stale  | -> NEW
                              | sidecar               |
  crash flip->retire (3->4)   | valid journal, shard  | REPLAY (no-op
                              | + sidecar both new    | bytes) + RETIRE
                              |                       | -> NEW

The shard is therefore ALWAYS either fully-old-verified or fully-new-
verified against its sidecar, never a mix: a valid journal always
carries every byte needed to roll the whole patch set forward, and a
torn journal proves the patch never began (step 2 starts only after the
journal is durable).

In the common repair case (restore a shard to MATCH its sidecar) the
new-leaf CRCs equal the sidecar's existing row and step 3 is a no-op —
but the window is still exercised, because generality (a future
content-changing patcher) and the chaos matrix demand it.

`reconstruct_leaves` is the companion math: rebuild only the rotten
leaves' byte ranges from k range-verified sibling sources (local files
or ranged peer fetches — the caller supplies `read_range`), verify the
output against the target's own leaf CRCs, and hand back patches ready
for `apply_leaf_repair`. Repair cost becomes ~k·64 KiB per rotten leaf
instead of ~k·shard.
"""

from __future__ import annotations

import os
import struct
import time
from dataclasses import dataclass, field

from .. import faults
from ..utils import metrics as M
from ..utils import trace
from ..utils.crc import crc32c
from ..utils.fs import fsync_dir
from ..utils.glog import logger
from .bitrot import BitrotError, BitrotProtection, fold_leaf_crcs
from .context import ECContext, ECError

log = logger("ec.repair")

JOURNAL_SUFFIX = ".repair"

MAGIC = 0x5357524A  # "SWRJ" — same self-checksummed header idiom as .ecsum
FORMAT_VERSION = 1
_HEADER = struct.Struct(">I")
_HEADER_REST = struct.Struct("<HII")


class JournalError(ECError):
    """The journal file is torn/malformed (fails its own checksum)."""


@dataclass(frozen=True)
class LeafPatch:
    """One leaf's replacement bytes. `offset` is the byte position in
    the shard file (leaf * leaf_size); `crc` is crc32c(data) — the CRC
    the sidecar's leaf row must carry once the patch is published."""

    leaf: int
    offset: int
    data: bytes
    crc: int


@dataclass
class RepairJournal:
    """Decoded `<shard>.repair` contents: the full intent record."""

    shard_id: int
    generation: int  # sidecar generation fence at intent time
    uuid: bytes  # sidecar uuid fence at intent time
    leaf_size: int
    shard_size: int  # sanity fence: in-place patches never resize
    patches: list[LeafPatch] = field(default_factory=list)

    def to_bytes(self) -> bytes:
        parts = [
            struct.pack(
                "<IQIQI",
                self.shard_id,
                self.generation,
                self.leaf_size,
                self.shard_size,
                len(self.patches),
            ),
            self.uuid,
        ]
        for p in self.patches:
            parts.append(struct.pack("<IQII", p.leaf, p.offset, len(p.data), p.crc))
        for p in self.patches:
            parts.append(p.data)
        payload = b"".join(parts)
        return (
            _HEADER.pack(MAGIC)
            + _HEADER_REST.pack(FORMAT_VERSION, len(payload), crc32c(payload))
            + payload
        )

    @classmethod
    def from_bytes(cls, raw: bytes) -> "RepairJournal":
        hs = _HEADER.size + _HEADER_REST.size
        if len(raw) < hs:
            raise JournalError("repair journal too short")
        (magic,) = _HEADER.unpack(raw[: _HEADER.size])
        version, plen, pcrc = _HEADER_REST.unpack(raw[_HEADER.size : hs])
        if magic != MAGIC:
            raise JournalError(f"bad repair-journal magic {magic:08x}")
        if version != FORMAT_VERSION:
            raise JournalError(f"unsupported repair-journal version {version}")
        payload = raw[hs : hs + plen]
        if len(payload) != plen or crc32c(payload) != pcrc:
            # the torn-write verdict: a crash mid-journal-write leaves a
            # short or corrupt payload, which proves the patch phase
            # never began (it only starts after the journal fsync)
            raise JournalError("repair journal torn (payload checksum mismatch)")
        try:
            sid, gen, lsize, ssize, count = struct.unpack("<IQIQI", payload[:28])
            uid = payload[28:44]
            pos = 44
            metas = []
            for _ in range(count):
                leaf, off, dlen, crc = struct.unpack(
                    "<IQII", payload[pos : pos + 20]
                )
                pos += 20
                metas.append((leaf, off, dlen, crc))
            patches = []
            for leaf, off, dlen, crc in metas:
                data = payload[pos : pos + dlen]
                if len(data) != dlen:
                    raise JournalError("repair journal truncated patch data")
                pos += dlen
                patches.append(LeafPatch(leaf, off, data, crc))
            if pos != plen:
                raise JournalError("trailing bytes in repair journal")
        except struct.error as e:
            raise JournalError(f"malformed repair journal: {e}") from None
        return cls(sid, gen, uid, lsize, ssize, patches)

    @classmethod
    def load(cls, path: str) -> "RepairJournal":
        try:
            with open(path, "rb") as f:
                return cls.from_bytes(f.read())
        except OSError as e:
            raise JournalError(f"unreadable repair journal {path}: {e}") from e


def journal_path(shard_path: str) -> str:
    return shard_path + JOURNAL_SUFFIX


def volume_journals(base: str, ctx: ECContext) -> list[tuple[int, str]]:
    """(shard_id, journal_path) for every `<shard>.repair` on disk."""
    out = []
    for sid in range(ctx.total):
        jp = journal_path(base + ctx.to_ext(sid))
        if os.path.exists(jp):
            out.append((sid, jp))
    return out


# ----------------------------------------------------------- publication


def _write_journal(jpath: str, journal: RepairJournal) -> None:
    data = journal.to_bytes()
    # torn-journal chaos: a mutate tears/corrupts the journal bytes the
    # way a power cut mid-write would; recovery must classify the file
    # torn and roll back
    data = faults.mutate("ec.repair.journal_bytes", data, path=jpath)
    with open(jpath, "wb") as f:
        f.write(data)
        # crash window: journal bytes written, not yet durable
        faults.fire("ec.repair.journal_write", path=jpath)
        f.flush()
        os.fsync(f.fileno())
    fsync_dir(jpath)


def _patch_shard(shard_path: str, patches: list[LeafPatch]) -> None:
    fd = os.open(shard_path, os.O_WRONLY)
    try:
        for p in patches:
            data = faults.mutate(
                "ec.repair.patch_bytes", p.data, path=shard_path, leaf=p.leaf
            )
            os.pwrite(fd, data, p.offset)
        # crash window: leaf bytes (possibly torn) written, not yet
        # durable — recovery replays the journal over them
        faults.fire("ec.repair.patch_write", path=shard_path)
        os.fsync(fd)
    finally:
        os.close(fd)


def _flip_sidecar(
    prot: BitrotProtection, ecsum_path: str, shard_id: int, patches: list[LeafPatch]
) -> bool:
    """Publish the sidecar with the patched leaves' CRCs (and block CRCs
    re-folded from the leaf row). Returns False when every patch CRC
    already matches — the repair-to-match-sidecar case."""
    row = prot.shard_leaf_crcs[shard_id]
    if all(p.leaf < len(row) and row[p.leaf] == p.crc for p in patches):
        return False
    for p in patches:
        row[p.leaf] = p.crc
    prot.shard_crcs[shard_id] = fold_leaf_crcs(
        row, prot.shard_sizes[shard_id], prot.leaf_size, prot.block_size
    )
    prot.save(ecsum_path)  # atomic_write: temp + fsync + rename
    return True


def apply_leaf_repair(
    shard_path: str,
    shard_id: int,
    prot: BitrotProtection,
    patches: list[LeafPatch],
    *,
    ecsum_path: str | None = None,
    span=None,
) -> None:
    """Run the full journal protocol for one shard's leaf patch set:
    intent -> in-place patch -> sidecar flip (when the CRCs change) ->
    retire. A crash at ANY point leaves the shard recoverable to a
    fully-verified state by `recover_volume_journals` (see the window
    table in the module docstring)."""
    if not patches:
        return
    if not prot.has_leaves:
        raise ECError(
            f"leaf repair of {shard_path} needs a v2 (leaf-CRC) sidecar"
        )
    if ecsum_path is None:
        # <base>.ec00 -> <base>.ecsum (shard extensions are .ecNN)
        ecsum_path = shard_path[: shard_path.rfind(".ec")] + ".ecsum"
    jpath = journal_path(shard_path)
    journal = RepairJournal(
        shard_id=shard_id,
        generation=prot.generation,
        uuid=prot.uuid,
        leaf_size=prot.leaf_size,
        shard_size=prot.shard_sizes[shard_id],
        patches=list(patches),
    )
    with trace.stage(span, "repair_patch"):
        _write_journal(jpath, journal)
        # crash window: intent durable, shard untouched
        faults.fire("ec.repair.after_journal", path=shard_path, shard=shard_id)
        _patch_shard(shard_path, patches)
        # crash window: shard patched + durable, sidecar flip pending
        faults.fire("ec.repair.after_patch", path=shard_path, shard=shard_id)
        _flip_sidecar(prot, ecsum_path, shard_id, patches)
        # crash window: sidecar published, journal retire pending
        faults.fire("ec.repair.after_sidecar", path=shard_path, shard=shard_id)
        os.unlink(jpath)
        fsync_dir(jpath)


# --------------------------------------------------------------- recovery


def recover_volume_journals(
    base: str, ctx: ECContext, prot: BitrotProtection | None = None
) -> dict:
    """Mount/scrub-time recovery: replay or roll back every pending
    `<shard>.repair` of this volume so serving never starts over a
    half-applied patch.

    - torn journal (fails its own checksum): the patch never began —
      ROLL BACK by deleting the journal; the shard is fully-old.
    - valid journal matching the current sidecar's generation + uuid:
      REPLAY the whole patch set (idempotent pwrites), re-publish the
      sidecar if its leaf row still differs, retire the journal; the
      shard is fully-new.
    - valid journal that does NOT match the mounted sidecar (volume
      re-encoded since) or whose shard file is gone/resized: the intent
      is STALE/ORPHANED — kept on disk for forensics until scrub's TTL
      sweep (`sweep_stale_journals`) retires it.

    Returns {"replayed": {sid: [leaf, ...]}, "rolled_back": [path],
    "kept": [path]}.
    """
    out: dict = {"replayed": {}, "rolled_back": [], "kept": []}
    pending = volume_journals(base, ctx)
    if not pending:
        return out
    if prot is None:
        try:
            prot = BitrotProtection.load(base + ".ecsum")
        except (OSError, BitrotError):
            prot = None
    for sid, jpath in pending:
        try:
            journal = RepairJournal.load(jpath)
        except JournalError as e:
            # torn intent: the protocol guarantees the shard was never
            # touched — deleting the journal IS the rollback
            log.warning("rolling back torn repair journal %s: %s", jpath, e)
            try:
                os.unlink(jpath)
                fsync_dir(jpath)
            except OSError:
                continue
            out["rolled_back"].append(jpath)
            M.ec_repair_journal_total.inc(action="rolled_back")
            continue
        shard_path = base + ctx.to_ext(sid)
        stale = (
            prot is None
            or journal.shard_id != sid
            or journal.generation != prot.generation
            or journal.uuid != prot.uuid
            or not os.path.exists(shard_path)
            or os.path.getsize(shard_path) != journal.shard_size
        )
        if stale:
            log.warning(
                "keeping stale/orphaned repair journal %s (sidecar or "
                "shard no longer matches the recorded intent)", jpath,
            )
            out["kept"].append(jpath)
            M.ec_repair_journal_total.inc(action="kept")
            continue
        try:
            _patch_shard(shard_path, journal.patches)
            if prot.has_leaves:
                _flip_sidecar(prot, base + ".ecsum", sid, journal.patches)
            os.unlink(jpath)
            fsync_dir(jpath)
        except OSError as e:
            log.error("repair-journal replay of %s failed: %s", jpath, e)
            out["kept"].append(jpath)
            M.ec_repair_journal_total.inc(action="kept")
            continue
        out["replayed"][sid] = sorted(p.leaf for p in journal.patches)
        M.ec_repair_journal_total.inc(action="replayed")
        log.warning(
            "replayed repair journal %s (leaves %s)", jpath, out["replayed"][sid]
        )
    return out


def sweep_stale_journals(
    base: str, ctx: ECContext, ttl_s: float, now: float | None = None
) -> list[str]:
    """Retire stale/orphaned `<shard>.repair` files older than `ttl_s`
    (recovery keeps them for forensics — see recover_volume_journals).
    Valid journals that still match the sidecar are NEVER swept: they
    are pending recovery work, not litter."""
    swept: list[str] = []
    pending = volume_journals(base, ctx)
    if not pending:
        return swept
    try:
        prot = BitrotProtection.load(base + ".ecsum")
    except (OSError, BitrotError):
        prot = None
    if now is None:
        now = time.time()
    for sid, jpath in pending:
        try:
            if now - os.path.getmtime(jpath) < ttl_s:
                continue
        except OSError:
            continue
        try:
            journal = RepairJournal.load(jpath)
            shard_path = base + ctx.to_ext(sid)
            live = (
                prot is not None
                and journal.shard_id == sid
                and journal.generation == prot.generation
                and journal.uuid == prot.uuid
                and os.path.exists(shard_path)
                and os.path.getsize(shard_path) == journal.shard_size
            )
        except JournalError:
            live = False  # torn: recovery will roll it back, but a torn
            # journal older than the TTL is also sweepable litter
        if live:
            continue
        try:
            os.unlink(jpath)
        except OSError:
            continue
        fsync_dir(jpath)
        swept.append(jpath)
        M.ec_repair_journal_total.inc(action="swept")
        log.info("swept stale repair journal %s", jpath)
    return swept


# ------------------------------------------------- leaf reconstruction


def leaf_verdict(
    path: str, shard_id: int, prot: BitrotProtection, on_block=None
) -> list[int] | None:
    """Leaf-granular verdict for one shard file: the list of leaf
    indices whose bytes mismatch the sidecar ([] = clean). None means
    the shard is NOT leaf-repairable — no leaf row in the sidecar, the
    file is missing/unreadable, or its size mismatches (truncation is
    not a patchable defect: the leaf offsets themselves are suspect)."""
    if not prot.has_leaves or shard_id >= len(prot.shard_leaf_crcs):
        return None
    lsize = prot.leaf_size
    crcs = prot.shard_leaf_crcs[shard_id]
    try:
        if os.path.getsize(path) != prot.shard_sizes[shard_id]:
            return None
        bad: list[int] = []
        with open(path, "rb") as f:
            for li, want in enumerate(crcs):
                chunk = f.read(lsize)
                if on_block is not None:
                    on_block(len(chunk))
                if crc32c(chunk) != want:
                    bad.append(li)
        return bad
    except OSError:
        return None


def patched_byte_ranges(
    prot: BitrotProtection, shard_id: int, leaves: list[int]
) -> list[tuple[int, int]]:
    """Byte ranges [(lo, hi), ...] covering the given leaves of one
    shard — the shape cache invalidation hooks consume."""
    return [
        (lo, hi)
        for lo, hi, _ in leaf_ranges(
            leaves, prot.leaf_size, prot.shard_sizes[shard_id]
        )
    ]


def leaf_ranges(
    leaves: list[int], leaf_size: int, shard_size: int
) -> list[tuple[int, int, list[int]]]:
    """Group leaf indices into contiguous byte ranges: [(lo, hi,
    [leaf, ...]), ...] with hi clamped to the shard tail."""
    out: list[tuple[int, int, list[int]]] = []
    run: list[int] = []
    for li in sorted(set(leaves)):
        if run and li != run[-1] + 1:
            lo = run[0] * leaf_size
            out.append((lo, min(run[-1] * leaf_size + leaf_size, shard_size), run))
            run = []
        run.append(li)
    if run:
        lo = run[0] * leaf_size
        out.append((lo, min(run[-1] * leaf_size + leaf_size, shard_size), run))
    return out


def reconstruct_leaves(
    prot: BitrotProtection,
    ctx: ECContext,
    shard_id: int,
    leaves: list[int],
    read_range,
    candidates: list[int],
    backend=None,
    span=None,
    on_bytes=None,
) -> list[LeafPatch]:
    """Rebuild ONLY the rotten leaves of `shard_id` from k verified
    sibling sources and return them as journal-ready patches.

    `read_range(sid, lo, size) -> bytes | None` supplies sibling bytes
    (local pread or a ranged peer fetch); every returned range is
    verified here against the sibling's own granule CRCs before it is
    fed to Reed-Solomon — a rotten sibling is skipped, never trusted.
    `candidates` orders the sibling ids to try. Fail-closed: fewer than
    k verified sources for any range, or reconstructed bytes that fail
    the target's own leaf CRCs, raise ECError with nothing returned.

    `on_bytes(n)` observes every sibling byte consumed (scrub's rate
    limiter / wire accounting).
    """
    import numpy as np

    if not prot.has_leaves:
        raise ECError("leaf reconstruction needs a v2 (leaf-CRC) sidecar")
    if backend is None:
        from .backend import get_backend

        backend = get_backend("cpu", ctx.data_shards, ctx.parity_shards)
    k = ctx.data_shards
    lsize = prot.leaf_size
    ssize = prot.shard_sizes[shard_id]
    target_crcs = prot.shard_leaf_crcs[shard_id]

    patches: list[LeafPatch] = []
    for lo, hi, range_leaves in leaf_ranges(leaves, lsize, ssize):
        size = hi - lo
        sources: dict[int, np.ndarray] = {}
        for sid in candidates:
            if len(sources) >= k:
                break
            if sid == shard_id:
                continue
            got = read_range(sid, lo, size)
            if got is None or len(got) != size:
                continue
            if on_bytes is not None:
                on_bytes(len(got))
            with trace.stage(span, "crc_verify"):
                if not prot.verify_range(sid, lo, got):
                    continue
            sources[sid] = np.frombuffer(got, dtype=np.uint8)
        if len(sources) < k:
            raise ECError(
                f"leaf repair of shard {shard_id} range [{lo}:{hi}): only "
                f"{len(sources)} verified sibling sources (need {k}); "
                f"refusing"
            )
        rec = backend.reconstruct(sources, want=[shard_id])
        out = np.asarray(rec[shard_id], dtype=np.uint8).tobytes()
        with trace.stage(span, "crc_verify"):
            for li in range_leaves:
                blk = out[li * lsize - lo : min((li + 1) * lsize, ssize) - lo]
                crc = crc32c(blk)
                if li >= len(target_crcs) or crc != target_crcs[li]:
                    raise ECError(
                        f"reconstructed leaf {li} of shard {shard_id} fails "
                        f".ecsum verification; refusing to patch"
                    )
                patches.append(
                    LeafPatch(leaf=li, offset=li * lsize, data=blk, crc=crc)
                )
    return patches
