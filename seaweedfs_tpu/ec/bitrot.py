"""Bitrot-protection sidecar: <base>.ecsum.

Per shard, a CRC32C per 16 MiB block, computed in the same pass that
writes the shard bytes. Self-checksummed header so a corrupt sidecar is
detected rather than trusted (reference ec_bitrot.go:15-58; this build
uses its own deterministic little-endian payload instead of protobuf).

The magic is deliberately NOT the reference's 'ECSU': the payload is a
different (non-protobuf) format, and a foreign reader that matched
magic+version but failed to unmarshal would classify the generation
BitrotInvalid (fail-closed, integrity alarms) instead of cleanly
treating the sidecar as unknown. A distinct magic makes foreign readers
reject it as "not my file" rather than "my file, corrupted".

File layout:
  [magic 'SWTS'(4, BE) | format_version=1 (u16 LE) | payload_len (u32 LE)
   | payload_crc32c (u32 LE)] [payload]

Payload (all LE):
  block_size u32 | generation u64 | data_shards u8 | parity_shards u8
  | uuid (16 raw bytes)
  | per shard (total times): shard_size u64 | crc_count u32 | crcs u32...
"""

from __future__ import annotations

import os
import struct
import uuid as uuid_mod
from dataclasses import dataclass, field

from ..utils.crc import crc32c
from .context import BITROT_BLOCK_SIZE, ECContext, ECError

MAGIC = 0x53575453  # "SWTS" — distinct from the reference's "ECSU"
# Sidecars written by pre-rename builds of THIS codebase carry "ECSU"
# around the same (non-protobuf) payload; keep reading them.
_LEGACY_MAGIC = 0x45435355  # "ECSU"
FORMAT_VERSION = 1
_HEADER = struct.Struct(">I")  # magic, big-endian like the reference
_HEADER_REST = struct.Struct("<HII")  # version, payload_len, payload_crc


class BitrotError(ECError):
    pass


class ShardChecksumBuilder:
    """Rolling per-block CRC32C accumulator for one shard's byte stream."""

    def __init__(self, block_size: int = BITROT_BLOCK_SIZE):
        self.block_size = block_size
        self.crcs: list[int] = []
        self._crc = 0
        self._filled = 0
        self.total = 0

    def write(self, data: bytes | memoryview) -> None:
        data = memoryview(data)
        self.total += len(data)
        while len(data) > 0:
            room = self.block_size - self._filled
            take = min(room, len(data))
            self._crc = crc32c(bytes(data[:take]), self._crc)
            self._filled += take
            data = data[take:]
            if self._filled == self.block_size:
                self.crcs.append(self._crc)
                self._crc = 0
                self._filled = 0

    def finish(self) -> list[int]:
        if self._filled > 0:
            self.crcs.append(self._crc)
            self._crc = 0
            self._filled = 0
        return self.crcs


@dataclass
class BitrotProtection:
    """Decoded .ecsum contents."""

    ctx: ECContext
    block_size: int = BITROT_BLOCK_SIZE
    generation: int = 0  # EncodeTsNs generation stamp
    uuid: bytes = b"\x00" * 16
    shard_sizes: list[int] = field(default_factory=list)
    shard_crcs: list[list[int]] = field(default_factory=list)

    @classmethod
    def from_builders(
        cls,
        ctx: ECContext,
        builders: list[ShardChecksumBuilder],
        generation: int = 0,
    ) -> "BitrotProtection":
        if len(builders) != ctx.total:
            raise BitrotError(f"expected {ctx.total} builders, got {len(builders)}")
        return cls(
            ctx=ctx,
            block_size=builders[0].block_size,
            generation=generation,
            uuid=uuid_mod.uuid4().bytes,
            shard_sizes=[b.total for b in builders],
            shard_crcs=[b.finish() for b in builders],
        )

    # ---- serialization ----

    def to_bytes(self) -> bytes:
        parts = [
            struct.pack(
                "<IQBB",
                self.block_size,
                self.generation,
                self.ctx.data_shards,
                self.ctx.parity_shards,
            ),
            self.uuid,
        ]
        for size, crcs in zip(self.shard_sizes, self.shard_crcs):
            parts.append(struct.pack("<QI", size, len(crcs)))
            parts.append(struct.pack(f"<{len(crcs)}I", *crcs))
        payload = b"".join(parts)
        header = _HEADER.pack(MAGIC) + _HEADER_REST.pack(
            FORMAT_VERSION, len(payload), crc32c(payload)
        )
        return header + payload

    @classmethod
    def from_bytes(cls, raw: bytes) -> "BitrotProtection":
        hs = _HEADER.size + _HEADER_REST.size
        if len(raw) < hs:
            raise BitrotError("sidecar too short")
        (magic,) = _HEADER.unpack(raw[: _HEADER.size])
        version, plen, pcrc = _HEADER_REST.unpack(raw[_HEADER.size : hs])
        if magic not in (MAGIC, _LEGACY_MAGIC):
            raise BitrotError(f"bad magic {magic:08x}")
        if version != FORMAT_VERSION:
            raise BitrotError(f"unsupported sidecar version {version}")
        payload = raw[hs : hs + plen]
        if len(payload) != plen:
            raise BitrotError("truncated payload")
        if crc32c(payload) != pcrc:
            raise BitrotError("payload checksum mismatch")
        try:
            block_size, generation, k, m = struct.unpack("<IQBB", payload[:14])
            uid = payload[14:30]
            ctx = ECContext(k, m)
            p = 30
            sizes, crcs = [], []
            for _ in range(ctx.total):
                size, count = struct.unpack("<QI", payload[p : p + 12])
                p += 12
                row = list(struct.unpack(f"<{count}I", payload[p : p + 4 * count]))
                p += 4 * count
                sizes.append(size)
                crcs.append(row)
            if p != plen:
                raise BitrotError("trailing bytes in payload")
        except struct.error as e:
            raise BitrotError(f"malformed payload: {e}") from None
        return cls(ctx, block_size, generation, uid, sizes, crcs)

    # ---- file io ----

    def save(self, path: str) -> None:
        from ..utils.fs import atomic_write

        atomic_write(path, self.to_bytes())

    @classmethod
    def load(cls, path: str) -> "BitrotProtection":
        with open(path, "rb") as f:
            return cls.from_bytes(f.read())

    # ---- verification ----

    def verify_shard_file(
        self,
        path: str,
        shard_id: int,
        on_block=None,
        stop_early: bool = False,
    ) -> list[int]:
        """-> list of mismatched block indices ([] = clean).

        A size mismatch counts as every expected block mismatching
        (truncation is corruption, reference fail-closed rule).
        `on_block(n_bytes)` is invoked per block read (rate-limiting
        hook for the scrubber); `stop_early` returns at the first
        mismatch when only a yes/no verdict is needed.
        """
        expected = self.shard_crcs[shard_id]
        if os.path.getsize(path) != self.shard_sizes[shard_id]:
            return list(range(max(len(expected), 1)))
        bad = []
        with open(path, "rb") as f:
            for i, want in enumerate(expected):
                block = f.read(self.block_size)
                if on_block is not None:
                    on_block(len(block))
                if crc32c(block) != want:
                    bad.append(i)
                    if stop_early:
                        break
        return bad
