"""Bitrot-protection sidecar: <base>.ecsum.

Per shard, a CRC32C per 16 MiB block, computed in the same pass that
writes the shard bytes. Self-checksummed header so a corrupt sidecar is
detected rather than trusted (reference ec_bitrot.go:15-58; this build
uses its own deterministic little-endian payload instead of protobuf).

The magic is deliberately NOT the reference's 'ECSU': the payload is a
different (non-protobuf) format, and a foreign reader that matched
magic+version but failed to unmarshal would classify the generation
BitrotInvalid (fail-closed, integrity alarms) instead of cleanly
treating the sidecar as unknown. A distinct magic makes foreign readers
reject it as "not my file" rather than "my file, corrupted".

File layout:
  [magic 'SWTS'(4, BE) | format_version (u16 LE) | payload_len (u32 LE)
   | payload_crc32c (u32 LE)] [payload]

Payload v1 (all LE):
  block_size u32 | generation u64 | data_shards u8 | parity_shards u8
  | uuid (16 raw bytes)
  | per shard (total times): shard_size u64 | crc_count u32 | crcs u32...

Payload v2 extends v1 with a sub-block CRC level — a CRC32C per
`leaf_size` leaf (64 KiB default) under the existing blocks — so the
degraded-read path verifies and reconstructs only the leaves covering a
requested extent instead of whole 16 MiB blocks:
  ... v1 fields ... | leaf_size u32
  | per shard (total times): leaf_count u32 | leaf_crcs u32...

v1 sidecars keep today's behavior (block-granular verification);
writers emit v1 whenever no leaf CRCs are present, so the format only
upgrades when the new data exists.
"""

from __future__ import annotations

import os
import struct
import uuid as uuid_mod
from dataclasses import dataclass, field

from ..utils.crc import crc32c, crc32c_combine
from .context import BITROT_BLOCK_SIZE, BITROT_LEAF_SIZE, ECContext, ECError

MAGIC = 0x53575453  # "SWTS" — distinct from the reference's "ECSU"
# Sidecars written by pre-rename builds of THIS codebase carry "ECSU"
# around the same (non-protobuf) payload; keep reading them.
_LEGACY_MAGIC = 0x45435355  # "ECSU"
FORMAT_VERSION = 1
FORMAT_VERSION_V2 = 2
_HEADER = struct.Struct(">I")  # magic, big-endian like the reference
_HEADER_REST = struct.Struct("<HII")  # version, payload_len, payload_crc


class BitrotError(ECError):
    pass


class ShardChecksumBuilder:
    """Rolling per-block CRC32C accumulator for one shard's byte stream.

    With `leaf_size` set, a second per-leaf CRC level is rolled in the
    same pass (the v2 sidecar's sub-block granularity). Leaves are
    independent CRCs (each starts from 0), blocks are rolled directly —
    both levels over the identical byte stream."""

    def __init__(
        self, block_size: int = BITROT_BLOCK_SIZE, leaf_size: int = 0
    ):
        if leaf_size and block_size % leaf_size != 0:
            raise BitrotError(
                f"leaf size {leaf_size} does not divide block size {block_size}"
            )
        self.block_size = block_size
        self.leaf_size = leaf_size
        self.crcs: list[int] = []
        self.leaf_crcs: list[int] = []
        self._crc = 0
        self._filled = 0
        self._leaf_crc = 0
        self._leaf_filled = 0
        self.total = 0

    def write(self, data: bytes | memoryview) -> None:
        data = memoryview(data)
        self.total += len(data)
        if self.leaf_size:
            d = data
            while len(d) > 0:
                take = min(self.leaf_size - self._leaf_filled, len(d))
                self._leaf_crc = crc32c(bytes(d[:take]), self._leaf_crc)
                self._leaf_filled += take
                d = d[take:]
                if self._leaf_filled == self.leaf_size:
                    self.leaf_crcs.append(self._leaf_crc)
                    self._leaf_crc = 0
                    self._leaf_filled = 0
        while len(data) > 0:
            room = self.block_size - self._filled
            take = min(room, len(data))
            self._crc = crc32c(bytes(data[:take]), self._crc)
            self._filled += take
            data = data[take:]
            if self._filled == self.block_size:
                self.crcs.append(self._crc)
                self._crc = 0
                self._filled = 0

    def finish(self) -> list[int]:
        if self._filled > 0:
            self.crcs.append(self._crc)
            self._crc = 0
            self._filled = 0
        if self._leaf_filled > 0:
            self.leaf_crcs.append(self._leaf_crc)
            self._leaf_crc = 0
            self._leaf_filled = 0
        return self.crcs

    def finish_leaves(self) -> list[int]:
        self.finish()
        return self.leaf_crcs


def fold_leaf_crcs(
    leaf_crcs: list[int], total: int, leaf_size: int, block_size: int
) -> list[int]:
    """Derive block-level CRCs from independent per-leaf CRCs via
    crc32c_combine — no byte re-reads. The inverse consistency property
    (folded == directly-rolled block CRCs) is what lets the fused
    native sink run at leaf granularity and still emit the v1-compatible
    block level."""
    if leaf_size <= 0 or block_size % leaf_size != 0:
        raise BitrotError(
            f"leaf size {leaf_size} does not divide block size {block_size}"
        )
    per_block = block_size // leaf_size
    out: list[int] = []
    remaining = total
    for bi in range(0, len(leaf_crcs), per_block):
        crc = 0
        for li, leaf in enumerate(leaf_crcs[bi : bi + per_block]):
            nbytes = min(leaf_size, remaining - li * leaf_size)
            crc = crc32c_combine(crc, leaf, nbytes)
        out.append(crc)
        remaining -= min(block_size, remaining)
    return out


@dataclass
class BitrotProtection:
    """Decoded .ecsum contents. `leaf_size`/`shard_leaf_crcs` are the
    v2 sub-block level; empty on v1 sidecars (block granularity only)."""

    ctx: ECContext
    block_size: int = BITROT_BLOCK_SIZE
    generation: int = 0  # EncodeTsNs generation stamp
    uuid: bytes = b"\x00" * 16
    shard_sizes: list[int] = field(default_factory=list)
    shard_crcs: list[list[int]] = field(default_factory=list)
    leaf_size: int = 0
    shard_leaf_crcs: list[list[int]] = field(default_factory=list)

    @property
    def has_leaves(self) -> bool:
        return self.leaf_size > 0 and bool(self.shard_leaf_crcs)

    def verify_granularity(self, shard_id: int) -> tuple[int, list[int]]:
        """(granule_bytes, crc_row) for extent verification: the finest
        level this sidecar records for `shard_id`. An out-of-range id
        gets an empty row (verification of it can only fail), never an
        IndexError — callers probe sibling ids freely."""
        if self.has_leaves and shard_id < len(self.shard_leaf_crcs):
            return self.leaf_size, self.shard_leaf_crcs[shard_id]
        if shard_id < len(self.shard_crcs):
            return self.block_size, self.shard_crcs[shard_id]
        return self.block_size, []

    @classmethod
    def from_builders(
        cls,
        ctx: ECContext,
        builders: list[ShardChecksumBuilder],
        generation: int = 0,
    ) -> "BitrotProtection":
        if len(builders) != ctx.total:
            raise BitrotError(f"expected {ctx.total} builders, got {len(builders)}")
        leaf_size = builders[0].leaf_size
        return cls(
            ctx=ctx,
            block_size=builders[0].block_size,
            generation=generation,
            uuid=uuid_mod.uuid4().bytes,
            shard_sizes=[b.total for b in builders],
            shard_crcs=[b.finish() for b in builders],
            leaf_size=leaf_size,
            shard_leaf_crcs=(
                [b.finish_leaves() for b in builders] if leaf_size else []
            ),
        )

    # ---- serialization ----

    def to_bytes(self) -> bytes:
        parts = [
            struct.pack(
                "<IQBB",
                self.block_size,
                self.generation,
                self.ctx.data_shards,
                self.ctx.parity_shards,
            ),
            self.uuid,
        ]
        for size, crcs in zip(self.shard_sizes, self.shard_crcs):
            parts.append(struct.pack("<QI", size, len(crcs)))
            parts.append(struct.pack(f"<{len(crcs)}I", *crcs))
        version = FORMAT_VERSION
        if self.has_leaves:
            # v2 tail: leaf level appended after the v1 body, so the v1
            # parse of a v2 payload is exactly the v1 payload prefix.
            version = FORMAT_VERSION_V2
            parts.append(struct.pack("<I", self.leaf_size))
            for crcs in self.shard_leaf_crcs:
                parts.append(struct.pack("<I", len(crcs)))
                parts.append(struct.pack(f"<{len(crcs)}I", *crcs))
        payload = b"".join(parts)
        header = _HEADER.pack(MAGIC) + _HEADER_REST.pack(
            version, len(payload), crc32c(payload)
        )
        return header + payload

    @classmethod
    def from_bytes(cls, raw: bytes) -> "BitrotProtection":
        hs = _HEADER.size + _HEADER_REST.size
        if len(raw) < hs:
            raise BitrotError("sidecar too short")
        (magic,) = _HEADER.unpack(raw[: _HEADER.size])
        version, plen, pcrc = _HEADER_REST.unpack(raw[_HEADER.size : hs])
        if magic not in (MAGIC, _LEGACY_MAGIC):
            raise BitrotError(f"bad magic {magic:08x}")
        if version not in (FORMAT_VERSION, FORMAT_VERSION_V2):
            raise BitrotError(f"unsupported sidecar version {version}")
        payload = raw[hs : hs + plen]
        if len(payload) != plen:
            raise BitrotError("truncated payload")
        if crc32c(payload) != pcrc:
            raise BitrotError("payload checksum mismatch")
        try:
            block_size, generation, k, m = struct.unpack("<IQBB", payload[:14])
            uid = payload[14:30]
            ctx = ECContext(k, m)
            p = 30
            sizes, crcs = [], []
            for _ in range(ctx.total):
                size, count = struct.unpack("<QI", payload[p : p + 12])
                p += 12
                row = list(struct.unpack(f"<{count}I", payload[p : p + 4 * count]))
                p += 4 * count
                sizes.append(size)
                crcs.append(row)
            leaf_size = 0
            leaf_crcs: list[list[int]] = []
            if version >= FORMAT_VERSION_V2:
                (leaf_size,) = struct.unpack("<I", payload[p : p + 4])
                p += 4
                if leaf_size <= 0 or block_size % leaf_size != 0:
                    raise BitrotError(
                        f"v2 leaf size {leaf_size} does not divide block "
                        f"size {block_size}"
                    )
                for _ in range(ctx.total):
                    (count,) = struct.unpack("<I", payload[p : p + 4])
                    p += 4
                    row = list(
                        struct.unpack(f"<{count}I", payload[p : p + 4 * count])
                    )
                    p += 4 * count
                    leaf_crcs.append(row)
            if p != plen:
                raise BitrotError("trailing bytes in payload")
        except struct.error as e:
            raise BitrotError(f"malformed payload: {e}") from None
        return cls(
            ctx, block_size, generation, uid, sizes, crcs, leaf_size, leaf_crcs
        )

    def verify_range(self, shard_id: int, lo: int, data: bytes) -> bool:
        """Verify `data` as the bytes of shard `shard_id` at [lo,
        lo+len(data)) against the finest granule CRCs the sidecar
        records. `lo` must be granule-aligned; the final granule may be
        the shard's partial tail. The ONE range-vs-granule check shared
        by degraded reads, leaf reconstruction, and ranged peer fetch —
        offset/tail arithmetic lives here exactly once."""
        gsize, crcs = self.verify_granularity(shard_id)
        hi = lo + len(data)
        for gi in range(lo // gsize, -(-hi // gsize)):
            blk = data[gi * gsize - lo : min((gi + 1) * gsize, hi) - lo]
            if gi >= len(crcs) or crc32c(blk) != crcs[gi]:
                return False
        return True

    # ---- file io ----

    def save(self, path: str) -> None:
        from ..utils.fs import atomic_write

        atomic_write(path, self.to_bytes())

    @classmethod
    def load(cls, path: str) -> "BitrotProtection":
        with open(path, "rb") as f:
            return cls.from_bytes(f.read())

    # ---- verification ----

    def verify_shard_file(
        self,
        path: str,
        shard_id: int,
        on_block=None,
        stop_early: bool = False,
    ) -> list[int]:
        """-> list of mismatched block indices ([] = clean).

        A size mismatch counts as every expected block mismatching
        (truncation is corruption, reference fail-closed rule).
        `on_block(n_bytes)` is invoked per block read (rate-limiting
        hook for the scrubber); `stop_early` returns at the first
        mismatch when only a yes/no verdict is needed.
        """
        expected = self.shard_crcs[shard_id]
        if os.path.getsize(path) != self.shard_sizes[shard_id]:
            return list(range(max(len(expected), 1)))
        bad = []
        with open(path, "rb") as f:
            for i, want in enumerate(expected):
                block = f.read(self.block_size)
                if on_block is not None:
                    on_block(len(block))
                if crc32c(block) != want:
                    bad.append(i)
                    if stop_early:
                        break
        return bad
