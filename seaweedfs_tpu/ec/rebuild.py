"""Rebuild missing EC shards from surviving ones.

Reference: weed/storage/erasure_coding/ec_encoder.go generateMissingEcFiles
(:147-379). The correctness envelope preserved here (the reference's
accumulated bug-fix scar tissue, SURVEY.md hard part (c)):

- bitrot sidecar verify-and-exclude: present-but-corrupt shards are
  reclassified as missing and regenerated, never fed to Reed-Solomon;
- fail-closed rules: malformed sidecar refuses; >parity mismatches means
  the *sidecar* is suspect (wholesale-mismatch guard) and refuses;
  fewer than k verified-good shards refuses;
- regenerated shards are verified against the sidecar before publish;
- temp file + fsync + atomic rename (+ dir fsync) publication; corrupt
  originals replaced in place only after their replacement verifies.
"""

from __future__ import annotations

import os

import numpy as np

from .. import faults
from .backend import RSBackend, get_backend
from .bitrot import BitrotError, BitrotProtection, ShardChecksumBuilder
from .context import DEFAULT_EC_CONTEXT, ECContext, ECError
from .decoder import _fsync_dir
from .encoder import DEFAULT_BATCH
from .volume_info import VolumeInfo


def rebuild_ec_files(
    base: str,
    ctx: ECContext | None = None,
    backend: RSBackend | None = None,
    unsafe_ignore_sidecar: bool = False,
    batch_size: int = DEFAULT_BATCH,
    only_shards: list[int] | None = None,
) -> list[int]:
    """Regenerate missing/corrupt shard files; returns regenerated ids.

    `only_shards` restricts which ABSENT shards are regenerated (a
    subset-holding server must not mint local copies of shards placed on
    peers); present-but-corrupt shards are always replaced regardless.
    """
    # Sidecar first: it records the shard ratio too, which backs up the
    # .vif for config resolution and cross-checks it.
    prot: BitrotProtection | None = None
    ecsum = base + ".ecsum"
    if os.path.exists(ecsum):
        try:
            prot = BitrotProtection.load(ecsum)
        except BitrotError as e:
            if not unsafe_ignore_sidecar:
                raise ECError(
                    f"bitrot sidecar for {base} is malformed ({e}); refusing "
                    f"to rebuild (pass unsafe_ignore_sidecar to override)"
                ) from e
            prot = None

    if ctx is None:
        vif_path = base + ".vif"
        if os.path.exists(vif_path):
            # .vif present but unreadable fails closed: silently falling
            # back to 10+4 would rebuild a custom-ratio volume with the
            # wrong layout (reference RebuildEcFiles).
            vi = VolumeInfo.load(vif_path)
            ctx = vi.ec_ctx
        if ctx is None and prot is not None:
            ctx = prot.ctx
        if ctx is None:
            ctx = DEFAULT_EC_CONTEXT
    if prot is not None and prot.ctx != ctx:
        if not unsafe_ignore_sidecar:
            raise ECError(
                f"bitrot sidecar for {base} records ratio {prot.ctx} but the "
                f"volume config says {ctx}; refusing to rebuild"
            )
        prot = None
    if backend is None:
        backend = get_backend("auto", ctx.data_shards, ctx.parity_shards)

    total, k = ctx.total, ctx.data_shards
    present = [i for i in range(total) if os.path.exists(base + ctx.to_ext(i))]
    missing = [i for i in range(total) if i not in present]
    if only_shards is not None:
        missing = [i for i in missing if i in only_shards]

    # --- bitrot verify-and-exclude ---------------------------------------
    corrupt: list[int] = []
    if prot is not None:
        for i in present:
            try:
                bad = prot.verify_shard_file(base + ctx.to_ext(i), i)
            except OSError:
                bad = [0]  # unreadable = untrustworthy RS input
            if bad:
                corrupt.append(i)
        if corrupt and not unsafe_ignore_sidecar:
            if len(corrupt) > ctx.parity_shards:
                raise ECError(
                    f"bitrot sidecar suspect for {base}: {len(corrupt)}/"
                    f"{len(present)} present shards mismatch (> parity "
                    f"{ctx.parity_shards}); refusing to rebuild"
                )
            if len(present) - len(corrupt) < k:
                raise ECError(
                    f"bitrot: only {len(present) - len(corrupt)} verified-good "
                    f"shards for {base}, need {k} data shards"
                )
            for i in corrupt:
                present.remove(i)
                missing.append(i)

    if len(present) < k:
        raise ECError(
            f"not enough shards to rebuild {base}: found {len(present)}, "
            f"need {k}, missing {sorted(missing)}"
        )
    if not missing:
        return []

    # --- reconstruct in batches ------------------------------------------
    sizes = {i: os.path.getsize(base + ctx.to_ext(i)) for i in present}
    shard_size = max(sizes.values())
    short = [i for i, s in sizes.items() if s != shard_size]
    if short:
        raise ECError(f"present shards have unequal sizes: {sizes}")

    src = sorted(present)[:k]
    fds = {i: os.open(base + ctx.to_ext(i), os.O_RDONLY) for i in src}
    tmp_paths = {i: base + ctx.to_ext(i) + ".rebuilding" for i in missing}
    outs = {i: open(p, "wb") for i, p in tmp_paths.items()}
    crc_block = prot.block_size if prot is not None else None
    builders = {
        i: ShardChecksumBuilder(crc_block) if crc_block else ShardChecksumBuilder()
        for i in missing
    }
    try:
        for off in range(0, shard_size, batch_size):
            width = min(batch_size, shard_size - off)
            block = {
                i: np.frombuffer(
                    faults.mutate(
                        "ec.rebuild.read_shard",
                        os.pread(fds[i], width, off),
                        base=base, shard=i, offset=off,
                    ),
                    dtype=np.uint8,
                )
                for i in src
            }
            if any(len(b) != width for b in block.values()):
                raise ECError(f"short shard read at offset {off}")
            rec = backend.reconstruct(block, want=missing)
            for i in missing:
                b = faults.mutate(
                    "ec.rebuild.shard_bytes",
                    np.asarray(rec[i], dtype=np.uint8).tobytes(),
                    base=base, shard=i, offset=off,
                )
                outs[i].write(b)
                builders[i].write(b)
        # Crash window: temp .rebuilding files written, not yet durable.
        faults.fire("ec.rebuild.before_fsync", base=base)
        for f in outs.values():
            f.flush()
            os.fsync(f.fileno())
    except BaseException:
        for f in outs.values():
            f.close()
        for p in tmp_paths.values():
            if os.path.exists(p):
                os.unlink(p)
        raise
    finally:
        for fd in fds.values():
            os.close(fd)

    for f in outs.values():
        f.close()

    # --- verify regenerated shards against the sidecar (fail closed) -----
    if prot is not None:
        for i in missing:
            if (
                builders[i].total != prot.shard_sizes[i]
                or builders[i].finish() != prot.shard_crcs[i]
            ):
                for p in tmp_paths.values():
                    if os.path.exists(p):
                        os.unlink(p)
                raise ECError(
                    f"regenerated shard {i} for {base} fails sidecar "
                    f"verification; refusing to publish"
                )

    # Crash window: temps durable + sidecar-verified, renames pending. A
    # crash here (or between renames) leaves a mix of published shards
    # and .rebuilding temps; a restarted rebuild regenerates the rest.
    faults.fire("ec.rebuild.before_rename", base=base)
    for i in missing:
        os.replace(tmp_paths[i], base + ctx.to_ext(i))
        faults.fire("ec.rebuild.after_rename", base=base, shard=i)
    _fsync_dir(base + ".dat")
    return sorted(missing)
